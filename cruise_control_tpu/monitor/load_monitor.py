"""LoadMonitor: sampling orchestration + cluster-model generation.

Parity with the reference's ``LoadMonitor`` (monitor/LoadMonitor.java:78):
owns the partition/broker aggregators, the metadata client, the capacity
resolver and the sample store; fetches samples (optionally via multiple
fetcher assignments — MetricFetcherManager.java:37); answers completeness
queries; and builds the ``TensorClusterModel`` on demand
(``clusterModel(from,to,requirements)`` — LoadMonitor.java:455-520).

Model generation is the object-graph → struct-of-arrays seam: topics,
partitions and brokers are densified to integer ids, aggregated window
values become the replica leader/follower load rows, and
``model.build_model`` pads + places the tensors.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.common.tracing import TRACE
from cruise_control_tpu.model.cpu_model import (DEFAULT_CPU_WEIGHT_OF_FOLLOWER,
                                                follower_cpu_util_from_leader_load)
from cruise_control_tpu.model.tensor_model import BrokerState, TensorClusterModel, build_model
from cruise_control_tpu.monitor.aggregator import AggregationResult, MetricSampleAggregator
from cruise_control_tpu.monitor.capacity import BrokerCapacityResolver, StaticCapacityResolver
from cruise_control_tpu.monitor.metadata import ClusterMetadata, MetadataClient
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF, RESOURCE_TO_METRIC_ID
from cruise_control_tpu.monitor.sampling import (MetricSampler, NoopSampleStore,
                                                 SampleStore, Samples, SamplingMode)


@dataclasses.dataclass(frozen=True)
class ModelCompletenessRequirements:
    """monitor/ModelCompletenessRequirements.java: gates model generation."""

    min_required_num_windows: int = 1
    min_monitored_partitions_percentage: float = 0.0
    include_all_topics: bool = False

    def combine(self, other: "ModelCompletenessRequirements") -> "ModelCompletenessRequirements":
        return ModelCompletenessRequirements(
            min_required_num_windows=max(self.min_required_num_windows,
                                         other.min_required_num_windows),
            min_monitored_partitions_percentage=max(
                self.min_monitored_partitions_percentage,
                other.min_monitored_partitions_percentage),
            include_all_topics=self.include_all_topics or other.include_all_topics)


class NotEnoughValidWindowsError(Exception):
    """monitor: NotEnoughValidWindowsException analogue."""


class LoadMonitorState(enum.Enum):
    """LoadMonitorTaskRunner states (monitor/task/LoadMonitorTaskRunner.java:57)."""

    NOT_STARTED = "not_started"
    RUNNING = "running"
    PAUSED = "paused"
    SAMPLING = "sampling"
    BOOTSTRAPPING = "bootstrapping"
    TRAINING = "training"
    LOADING = "loading"


@dataclasses.dataclass
class ModelGeneration:
    """(metadata generation, aggregator generation) — staleness detection
    (monitor/ModelGeneration.java)."""

    cluster_generation: int
    load_generation: int

    def as_tuple(self) -> Tuple[int, int]:
        return (self.cluster_generation, self.load_generation)


class LoadMonitor:
    def __init__(self,
                 metadata_client: MetadataClient,
                 capacity_resolver: Optional[BrokerCapacityResolver] = None,
                 sample_store: Optional[SampleStore] = None,
                 num_partition_windows: int = 5,
                 partition_window_ms: int = 300_000,
                 num_broker_windows: int = 20,
                 broker_window_ms: int = 300_000,
                 min_samples_per_window: int = 1,
                 max_allowed_extrapolations: int = 5,
                 min_samples_per_broker_window: Optional[int] = None,
                 max_allowed_broker_extrapolations: Optional[int] = None,
                 follower_cpu_ratio: float = DEFAULT_CPU_WEIGHT_OF_FOLLOWER,
                 on_execution_store: Optional[SampleStore] = None):
        self._metadata = metadata_client
        self._capacity = capacity_resolver or StaticCapacityResolver()
        self._store = sample_store or NoopSampleStore()
        self._follower_cpu_ratio = follower_cpu_ratio
        self.partition_aggregator = MetricSampleAggregator(
            num_partition_windows, partition_window_ms, min_samples_per_window,
            max_allowed_extrapolations)
        # The broker aggregator has its own validity knobs
        # (min.samples.per.broker.metrics.window /
        # max.allowed.extrapolations.per.broker, MonitorConfig).
        self.broker_aggregator = MetricSampleAggregator(
            num_broker_windows, broker_window_ms,
            (min_samples_per_broker_window
             if min_samples_per_broker_window is not None
             else min_samples_per_window),
            (max_allowed_broker_extrapolations
             if max_allowed_broker_extrapolations is not None
             else max_allowed_extrapolations))
        self._lock = threading.RLock()
        self._state = LoadMonitorState.NOT_STARTED
        self._sampling_paused = False
        self._pause_reason: Optional[str] = None
        # Execution-time segregation (adjustSamplingModeBeforeExecution,
        # Executor.java:1051-1067 + KafkaPartitionMetricSampleOnExecutionStore):
        # while the executor runs, partition samples are rebalance-biased —
        # they are diverted to this store instead of the aggregator/main
        # store; broker samples keep flowing (the ConcurrencyAdjuster needs
        # live health).
        self._execution_mode = False
        self._on_execution_store = on_execution_store
        # Model-generation semaphore (LoadMonitor.java:92,165): bounds
        # concurrent model builds.
        self._model_semaphore = threading.Semaphore(2)
        self._monitored_pct_cache: Optional[Tuple[Tuple[int, int], float]] = None
        # Sensor registrations (LoadMonitor.java:180-195; Sensors.md:
        # valid-windows, monitored-partitions-percentage,
        # total-monitored-windows, cluster-model-creation-timer).
        from cruise_control_tpu.common.sensors import SENSORS
        SENSORS.gauge("LoadMonitor.valid-windows",
                      lambda: self.partition_aggregator.valid_windows(),
                      help="Metric windows complete enough to model from")
        SENSORS.gauge("LoadMonitor.monitored-partitions-percentage",
                      self.monitored_partitions_percentage,
                      help="Fraction of partitions with valid metric samples")
        SENSORS.gauge("LoadMonitor.total-monitored-windows",
                      lambda: self.partition_aggregator.num_windows,
                      help="Metric windows currently retained")
        self._model_timer = SENSORS.timer(
            "LoadMonitor.cluster-model-creation-timer",
            help="Wall time to build a cluster model from the aggregator")

    # -- lifecycle / state -------------------------------------------------
    def start_up(self, skip_loading_samples: bool = False) -> None:
        """Replay persisted samples to warm the windows
        (LoadMonitor.startUp → KafkaSampleStore.loadSamples)."""
        with self._lock:
            if not skip_loading_samples:
                self._state = LoadMonitorState.LOADING
                self._ingest(self._store.load_samples(), persist=False)
            self._state = LoadMonitorState.RUNNING

    def state(self) -> LoadMonitorState:
        with self._lock:
            if self._sampling_paused:
                return LoadMonitorState.PAUSED
            return self._state

    def pause_sampling(self, reason: str = "") -> None:
        with self._lock:
            self._sampling_paused = True
            self._pause_reason = reason or None

    def resume_sampling(self) -> None:
        with self._lock:
            self._sampling_paused = False
            self._pause_reason = None

    def set_execution_mode(self, active: bool, reason: str = "") -> None:
        """Executor hook: switch sampling to ONGOING_EXECUTION instead of a
        full pause — broker metrics continue (live health for the
        ConcurrencyAdjuster), partition metrics divert to the segregated
        on-execution store.  An operator pause's reason is never clobbered
        (the execution only annotates the reason while nothing else owns it)."""
        with self._lock:
            self._execution_mode = active
            if not self._sampling_paused:
                self._pause_reason = ((reason or "ongoing execution")
                                      if active else None)

    @property
    def pause_reason(self) -> Optional[str]:
        return self._pause_reason

    def model_generation(self) -> ModelGeneration:
        return ModelGeneration(self._metadata.cluster().generation,
                               self.partition_aggregator.generation)

    def generation_changed(self, since) -> bool:
        """Has the model generation advanced past ``since`` (an
        ``as_tuple()`` value; None = no baseline → always True)?  The
        cruise loop's cheap poll predicate — no model build, just two
        counter reads."""
        return since is None or self.model_generation().as_tuple() != tuple(since)

    # -- sampling ----------------------------------------------------------
    def fetch_once(self, sampler: MetricSampler, start_ms: int, end_ms: int,
                   mode: SamplingMode = SamplingMode.ALL) -> int:
        """One sampling pass over all partitions (SamplingTask →
        MetricFetcherManager.fetchMetricSamples).  Returns #samples added."""
        with self._lock:
            if self._sampling_paused:
                return 0
            effective = mode
            if self._execution_mode and mode == SamplingMode.ALL:
                effective = SamplingMode.ONGOING_EXECUTION
        with TRACE.span("monitor.fetch", mode=effective.name) as sp:
            cluster = self._metadata.cluster()
            tps = [p.tp for p in cluster.partitions]
            samples = sampler.get_samples(cluster, tps, start_ms, end_ms,
                                          effective)
            if effective == SamplingMode.ONGOING_EXECUTION:
                n = self._ingest_on_execution(samples)
            else:
                n = self._ingest(samples, persist=True)
            sp.annotate(samples=n)
            return n

    def _ingest_on_execution(self, samples: Samples) -> int:
        """Broker samples flow normally (aggregated AND persisted, so
        broker-window history has no restart gap across a long execution);
        partition samples (biased by the rebalance traffic itself) go only
        to the segregated store."""
        n = self.broker_aggregator.add_samples(
            [(bs.entity, bs.time_ms, bs.metrics) for bs in samples.broker_samples])
        if samples.broker_samples and n:
            self._store.store_samples(Samples(
                partition_samples=[], broker_samples=samples.broker_samples))
        if samples.partition_samples and self._on_execution_store is not None:
            self._on_execution_store.store_samples(Samples(
                partition_samples=samples.partition_samples,
                broker_samples=[]))
        return n

    def bootstrap(self, sampler: MetricSampler, start_ms: int, end_ms: int,
                  step_ms: Optional[int] = None) -> int:
        """Replay a historical range window by window (BootstrapTask)."""
        with self._lock:
            self._state = LoadMonitorState.BOOTSTRAPPING
        step = step_ms or self.partition_aggregator.window_ms
        total = 0
        t = start_ms
        while t < end_ms:
            total += self.fetch_once(sampler, t, min(t + step, end_ms))
            t += step
        with self._lock:
            self._state = LoadMonitorState.RUNNING
        return total

    def _ingest(self, samples: Samples, persist: bool) -> int:
        n = self.partition_aggregator.add_samples(
            [(ps.entity, ps.time_ms, ps.metrics) for ps in samples.partition_samples])
        n += self.broker_aggregator.add_samples(
            [(bs.entity, bs.time_ms, bs.metrics) for bs in samples.broker_samples])
        if persist and n:
            self._store.store_samples(samples)
        return n

    def broker_history(self):
        """The (broker × window × metric) history tensor the device detector
        scores per tick — the broker aggregator's ``AggregationResult``
        (``values`` f32[E, W, M] plus the ``window_valid`` mask and
        ``generation`` stamp the scorer's dispatch cache keys on)."""
        return self.broker_aggregator.aggregate()

    def broker_health_metrics(self) -> Dict[int, Dict[str, float]]:
        """{broker → {metric name → latest collapsed value}} for the
        executor's ConcurrencyAdjuster (Executor.java:335-447 reads live
        request-queue depth / handler idle ratio from the broker metric
        history)."""
        agg = self.broker_aggregator.aggregate()
        out: Dict[int, Dict[str, float]] = {}
        names = [KAFKA_METRIC_DEF.metric_info_by_id(m).name
                 for m in range(agg.collapsed.shape[1])]
        for row, broker_id in enumerate(agg.entities):
            if not agg.entity_valid[row]:
                continue
            out[int(broker_id)] = {
                name: float(agg.collapsed[row, m])
                for m, name in enumerate(names)}
        return out

    # -- completeness ------------------------------------------------------
    def monitored_partitions_percentage(self) -> float:
        # Generation-cached: this is a sensor read on the /state and
        # /metrics hot paths, and a full window aggregation per scrape is a
        # heavyweight recomputation at the 1M-replica scale.
        gen = (self._metadata.cluster().generation,
               self.partition_aggregator.generation)
        cached = self._monitored_pct_cache
        if cached is not None and cached[0] == gen:
            return cached[1]
        agg = self.partition_aggregator.aggregate()
        total = self._metadata.cluster().partition_count()
        pct = float(agg.entity_valid.sum()) / total if total else 0.0
        self._monitored_pct_cache = (gen, pct)
        return pct

    def meets_completeness_requirements(self, req: ModelCompletenessRequirements) -> bool:
        if self.partition_aggregator.valid_windows() < req.min_required_num_windows:
            return False
        return self.monitored_partitions_percentage() >= \
            req.min_monitored_partitions_percentage

    # -- model generation --------------------------------------------------
    def cluster_model(self,
                      requirements: Optional[ModelCompletenessRequirements] = None,
                      allow_capacity_estimation: bool = True,
                      pad_replicas_to: Optional[int] = None) -> TensorClusterModel:
        """Build the tensor cluster model from aggregated partition metrics +
        metadata + capacities (LoadMonitor.clusterModel, LoadMonitor.java:455)."""
        return self.cluster_model_and_naming(requirements, allow_capacity_estimation,
                                             pad_replicas_to)[0]

    def cluster_model_and_naming(
            self, requirements: Optional[ModelCompletenessRequirements] = None,
            allow_capacity_estimation: bool = True,
            pad_replicas_to: Optional[int] = None
    ) -> Tuple[TensorClusterModel, Dict[str, object]]:
        """Model + the dense-id↔name maps derived from the SAME metadata
        snapshot.  Callers that later translate dense indices back to cluster
        ids (proposal renumbering, executor requests) must use this naming,
        not a fresh ``naming()`` read — membership can change mid-operation
        and would silently misaddress every proposal."""
        req = requirements or ModelCompletenessRequirements()
        with self._model_semaphore, self._model_timer.time(), \
                TRACE.span("monitor.cluster_model") as sp:
            cluster = self._metadata.cluster()
            sp.annotate(brokers=len(cluster.brokers),
                        partitions=cluster.partition_count())
            if self.partition_aggregator.valid_windows() < req.min_required_num_windows:
                raise NotEnoughValidWindowsError(
                    f"have {self.partition_aggregator.valid_windows()} valid windows, "
                    f"need {req.min_required_num_windows}")
            agg = self.partition_aggregator.aggregate()
            pct = 0.0
            total = cluster.partition_count()
            if total:
                pct = float(agg.entity_valid.sum()) / total
            if pct < req.min_monitored_partitions_percentage:
                raise NotEnoughValidWindowsError(
                    f"monitored partition percentage {pct:.3f} below "
                    f"{req.min_monitored_partitions_percentage:.3f}")
            model = self._build_model(cluster, agg, allow_capacity_estimation,
                                      pad_replicas_to)
            return model, self.naming_for(cluster)

    def _build_model(self, cluster: ClusterMetadata, agg: AggregationResult,
                     allow_capacity_estimation: bool,
                     pad_replicas_to: Optional[int]) -> TensorClusterModel:
        # Row map from the aggregation snapshot itself (not the live aggregator),
        # so concurrently registered entities cannot index past the arrays.
        entity_rows = {e: i for i, e in enumerate(agg.entities)}

        topics = cluster.topics()
        topic_id = {t: i for i, t in enumerate(topics)}
        broker_ids = sorted(cluster.broker_ids())
        broker_idx = {b: i for i, b in enumerate(broker_ids)}
        racks: Dict[str, int] = {}
        brokers_by_id = {b.broker_id: b for b in cluster.brokers}
        for b in cluster.brokers:
            racks.setdefault(b.rack, len(racks))
        hosts: Dict[str, int] = {}
        for b in cluster.brokers:
            hosts.setdefault(b.host or f"host-{b.broker_id}", len(hosts))

        # Partition table ordered (topic, partition).
        parts = sorted(cluster.partitions, key=lambda p: (topic_id[p.topic], p.partition))
        part_gid = {p.tp: i for i, p in enumerate(parts)}

        rb, rp, rt, rl, roff = [], [], [], [], []
        load_lead, load_foll = [], []
        cpu_id = RESOURCE_TO_METRIC_ID[Resource.CPU]
        nwi_id = RESOURCE_TO_METRIC_ID[Resource.NW_IN]
        nwo_id = RESOURCE_TO_METRIC_ID[Resource.NW_OUT]
        dsk_id = RESOURCE_TO_METRIC_ID[Resource.DISK]
        for p in parts:
            row = entity_rows.get(p.tp)
            if row is not None and agg.entity_valid[row]:
                vals = agg.collapsed[row]
                cpu, nwi = float(vals[cpu_id]), float(vals[nwi_id])
                nwo, dsk = float(vals[nwo_id]), float(vals[dsk_id])
            else:
                cpu = nwi = nwo = dsk = 0.0
            f_cpu = follower_cpu_util_from_leader_load(nwi, nwo, cpu,
                                                       self._follower_cpu_ratio)
            lead_row = np.array([cpu, nwi, nwo, dsk], np.float32)
            foll_row = np.array([f_cpu, nwi, 0.0, dsk], np.float32)
            gid = part_gid[p.tp]
            for b in p.replicas:
                rb.append(broker_idx[b])
                rp.append(gid)
                rt.append(topic_id[p.topic])
                rl.append(b == p.leader)
                roff.append(b in p.offline_replicas)
                load_lead.append(lead_row)
                load_foll.append(foll_row)

        bcap = np.zeros((len(broker_ids), NUM_RESOURCES), np.float32)
        brack = np.zeros(len(broker_ids), np.int32)
        bhost = np.zeros(len(broker_ids), np.int32)
        bstate = np.zeros(len(broker_ids), np.int8)
        for b_id, i in broker_idx.items():
            info = brokers_by_id[b_id]
            cap = self._capacity.capacity_for_broker(
                info.rack, info.host, b_id, allow_capacity_estimation)
            bcap[i] = cap.as_row()
            brack[i] = racks[info.rack]
            bhost[i] = hosts[info.host or f"host-{b_id}"]
            bstate[i] = BrokerState.ALIVE if info.is_alive else BrokerState.DEAD

        model = build_model(
            replica_broker=np.asarray(rb, np.int32),
            replica_partition=np.asarray(rp, np.int32),
            replica_topic=np.asarray(rt, np.int32),
            replica_is_leader=np.asarray(rl, bool),
            replica_load_leader=np.stack(load_lead) if load_lead else
            np.zeros((0, NUM_RESOURCES), np.float32),
            replica_load_follower=np.stack(load_foll) if load_foll else
            np.zeros((0, NUM_RESOURCES), np.float32),
            broker_capacity=bcap,
            broker_rack=brack,
            broker_host=bhost,
            broker_state=bstate,
            partition_topic=np.asarray([topic_id[p.topic] for p in parts], np.int32),
            pad_replicas_to=pad_replicas_to,
        )
        # Offline markers from metadata (offline logdir replicas).
        if any(roff):
            import jax.numpy as jnp
            off = np.zeros(model.num_replicas_padded, bool)
            off[: len(roff)] = roff
            model = model.replace(replica_offline=jnp.asarray(off))
        return model

    # -- naming maps for the API layer ------------------------------------
    def naming(self) -> Dict[str, object]:
        """Dense-id ↔ name maps from the CURRENT metadata snapshot.  For
        translating a model's dense indices use the naming returned by
        ``cluster_model_and_naming`` (same snapshot as the model)."""
        return self.naming_for(self._metadata.cluster())

    @staticmethod
    def naming_for(cluster: ClusterMetadata) -> Dict[str, object]:
        topics = cluster.topics()
        topic_id = {t: i for i, t in enumerate(topics)}
        parts = sorted(cluster.partitions,
                       key=lambda p: (topic_id[p.topic], p.partition))
        return {
            "topics": topics,
            "partitions": [p.tp for p in parts],
            "brokers": sorted(cluster.broker_ids()),
        }
