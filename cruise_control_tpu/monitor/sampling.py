"""Metric sampling: sampler SPI, sample types, sample stores.

Parity with the reference's sampling stack (monitor/sampling/):
``MetricSampler`` SPI (MetricSampler.java:26,96) with ``SamplingMode``,
``PartitionMetricSample``/``BrokerMetricSample`` holders (holder/),
``SampleStore`` SPI with persistence + warm-start replay
(KafkaSampleStore.java:69 — here a JSONL file store; the Kafka-topic store
becomes an adapter at the edge), and the metric processor that derives
per-partition CPU from broker CPU weighted by bytes rates
(SamplingUtils.estimateLeaderCpuUtil, sampling/SamplingUtils.java:84-111).
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from cruise_control_tpu.monitor.metadata import ClusterMetadata


class SamplingMode(enum.Enum):
    """Reference: MetricSampler.SamplingMode (MetricSampler.java:96)."""

    ALL = "all"
    BROKER_METRICS_ONLY = "broker_metrics_only"
    PARTITION_METRICS_ONLY = "partition_metrics_only"
    ONGOING_EXECUTION = "ongoing_execution"


@dataclasses.dataclass(frozen=True)
class PartitionMetricSample:
    """holder/PartitionMetricSample analogue: one (topic, partition) sample."""

    topic: str
    partition: int
    broker_id: int            # leader broker at sample time
    time_ms: int
    metrics: Dict[str, float]  # metric name → value (KAFKA_METRIC_DEF names)

    @property
    def entity(self) -> Tuple[str, int]:
        return (self.topic, self.partition)

    def to_json(self) -> str:
        return json.dumps({"type": "partition", "topic": self.topic,
                           "partition": self.partition, "broker": self.broker_id,
                           "time_ms": self.time_ms, "metrics": self.metrics})


@dataclasses.dataclass(frozen=True)
class BrokerMetricSample:
    """holder/BrokerMetricSample analogue."""

    broker_id: int
    time_ms: int
    metrics: Dict[str, float]

    @property
    def entity(self) -> int:
        return self.broker_id

    def to_json(self) -> str:
        return json.dumps({"type": "broker", "broker": self.broker_id,
                           "time_ms": self.time_ms, "metrics": self.metrics})


@dataclasses.dataclass
class Samples:
    partition_samples: List[PartitionMetricSample]
    broker_samples: List[BrokerMetricSample]


class MetricSampler:
    """SPI (MetricSampler.java:26): fetch samples for assigned partitions in
    a time range."""

    def get_samples(self, cluster: ClusterMetadata,
                    partitions: Sequence[Tuple[str, int]],
                    start_ms: int, end_ms: int,
                    mode: SamplingMode = SamplingMode.ALL) -> Samples:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SyntheticWorkloadSampler(MetricSampler):
    """Deterministic synthetic sampler for tests/benchmarks: each partition
    carries a stable per-partition workload (seeded by hash) with optional
    time jitter — the in-memory analogue of the embedded-cluster fixtures."""

    def __init__(self, mean_nw_kb: float = 100.0, mean_disk_mb: float = 100.0,
                 cpu_per_kb: float = 0.001, seed: int = 0):
        self._nw = mean_nw_kb
        self._disk = mean_disk_mb
        self._cpu_per_kb = cpu_per_kb
        self._seed = seed

    def _partition_scale(self, topic: str, partition: int) -> float:
        # crc32, not hash(): builtin str hashing is randomized per process
        # (PYTHONHASHSEED), which made "deterministic" quietly mean
        # "deterministic within one interpreter" — plan sizes, and any test
        # or bench thresholds derived from them, drifted across runs.
        h = zlib.crc32(f"{self._seed}/{topic}/{partition}".encode()) & 0xFFFF
        return 0.25 + 1.5 * (h / 0xFFFF)

    def get_samples(self, cluster, partitions, start_ms, end_ms,
                    mode=SamplingMode.ALL) -> Samples:
        psamples: List[PartitionMetricSample] = []
        bsamples: List[BrokerMetricSample] = []
        by_tp = {p.tp: p for p in cluster.partitions}
        t = end_ms
        want_partitions = mode in (SamplingMode.ALL, SamplingMode.PARTITION_METRICS_ONLY,
                                   SamplingMode.ONGOING_EXECUTION)
        want_brokers = mode in (SamplingMode.ALL, SamplingMode.BROKER_METRICS_ONLY,
                                SamplingMode.ONGOING_EXECUTION)
        # Broker CPU derives from the leaders' workloads, so compute the
        # per-partition rows regardless of mode and only *emit* them when the
        # mode asks for partition samples.
        per_broker_cpu: Dict[int, float] = {}
        if want_partitions or want_brokers:
            for tp in partitions:
                info = by_tp.get(tuple(tp))
                if info is None or info.leader < 0:
                    continue
                s = self._partition_scale(*tp)
                nw_in = self._nw * s
                nw_out = 1.4 * self._nw * s
                cpu = self._cpu_per_kb * (nw_in + nw_out)
                per_broker_cpu[info.leader] = per_broker_cpu.get(info.leader, 0.0) + cpu
                if want_partitions:
                    psamples.append(PartitionMetricSample(
                        topic=tp[0], partition=tp[1], broker_id=info.leader, time_ms=t,
                        metrics={
                            "CPU_USAGE": cpu,
                            "DISK_USAGE": self._disk * s,
                            "LEADER_BYTES_IN": nw_in,
                            "LEADER_BYTES_OUT": nw_out,
                            "PRODUCE_RATE": 10.0 * s,
                            "FETCH_RATE": 14.0 * s,
                            "MESSAGE_IN_RATE": 100.0 * s,
                            "REPLICATION_BYTES_IN_RATE": nw_in * (len(info.replicas) - 1),
                            "REPLICATION_BYTES_OUT_RATE": nw_in * (len(info.replicas) - 1),
                        }))
        if want_brokers:
            for b in cluster.brokers:
                if not b.is_alive:
                    continue
                bsamples.append(BrokerMetricSample(
                    broker_id=b.broker_id, time_ms=t,
                    metrics={
                        "CPU_USAGE": per_broker_cpu.get(b.broker_id, 0.0),
                        "BROKER_REQUEST_QUEUE_SIZE": 1.0,
                        "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT": 0.9,
                        "BROKER_LOG_FLUSH_TIME_MS_999TH": 5.0,
                    }))
        return Samples(psamples, bsamples)


# ---------------------------------------------------------------------------
# Sample stores (SampleStore SPI; checkpoint/resume of derived samples)
# ---------------------------------------------------------------------------

class SampleStore:
    """SPI (sampling/SampleStore.java): persist derived samples and replay
    them on startup — the reference's checkpoint mechanism (SURVEY.md §5)."""

    def store_samples(self, samples: Samples) -> None:
        raise NotImplementedError

    def load_samples(self) -> Samples:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NoopSampleStore(SampleStore):
    def store_samples(self, samples: Samples) -> None:
        pass

    def load_samples(self) -> Samples:
        return Samples([], [])


class InMemorySampleStore(SampleStore):
    def __init__(self):
        self._lock = threading.Lock()
        self._p: List[PartitionMetricSample] = []
        self._b: List[BrokerMetricSample] = []

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            self._p.extend(samples.partition_samples)
            self._b.extend(samples.broker_samples)

    def load_samples(self) -> Samples:
        with self._lock:
            return Samples(list(self._p), list(self._b))


class FileSampleStore(SampleStore):
    """JSONL append-log store; replay on startup rebuilds aggregation windows
    without waiting (KafkaSampleStore.loadSamples warm-start semantics)."""

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def store_samples(self, samples: Samples) -> None:
        with self._lock:
            for s in samples.partition_samples:
                self._f.write(s.to_json() + "\n")
            for s in samples.broker_samples:
                self._f.write(s.to_json() + "\n")
            self._f.flush()

    def load_samples(self) -> Samples:
        out = Samples([], [])
        if not os.path.exists(self._path):
            return out
        with open(self._path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                d = json.loads(line)
                if d["type"] == "partition":
                    out.partition_samples.append(PartitionMetricSample(
                        topic=d["topic"], partition=d["partition"],
                        broker_id=d["broker"], time_ms=d["time_ms"],
                        metrics=d["metrics"]))
                else:
                    out.broker_samples.append(BrokerMetricSample(
                        broker_id=d["broker"], time_ms=d["time_ms"],
                        metrics=d["metrics"]))
        return out

    def close(self) -> None:
        with self._lock:
            self._f.close()


def assign_partitions(cluster: ClusterMetadata, num_fetchers: int
                      ) -> List[List[Tuple[str, int]]]:
    """Topic-granular even spread of partitions over fetchers
    (DefaultMetricSamplerPartitionAssignor semantics)."""
    assignments: List[List[Tuple[str, int]]] = [[] for _ in range(num_fetchers)]
    sizes = [0] * num_fetchers
    topics = sorted(cluster.topics(),
                    key=lambda t: -len(cluster.partitions_for_topic(t)))
    for topic in topics:
        tps = [p.tp for p in cluster.partitions_for_topic(topic)]
        i = sizes.index(min(sizes))
        assignments[i].extend(tps)
        sizes[i] += len(tps)
    return assignments
