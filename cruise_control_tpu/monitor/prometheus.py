"""Prometheus-backed metric sampler.

Parity with ``PrometheusMetricSampler``
(monitor/sampling/prometheus/PrometheusMetricSampler.java:53 +
PrometheusAdapter): instead of consuming the reporter topic, query a
Prometheus server's ``/api/v1/query_range`` for the broker/topic/partition
series (the jmx-exporter names the reference queries), convert each series
point to a ``RawMetric``, and reuse the standard processor to derive
partition/broker samples.

Stdlib-only HTTP; the adapter takes an injectable ``http_get`` so tests run
against a canned responder.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.monitor.metadata import ClusterMetadata
from cruise_control_tpu.monitor.metrics_processor import CruiseControlMetricsProcessor
from cruise_control_tpu.monitor.sampling import (MetricSampler, Samples,
                                                 SamplingMode)
from cruise_control_tpu.reporter.raw_metrics import (MetricScope, RawMetric,
                                                     RawMetricType)

Tp = Tuple[str, int]

# RawMetricType → PromQL (the reference's DEFAULT_QUERIES: jmx-exporter
# metric names, PrometheusMetricSampler.java buildQueries).
DEFAULT_QUERIES: Dict[RawMetricType, str] = {
    RawMetricType.BROKER_CPU_UTIL:
        "1 - avg by (instance) (irate(node_cpu_seconds_total{mode=\"idle\"}[1m]))",
    RawMetricType.ALL_TOPIC_BYTES_IN:
        "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_BytesInPerSec[1m]))",
    RawMetricType.ALL_TOPIC_BYTES_OUT:
        "sum by (instance) (irate(kafka_server_BrokerTopicMetrics_BytesOutPerSec[1m]))",
    RawMetricType.TOPIC_BYTES_IN:
        "irate(kafka_server_BrokerTopicMetrics_BytesInPerSec{topic!=\"\"}[1m])",
    RawMetricType.TOPIC_BYTES_OUT:
        "irate(kafka_server_BrokerTopicMetrics_BytesOutPerSec{topic!=\"\"}[1m])",
    RawMetricType.PARTITION_SIZE:
        "kafka_log_Log_Size{topic!=\"\",partition!=\"\"}",
}


class PrometheusAdapter:
    """Thin /api/v1/query_range client (prometheus/PrometheusAdapter.java)."""

    def __init__(self, endpoint: str,
                 http_get: Optional[Callable[[str], bytes]] = None,
                 step_s: int = 60):
        self._endpoint = endpoint.rstrip("/")
        self._http_get = http_get or self._default_get
        self.step_s = step_s

    @staticmethod
    def _default_get(url: str) -> bytes:
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.read()

    def query_range(self, promql: str, start_s: float, end_s: float
                    ) -> List[dict]:
        qs = urllib.parse.urlencode({
            "query": promql, "start": start_s, "end": end_s,
            "step": self.step_s})
        raw = self._http_get(f"{self._endpoint}/api/v1/query_range?{qs}")
        doc = json.loads(raw)
        if doc.get("status") != "success":
            raise RuntimeError(f"prometheus query failed: {doc}")
        return doc.get("data", {}).get("result", [])


class PrometheusMetricSampler(MetricSampler):
    def __init__(self, adapter: PrometheusAdapter,
                 queries: Optional[Dict[RawMetricType, str]] = None,
                 broker_id_of: Optional[Callable[[Dict[str, str],
                                                  ClusterMetadata],
                                                 Optional[int]]] = None):
        self._adapter = adapter
        self._queries = dict(queries or DEFAULT_QUERIES)
        self._broker_id_of = broker_id_of or self._default_broker_id

    @staticmethod
    def _default_broker_id(labels: Dict[str, str],
                           cluster: ClusterMetadata) -> Optional[int]:
        """Map the series' instance label (host[:port]) onto a broker id by
        host (the reference resolves instance host → broker likewise)."""
        instance = labels.get("instance", "")
        host = instance.rsplit(":", 1)[0]
        for b in cluster.brokers:
            if b.host == host or str(b.broker_id) == host:
                return b.broker_id
        return None

    def get_samples(self, cluster: ClusterMetadata, partitions: Sequence[Tp],
                    start_ms: int, end_ms: int,
                    mode: SamplingMode = SamplingMode.ALL) -> Samples:
        processor = CruiseControlMetricsProcessor()
        for metric_type, promql in self._queries.items():
            try:
                series = self._adapter.query_range(
                    promql, start_ms / 1000.0, end_ms / 1000.0)
            except (OSError, RuntimeError, ValueError):
                continue  # one failing query must not kill the whole pass
            for entry in series:
                labels = entry.get("metric", {})
                broker = self._broker_id_of(labels, cluster)
                if broker is None:
                    continue
                topic = labels.get("topic")
                partition = int(labels.get("partition", -1))
                scope = metric_type.scope
                if scope != MetricScope.BROKER and not topic:
                    continue
                if scope == MetricScope.PARTITION and partition < 0:
                    continue
                for ts, value in entry.get("values", []):
                    try:
                        v = float(value)
                    except (TypeError, ValueError):
                        continue
                    if metric_type == RawMetricType.BROKER_CPU_UTIL:
                        v = min(max(v, 0.0), 1.0)
                    processor.add_metric(RawMetric(
                        metric_type=metric_type, time_ms=int(float(ts) * 1000),
                        broker_id=broker, value=v,
                        topic=topic if scope != MetricScope.BROKER else None,
                        partition=partition if scope == MetricScope.PARTITION
                        else -1))
        samples = processor.process(cluster, partitions, time_ms=end_ms - 1)
        want_partitions = mode in (SamplingMode.ALL,
                                   SamplingMode.PARTITION_METRICS_ONLY,
                                   SamplingMode.ONGOING_EXECUTION)
        # ONGOING_EXECUTION still collects broker metrics — the
        # ConcurrencyAdjuster reads live health during execution; only the
        # partition samples are segregated downstream.
        want_brokers = mode in (SamplingMode.ALL,
                                SamplingMode.BROKER_METRICS_ONLY,
                                SamplingMode.ONGOING_EXECUTION)
        return Samples(samples.partition_samples if want_partitions else [],
                       samples.broker_samples if want_brokers else [])
