"""Raw reporter metrics → derived metric samples.

Parity with ``CruiseControlMetricsProcessor``
(monitor/sampling/CruiseControlMetricsProcessor.java:36) +
``SamplingUtils.estimateLeaderCpuUtil`` (sampling/SamplingUtils.java:84-111):
turn the raw per-broker / per-topic / per-partition records the reporter
produced into ``PartitionMetricSample`` / ``BrokerMetricSample`` rows the
aggregator consumes.

Semantics carried over:

- Topic-level byte rates are reported per broker (each broker reports the
  rates of the partitions it leads); the processor splits a broker's topic
  rate evenly across that broker's leader partitions of the topic.
- Per-partition CPU is estimated from broker CPU weighted by the
  partition's share of the broker's total bytes in+out
  (ModelUtils.estimateLeaderCpuUtilPerCore, model/ModelUtils.java:92).
- Missing-metric tolerance (holder/BrokerLoad.java:243): partitions without
  a size sample and brokers without a CPU sample are skipped, not invented.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from cruise_control_tpu.monitor.metadata import ClusterMetadata
from cruise_control_tpu.monitor.sampling import (BrokerMetricSample,
                                                 PartitionMetricSample, Samples)
from cruise_control_tpu.reporter.raw_metrics import RawMetric, RawMetricType

Tp = Tuple[str, int]

# RawMetricType → broker-sample metric name (KAFKA_METRIC_DEF).
_BROKER_METRIC_NAMES: Dict[RawMetricType, str] = {
    RawMetricType.BROKER_PRODUCE_REQUEST_RATE: "BROKER_PRODUCE_REQUEST_RATE",
    RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_RATE:
        "BROKER_CONSUMER_FETCH_REQUEST_RATE",
    RawMetricType.BROKER_FOLLOWER_FETCH_REQUEST_RATE:
        "BROKER_FOLLOWER_FETCH_REQUEST_RATE",
    RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT:
        "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT",
    RawMetricType.BROKER_REQUEST_QUEUE_SIZE: "BROKER_REQUEST_QUEUE_SIZE",
    RawMetricType.BROKER_RESPONSE_QUEUE_SIZE: "BROKER_RESPONSE_QUEUE_SIZE",
    RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX:
        "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX",
    RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN:
        "BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN",
    RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX:
        "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX",
    RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN:
        "BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN",
    RawMetricType.BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX:
        "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX",
    RawMetricType.BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN:
        "BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN",
    RawMetricType.BROKER_LOG_FLUSH_RATE: "BROKER_LOG_FLUSH_RATE",
    RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MAX: "BROKER_LOG_FLUSH_TIME_MS_MAX",
    RawMetricType.BROKER_LOG_FLUSH_TIME_MS_MEAN: "BROKER_LOG_FLUSH_TIME_MS_MEAN",
    RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH:
        "BROKER_LOG_FLUSH_TIME_MS_999TH",
}

BYTES_TO_KB = 1.0 / 1024.0
BYTES_TO_MB = 1.0 / (1024.0 * 1024.0)


class CruiseControlMetricsProcessor:
    """Accumulates raw metrics, then derives samples against a metadata
    snapshot (process() clears the accumulation)."""

    def __init__(self):
        self._raw: List[RawMetric] = []

    def add_metric(self, metric: RawMetric) -> None:
        self._raw.append(metric)

    def add_metrics(self, metrics: Iterable[RawMetric]) -> None:
        self._raw.extend(metrics)

    def pending(self) -> int:
        return len(self._raw)

    def process(self, cluster: ClusterMetadata,
                partitions: Optional[Iterable[Tp]] = None,
                time_ms: Optional[int] = None) -> Samples:
        raw, self._raw = self._raw, []
        want = set(tuple(tp) for tp in partitions) if partitions is not None \
            else None

        # ---- bucket raw metrics ------------------------------------------
        broker_cpu: Dict[int, float] = {}
        broker_all_bytes: Dict[int, float] = {}    # in + out, bytes/s
        broker_health: Dict[int, Dict[str, float]] = {}
        topic_rates: Dict[Tuple[int, str], Dict[RawMetricType, float]] = {}
        partition_size: Dict[Tp, float] = {}
        latest_ms = 0
        for m in raw:
            latest_ms = max(latest_ms, m.time_ms)
            t = m.metric_type
            if t == RawMetricType.BROKER_CPU_UTIL:
                broker_cpu[m.broker_id] = m.value
            elif t in (RawMetricType.ALL_TOPIC_BYTES_IN,
                       RawMetricType.ALL_TOPIC_BYTES_OUT):
                broker_all_bytes[m.broker_id] = \
                    broker_all_bytes.get(m.broker_id, 0.0) + m.value
            elif t in _BROKER_METRIC_NAMES:
                broker_health.setdefault(m.broker_id, {})[
                    _BROKER_METRIC_NAMES[t]] = m.value
            elif t.name.startswith("TOPIC_"):
                topic_rates.setdefault((m.broker_id, m.topic), {})[t] = m.value
            elif t == RawMetricType.PARTITION_SIZE:
                partition_size[(m.topic, m.partition)] = m.value
        ts = time_ms if time_ms is not None else latest_ms

        # ---- leader partitions per (broker, topic) -----------------------
        leaders: Dict[Tuple[int, str], List[Tp]] = {}
        leader_of: Dict[Tp, int] = {}
        for p in cluster.partitions:
            if p.leader < 0:
                continue
            leader_of[p.tp] = p.leader
            leaders.setdefault((p.leader, p.topic), []).append(p.tp)

        def topic_rate(broker: int, topic: str, t: RawMetricType) -> float:
            return topic_rates.get((broker, topic), {}).get(t, 0.0)

        # ---- partition samples -------------------------------------------
        psamples: List[PartitionMetricSample] = []
        for tp, size_bytes in sorted(partition_size.items()):
            if want is not None and tp not in want:
                continue
            leader = leader_of.get(tp)
            if leader is None:
                continue  # stale record for a vanished partition
            n = max(len(leaders.get((leader, tp[0]), [tp])), 1)
            b_in = topic_rate(leader, tp[0], RawMetricType.TOPIC_BYTES_IN) / n
            b_out = topic_rate(leader, tp[0], RawMetricType.TOPIC_BYTES_OUT) / n
            rep_in = topic_rate(leader, tp[0],
                                RawMetricType.TOPIC_REPLICATION_BYTES_IN) / n
            rep_out = topic_rate(leader, tp[0],
                                 RawMetricType.TOPIC_REPLICATION_BYTES_OUT) / n
            # CPU share ∝ partition's bytes share of the broker's total
            # (estimateLeaderCpuUtil); even share when rates are absent.
            total = broker_all_bytes.get(leader, 0.0)
            if total > 0:
                share = (b_in + b_out) / total
            else:
                share = 1.0 / max(sum(len(v) for (b, _), v in leaders.items()
                                      if b == leader), 1)
            cpu = broker_cpu.get(leader, 0.0) * share
            psamples.append(PartitionMetricSample(
                topic=tp[0], partition=tp[1], broker_id=leader, time_ms=ts,
                metrics={
                    "CPU_USAGE": cpu,
                    "DISK_USAGE": size_bytes * BYTES_TO_MB,
                    "LEADER_BYTES_IN": b_in * BYTES_TO_KB,
                    "LEADER_BYTES_OUT": b_out * BYTES_TO_KB,
                    "PRODUCE_RATE": topic_rate(
                        leader, tp[0], RawMetricType.TOPIC_PRODUCE_REQUEST_RATE) / n,
                    "FETCH_RATE": topic_rate(
                        leader, tp[0], RawMetricType.TOPIC_FETCH_REQUEST_RATE) / n,
                    "MESSAGE_IN_RATE": topic_rate(
                        leader, tp[0], RawMetricType.TOPIC_MESSAGES_IN_PER_SEC) / n,
                    "REPLICATION_BYTES_IN_RATE": rep_in * BYTES_TO_KB,
                    "REPLICATION_BYTES_OUT_RATE": rep_out * BYTES_TO_KB,
                }))

        # ---- broker samples ----------------------------------------------
        bsamples: List[BrokerMetricSample] = []
        for b in sorted(broker_cpu):
            metrics = {"CPU_USAGE": broker_cpu[b]}
            metrics.update(broker_health.get(b, {}))
            bsamples.append(BrokerMetricSample(broker_id=b, time_ms=ts,
                                               metrics=metrics))
        return Samples(psamples, bsamples)
