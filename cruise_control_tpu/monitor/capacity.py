"""Broker capacity resolution.

Parity with the ``BrokerCapacityConfigResolver`` SPI and its JSON file
implementation (config/BrokerCapacityConfigResolver.java:17,
BrokerCapacityConfigFileResolver.java:149, BrokerCapacityInfo.java): per-
broker capacity for CPU (cores → percent), network in/out (KB/s) and disk
(MB, per logdir for JBOD), with a ``-1`` broker id carrying the default.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, Optional, Tuple

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource

DEFAULT_CAPACITY_BROKER_ID = -1


@dataclasses.dataclass(frozen=True)
class BrokerCapacityInfo:
    """Capacity of one broker (config/BrokerCapacityInfo.java)."""

    cpu: float                 # total percent (100 × cores)
    network_in: float          # KB/s
    network_out: float         # KB/s
    disk: float                # MB total
    disk_by_logdir: Tuple[Tuple[str, float], ...] = ()
    num_cores: int = 1
    is_estimated: bool = False
    estimation_info: str = ""

    def as_row(self) -> np.ndarray:
        row = np.zeros(NUM_RESOURCES, np.float32)
        row[Resource.CPU] = self.cpu
        row[Resource.NW_IN] = self.network_in
        row[Resource.NW_OUT] = self.network_out
        row[Resource.DISK] = self.disk
        return row


class BrokerCapacityResolver:
    """SPI: resolve a broker's capacity (BrokerCapacityConfigResolver)."""

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        raise NotImplementedError


class FileCapacityResolver(BrokerCapacityResolver):
    """JSON file resolver (BrokerCapacityConfigFileResolver.java:149).

    Accepts the reference's ``capacityJBOD.json`` shape::

        {"brokerCapacities": [
            {"brokerId": "-1", "capacity": {"DISK": {"/logdir1": "100000", ...}
                                            | "100000",
                                            "CPU": "100" | {"num.cores": "8"},
                                            "NW_IN": "10000", "NW_OUT": "10000"}}]}
    """

    def __init__(self, path: Optional[str] = None, doc: Optional[dict] = None):
        if doc is None:
            with open(path) as f:
                doc = json.load(f)
        self._by_broker: Dict[int, BrokerCapacityInfo] = {}
        for entry in doc.get("brokerCapacities", []):
            broker_id = int(entry["brokerId"])
            self._by_broker[broker_id] = self._parse(entry["capacity"])
        if DEFAULT_CAPACITY_BROKER_ID not in self._by_broker:
            raise ValueError("capacity config must define default brokerId -1")

    @staticmethod
    def _parse(cap: dict) -> BrokerCapacityInfo:
        disk_raw = cap["DISK"]
        if isinstance(disk_raw, dict):
            by_logdir = tuple((ld, float(v)) for ld, v in disk_raw.items())
            disk = float(sum(v for _, v in by_logdir))
        else:
            by_logdir = ()
            disk = float(disk_raw)
        cpu_raw = cap["CPU"]
        if isinstance(cpu_raw, dict):
            cores = int(cpu_raw.get("num.cores", 1))
            cpu = 100.0 * cores
        else:
            cores = max(int(float(cpu_raw) // 100), 1)
            cpu = float(cpu_raw)
        return BrokerCapacityInfo(
            cpu=cpu, network_in=float(cap["NW_IN"]), network_out=float(cap["NW_OUT"]),
            disk=disk, disk_by_logdir=by_logdir, num_cores=cores)

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        info = self._by_broker.get(broker_id)
        if info is not None:
            return info
        default = self._by_broker[DEFAULT_CAPACITY_BROKER_ID]
        if not allow_estimation:
            raise ValueError(f"no capacity configured for broker {broker_id} "
                             "and estimation disallowed")
        return dataclasses.replace(default, is_estimated=True,
                                   estimation_info=f"default capacity for broker {broker_id}")


class StaticCapacityResolver(BrokerCapacityResolver):
    """Uniform capacity for every broker (tests / synthetic runs)."""

    def __init__(self, cpu: float = 100.0, network_in: float = 200000.0,
                 network_out: float = 200000.0, disk: float = 1000000.0):
        self._info = BrokerCapacityInfo(cpu=cpu, network_in=network_in,
                                        network_out=network_out, disk=disk)

    def capacity_for_broker(self, rack: str, host: str, broker_id: int,
                            allow_estimation: bool = True) -> BrokerCapacityInfo:
        return self._info
