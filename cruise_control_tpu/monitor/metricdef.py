"""Metric definitions.

Parity with the core metric registry (`cruise-control-core/.../metricdef/` —
``MetricDef``, ``MetricInfo``, ``ValueComputingStrategy``) and its Kafka
binding ``KafkaMetricDef``
(monitor/metricdefinition/KafkaMetricDef.java:42-102): a fixed id-indexed
registry of metric names with a window-collapse strategy (AVG / MAX /
LATEST) and a COMMON vs BROKER_ONLY scope split.  Ids are the metric-axis
column indices of the aggregation tensors, so the registry is frozen at
import time.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Tuple

from cruise_control_tpu.common.resources import Resource


class ValueComputingStrategy(enum.Enum):
    AVG = "avg"
    MAX = "max"
    LATEST = "latest"


@dataclasses.dataclass(frozen=True)
class MetricInfo:
    name: str
    metric_id: int
    strategy: ValueComputingStrategy
    group: Optional[str] = None  # resource-group name for group aggregates
    broker_only: bool = False


class MetricDef:
    """Immutable name→id→strategy registry (core MetricDef analogue)."""

    def __init__(self, infos: List[MetricInfo]):
        self._infos = tuple(infos)
        self._by_name: Dict[str, MetricInfo] = {i.name: i for i in infos}
        if len(self._by_name) != len(infos):
            raise ValueError("duplicate metric names")
        for idx, info in enumerate(infos):
            if info.metric_id != idx:
                raise ValueError(f"metric {info.name} id {info.metric_id} != index {idx}")

    def metric_info(self, name: str) -> MetricInfo:
        return self._by_name[name]

    def metric_info_by_id(self, metric_id: int) -> MetricInfo:
        return self._infos[metric_id]

    def all_metric_infos(self) -> Tuple[MetricInfo, ...]:
        return self._infos

    @property
    def num_metrics(self) -> int:
        return len(self._infos)

    def common_ids(self) -> List[int]:
        return [i.metric_id for i in self._infos if not i.broker_only]


def _build(entries) -> MetricDef:
    return MetricDef([MetricInfo(name=n, metric_id=i, strategy=s, group=g,
                                 broker_only=b)
                      for i, (n, s, g, b) in enumerate(entries)])


A, M, L = ValueComputingStrategy.AVG, ValueComputingStrategy.MAX, ValueComputingStrategy.LATEST

# The Kafka metric space (KafkaMetricDef.java:42-102).  COMMON metrics exist
# for partitions and brokers; BROKER_ONLY only in broker samples.
KAFKA_METRIC_DEF = _build([
    # name, strategy, resource-group, broker_only
    ("CPU_USAGE", A, "cpu", False),
    ("DISK_USAGE", L, "disk", False),
    ("LEADER_BYTES_IN", A, "networkInbound", False),
    ("LEADER_BYTES_OUT", A, "networkOutbound", False),
    ("PRODUCE_RATE", A, None, False),
    ("FETCH_RATE", A, None, False),
    ("MESSAGE_IN_RATE", A, None, False),
    ("REPLICATION_BYTES_IN_RATE", A, None, False),
    ("REPLICATION_BYTES_OUT_RATE", A, None, False),
    ("BROKER_PRODUCE_REQUEST_RATE", A, None, True),
    ("BROKER_CONSUMER_FETCH_REQUEST_RATE", A, None, True),
    ("BROKER_FOLLOWER_FETCH_REQUEST_RATE", A, None, True),
    ("BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT", A, None, True),
    ("BROKER_REQUEST_QUEUE_SIZE", M, None, True),
    ("BROKER_RESPONSE_QUEUE_SIZE", M, None, True),
    ("BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX", M, None, True),
    ("BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN", A, None, True),
    ("BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX", M, None, True),
    ("BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN", A, None, True),
    ("BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX", M, None, True),
    ("BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN", A, None, True),
    ("BROKER_LOG_FLUSH_RATE", A, None, True),
    ("BROKER_LOG_FLUSH_TIME_MS_MAX", M, None, True),
    ("BROKER_LOG_FLUSH_TIME_MS_MEAN", A, None, True),
    ("BROKER_LOG_FLUSH_TIME_MS_999TH", M, None, True),
])

# Resource → COMMON metric id providing its utilization (model building).
RESOURCE_TO_METRIC_ID: Dict[Resource, int] = {
    Resource.CPU: KAFKA_METRIC_DEF.metric_info("CPU_USAGE").metric_id,
    Resource.NW_IN: KAFKA_METRIC_DEF.metric_info("LEADER_BYTES_IN").metric_id,
    Resource.NW_OUT: KAFKA_METRIC_DEF.metric_info("LEADER_BYTES_OUT").metric_id,
    Resource.DISK: KAFKA_METRIC_DEF.metric_info("DISK_USAGE").metric_id,
}

REPLICATION_BYTES_IN_ID = KAFKA_METRIC_DEF.metric_info("REPLICATION_BYTES_IN_RATE").metric_id
