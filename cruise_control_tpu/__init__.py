"""cruise_control_tpu — a TPU-native cluster-workload balancing framework.

A brand-new implementation of the capabilities of Kafka Cruise Control
(reference: viktorsomogyi/cruise-control), redesigned TPU-first:

- The cluster workload model is a struct-of-arrays tensor pytree
  (``model.TensorClusterModel``) instead of a JVM object graph
  (reference: cruise-control/src/main/java/.../model/ClusterModel.java:46).
- Goals are pure vectorized ``(cost, feasibility, acceptance)`` functions
  (reference: analyzer/goals/Goal.java:39) and the optimizer scores tens of
  thousands of candidate balancing actions per step on the MXU via jit/vmap
  instead of iterating replica-by-replica (reference:
  analyzer/goals/AbstractGoal.java:82).
- Multi-chip scaling uses a jax.sharding.Mesh + collectives over ICI, not
  thread pools.

Subpackages mirror the reference's layer map (SURVEY.md §1):
``monitor`` (sampling/aggregation) → ``model`` (cluster model) →
``analyzer`` (goals + optimizer) → ``executor`` (movement execution) →
``detector`` (anomalies/self-healing) → ``api``/``client`` (REST/CLI),
with ``ops``/``parallel`` holding the TPU kernels and sharding layer.
"""

__version__ = "0.1.0"
