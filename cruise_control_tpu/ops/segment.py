"""Segment reductions used by the tensor cluster model.

These are the TPU-native replacement for the reference's per-broker
bookkeeping (Rack/Host/Broker cascading load updates —
model/ClusterModel.java:428-431): instead of mutating per-object
accumulators on every replica move, broker/host/rack aggregates are
*recomputed* as one XLA scatter-add over the replica axis, which lowers to a
single fused kernel and vectorizes over the resource axis for free.

All functions take a static ``num_segments`` so shapes stay static under
``jit``.  Invalid rows are handled with a mask (padding rows carry segment id
pointing anywhere; the mask zeroes their contribution) — the standard
padding+mask idiom for dynamic-size data on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def masked_segment_sum(values: Array, segment_ids: Array, num_segments: int, mask: Array | None = None) -> Array:
    """Sum ``values`` rows into ``num_segments`` buckets, zeroing masked rows.

    values: f32[N, ...]; segment_ids: i32[N]; mask: bool[N] or None.
    Returns f32[num_segments, ...].
    """
    if mask is not None:
        expand = (slice(None),) + (None,) * (values.ndim - 1)
        values = jnp.where(mask[expand], values, 0)
        segment_ids = jnp.where(mask, segment_ids, 0)
    out_shape = (num_segments,) + values.shape[1:]
    return jnp.zeros(out_shape, values.dtype).at[segment_ids].add(values)


def masked_segment_count(segment_ids: Array, num_segments: int, mask: Array | None = None) -> Array:
    """Count rows per segment. Returns i32[num_segments]."""
    ones = jnp.ones(segment_ids.shape[0], jnp.int32)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
        segment_ids = jnp.where(mask, segment_ids, 0)
    return jnp.zeros((num_segments,), jnp.int32).at[segment_ids].add(ones)


def segment_max(values: Array, segment_ids: Array, num_segments: int, mask: Array | None = None,
                initial: float = 0.0) -> Array:
    """Per-segment max with masked rows contributing ``initial``."""
    if mask is not None:
        values = jnp.where(mask, values, initial)
        segment_ids = jnp.where(mask, segment_ids, 0)
    return jnp.full((num_segments,), initial, values.dtype).at[segment_ids].max(values)


def segment_min(values: Array, segment_ids: Array, num_segments: int, mask: Array | None = None,
                initial: float = jnp.inf) -> Array:
    """Per-segment min with masked rows contributing ``initial``."""
    if mask is not None:
        values = jnp.where(mask, values, initial)
        segment_ids = jnp.where(mask, segment_ids, 0)
    return jnp.full((num_segments,), initial, values.dtype).at[segment_ids].min(values)
