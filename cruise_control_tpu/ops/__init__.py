from cruise_control_tpu.ops.segment import (
    masked_segment_sum,
    masked_segment_count,
    segment_max,
    segment_min,
)

__all__ = [
    "masked_segment_sum",
    "masked_segment_count",
    "segment_max",
    "segment_min",
]
