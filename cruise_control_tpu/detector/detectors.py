"""Anomaly detectors.

Parity with the reference's detector suite (SURVEY.md §2.6):

- ``GoalViolationDetector`` (GoalViolationDetector.java:55): re-checks the
  detection goals on a fresh cluster model, splits violations into fixable
  vs unfixable, skips when offline replicas exist (defers to the failure
  detectors).
- ``BrokerFailureDetector`` (BrokerFailureDetector.java:44): diffs the
  expected broker set against live metadata; failure times persisted to a
  JSON file so grace periods survive restarts (the reference persists them
  in its own ZK path).
- ``DiskFailureDetector`` (DiskFailureDetector.java:34): offline logdirs via
  the admin's describe_logdirs.
- ``MetricAnomalyDetector`` + ``PercentileMetricAnomalyFinder`` (core SPI,
  cruise-control-core detector/metricanomaly/) and ``SlowBrokerFinder``
  (SlowBrokerFinder.java:33-105): log-flush-time 999th percentile, raw and
  normalized by bytes-in, compared against the broker's own history
  percentile AND its peers; slowness-score escalation demotion → removal;
  unfixable when too many brokers look slow at once.
- ``TopicAnomalyDetector`` with RF and partition-size finders
  (TopicReplicationFactorAnomalyFinder.java, PartitionSizeAnomalyFinder).
- ``MaintenanceEventDetector`` + queue-backed reader with idempotence cache
  (MaintenanceEventTopicReader.java:25, IdempotenceCache.java).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import kernels
from cruise_control_tpu.analyzer.goals.specs import goals_by_priority
from cruise_control_tpu.analyzer.state import BrokerArrays
from cruise_control_tpu.detector.anomalies import (Anomaly, BrokerFailures, DiskFailures,
                                                   GoalViolations, MaintenanceEvent,
                                                   SlowBrokers,
                                                   TopicPartitionSizeAnomaly,
                                                   TopicReplicationFactorAnomaly)
from cruise_control_tpu.monitor.load_monitor import (LoadMonitor,
                                                     NotEnoughValidWindowsError)
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF


class GoalViolationDetector:
    def __init__(self, load_monitor: LoadMonitor, detection_goals: Sequence[str],
                 constraint: Optional[BalancingConstraint] = None,
                 provisioner=None,
                 balancedness_priority_weight: float = 1.1,
                 balancedness_strictness_weight: float = 1.5):
        from cruise_control_tpu.analyzer.balancedness import (
            MAX_BALANCEDNESS_SCORE, balancedness_cost_by_goal)
        self._lm = load_monitor
        self._goals = list(detection_goals)
        self._constraint = constraint or BalancingConstraint.default()
        # Provisioner SPI (detector/Provisioner.java): receives UNDER/OVER
        # recommendations aggregated over the detection pass
        # (GoalViolationDetector.java:160-237 optionally right-sizes).
        self._provisioner = provisioner
        self.last_checked_generation: Optional[Tuple[int, int]] = None
        self.last_provision_response = None
        self.last_rightsize_result = None
        # Rolling balancedness (GoalViolationDetector.java:63-64,106):
        # refreshed on every detection pass; 100 until the first pass.
        self._balancedness_costs = (
            balancedness_cost_by_goal(goals_by_priority(self._goals),
                                      balancedness_priority_weight,
                                      balancedness_strictness_weight)
            if self._goals else {})  # empty detection set = detector disabled
        self.balancedness_score: float = MAX_BALANCEDNESS_SCORE

    def _goal_satisfactions(self, model):
        """Per-goal satisfied flags plus the any-offline-replica verdict.

        The scalar path costs one device round-trip per goal; the device
        subclass (``detector.device.DeviceGoalViolationDetector``) answers
        both questions in ONE fused stack-satisfied sweep dispatch.  Returns
        ``(sat, any_offline)`` where ``sat`` is a list of bools in
        ``goals_by_priority`` order (None when offline replicas exist — the
        caller defers to the failure detectors without evaluating goals)."""
        if bool(np.asarray(model.replica_offline_now()).any()):
            return None, True
        arrays = BrokerArrays.from_model(model)
        sat = [bool(kernels.goal_satisfied(spec, model, arrays,
                                           self._constraint))
               for spec in goals_by_priority(self._goals)]
        return sat, False

    def detect(self, now_ms: int) -> Optional[GoalViolations]:
        from cruise_control_tpu.analyzer.balancedness import (
            BALANCEDNESS_SCORE_WITH_OFFLINE_REPLICAS, balancedness_score)
        try:
            model = self._lm.cluster_model()
        except NotEnoughValidWindowsError:
            return None
        sat, any_offline = self._goal_satisfactions(model)
        if any_offline:
            # Defer to broker/disk failure detectors (GoalViolationDetector
            # skips when offline replicas exist, :160-237); the score is
            # pinned to the offline sentinel meanwhile (:69,281).
            self.balancedness_score = BALANCEDNESS_SCORE_WITH_OFFLINE_REPLICAS
            return None
        gen = self._lm.model_generation().as_tuple()
        self.last_checked_generation = gen
        fixable: List[str] = []
        unfixable: List[str] = []
        rf_max = int(np.asarray(model.partition_replication_factor()).max(initial=0))
        from cruise_control_tpu.analyzer.provisioning import (
            ProvisionResponse, ProvisionStatus, host_view,
            provision_verdict_for_goal)
        provision = ProvisionResponse()
        view = host_view(model)
        for spec, satisfied in zip(goals_by_priority(self._goals), sat):
            provision.aggregate(provision_verdict_for_goal(
                spec, model, self._constraint, satisfied, view))
            if satisfied:
                continue
            if spec.kind in ("rack", "rack_distribution") and rf_max > model.num_racks:
                unfixable.append(spec.name)
            else:
                fixable.append(spec.name)
        self.last_provision_response = provision
        self.balancedness_score = balancedness_score(
            self._balancedness_costs, fixable + unfixable)
        if self._provisioner is not None and provision.status in (
                ProvisionStatus.UNDER_PROVISIONED,
                ProvisionStatus.OVER_PROVISIONED):
            self.last_rightsize_result = self._provisioner.rightsize(
                provision.recommendations)
        if not fixable and not unfixable:
            return None
        return GoalViolations(detection_time_ms=now_ms, fixable_goals=fixable,
                              unfixable_goals=unfixable)


class BrokerFailureDetector:
    def __init__(self, metadata_client, persist_path: Optional[str] = None):
        self._md = metadata_client
        self._path = persist_path
        self._failure_times: Dict[int, int] = {}
        self._known: Set[int] = set()
        self._lock = threading.Lock()
        if persist_path and os.path.exists(persist_path):
            with open(persist_path) as f:
                self._failure_times = {int(k): int(v) for k, v in json.load(f).items()}

    def _persist(self) -> None:
        if self._path:
            os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
            with open(self._path, "w") as f:
                json.dump(self._failure_times, f)

    def detect(self, now_ms: int) -> Optional[BrokerFailures]:
        cluster = self._md.cluster()
        with self._lock:
            alive = set(cluster.alive_broker_ids())
            self._known |= {b.broker_id for b in cluster.brokers}
            failed = self._known - alive
            changed = False
            for b in failed:
                if b not in self._failure_times:
                    self._failure_times[b] = now_ms
                    changed = True
            for b in list(self._failure_times):
                if b in alive:
                    del self._failure_times[b]
                    changed = True
            if changed:
                self._persist()
            if not self._failure_times:
                return None
            return BrokerFailures(detection_time_ms=now_ms,
                                  failed_brokers=dict(self._failure_times))

    def forget(self, brokers: Sequence[int]) -> None:
        """Drop brokers that were healed/removed so they stop re-alerting."""
        with self._lock:
            for b in brokers:
                self._failure_times.pop(b, None)
                self._known.discard(b)
            self._persist()


class DiskFailureDetector:
    def __init__(self, admin, metadata_client):
        self._admin = admin
        self._md = metadata_client

    def detect(self, now_ms: int) -> Optional[DiskFailures]:
        alive = set(self._md.cluster().alive_broker_ids())
        failed: Dict[int, Tuple[str, ...]] = {}
        for broker, dirs in self._admin.describe_logdirs().items():
            if broker not in alive:
                continue  # whole-broker failure is the broker detector's job
            dead = tuple(ld for ld, ok in dirs.items() if not ok)
            if dead:
                failed[broker] = dead
        if not failed:
            return None
        return DiskFailures(detection_time_ms=now_ms, failed_disks=failed)


class PercentileMetricAnomalyFinder:
    """core detector/metricanomaly/PercentileMetricAnomalyFinder.java: flag
    brokers whose latest value exceeds the upper percentile of their own
    history by a margin."""

    def __init__(self, metric_name: str = "BROKER_LOG_FLUSH_TIME_MS_999TH",
                 upper_percentile: float = 95.0, margin: float = 1.5,
                 persistence: int = 1):
        # The default metric matches the reference's slow-broker signal so
        # the class is loadable via metric.anomaly.finder.class.  The 1.5x
        # default margin = the reference's metric.anomaly.upper.margin=0.5
        # over the history percentile.
        self.metric = metric_name
        self._pct = upper_percentile
        self._margin = margin
        # Optional: consecutive excursions required before reporting
        # (reference parity is 1 — report on detection; raise for noisy
        # metrics, noting an excursion folds into its own history next
        # window).
        self._persistence = persistence
        self._streak: Dict[int, int] = {}

    def configure(self, config: Dict[str, object]) -> None:
        """Plugin-style init (metric.anomaly.finder.class): the reference's
        PercentileMetricAnomalyFinderConfig keys — upper percentile and the
        fractional upper margin (threshold = percentile x (1 + margin))."""
        from cruise_control_tpu.config import constants as C
        if C.METRIC_ANOMALY_PERCENTILE_UPPER_THRESHOLD_CONFIG in config:
            self._pct = float(
                config[C.METRIC_ANOMALY_PERCENTILE_UPPER_THRESHOLD_CONFIG])
        if C.METRIC_ANOMALY_UPPER_MARGIN_CONFIG in config:
            self._margin = 1.0 + float(
                config[C.METRIC_ANOMALY_UPPER_MARGIN_CONFIG])

    def anomalies(self, broker_agg) -> Dict[int, float]:
        res = broker_agg.aggregate()
        mid = KAFKA_METRIC_DEF.metric_info(self.metric).metric_id
        out: Dict[int, float] = {}
        vals = res.values[:, :, mid]  # [E, W]
        if vals.shape[1] < 3:
            return out
        for row, broker in enumerate(res.entities):
            history, latest = vals[row, :-1], vals[row, -1]
            if not res.window_valid[row, -1] or not res.window_valid[row, :-1].any():
                continue
            hist = history[res.window_valid[row, :-1]]
            threshold = np.percentile(hist, self._pct) * self._margin
            if latest > threshold and latest > 0:
                out[broker] = float(latest / max(threshold, 1e-9))
        return out

    def detect(self, broker_agg, now_ms: int) -> Optional[SlowBrokers]:
        """Finder SPI (metric.anomaly.finder.class): persistent percentile
        excursions surface as a demote-class metric anomaly carrying the
        excursion ratio as the score.  Guards mirror SlowBrokerFinder's:
        a broker must exceed its threshold on ``persistence`` consecutive
        passes, and a systemic event (more than half the brokers excursive
        at once — a cluster-wide load spike, not per-broker slowness)
        reports nothing."""
        found = self.anomalies(broker_agg)
        for b in list(self._streak):
            if b not in found:
                del self._streak[b]
        for b in found:
            self._streak[b] = self._streak.get(b, 0) + 1
        num_brokers = len(broker_agg.aggregate().entities)
        # Systemic guard (SlowBrokerFinder semantics): when most of a
        # non-trivial cluster looks anomalous at once it's a workload
        # event, not broker sickness — self-healing must not demote half
        # the fleet.
        if num_brokers >= 4 and len(found) > num_brokers // 2:
            return None
        persistent = {b: found[b] for b, n in self._streak.items()
                      if n >= self._persistence and b in found}
        if not persistent:
            return None
        return SlowBrokers(detection_time_ms=now_ms, slow_brokers=persistent,
                           fix_by_removal=False)


class SlowBrokerFinder:
    """SlowBrokerFinder.java:109 semantics, over the broker aggregator.

    A broker is *suspect* when its log-flush-time 999th (raw AND normalized
    by bytes-in) exceeds both (a) its own history's upper percentile and
    (b) the peer-cluster median by a factor.  Suspects accumulate a
    slowness score across detections; score ≥ demote threshold → demote,
    ≥ removal threshold → remove.  If more than half the cluster looks
    slow, the anomaly is unfixable (self-healing would destroy capacity) —
    reported with no brokers to fix.
    """

    METRIC = "BROKER_LOG_FLUSH_TIME_MS_999TH"
    BYTES_METRIC = "LEADER_BYTES_IN"

    def __init__(self, history_percentile: float = 90.0, history_margin: float = 3.0,
                 peer_percentile: float = 50.0, peer_margin: float = 3.0,
                 demote_score: int = 5, removal_score: int = 10,
                 bytes_in_rate_detection_threshold: float = 0.0,
                 log_flush_time_threshold_ms: float = 0.0):
        self._pct = history_percentile
        self._hist_margin = history_margin
        # slow.broker.peer.metric.percentile.threshold: which percentile of
        # the peer cluster's latest values anchors the peer comparison
        # (50 = the reference's median default).
        self._peer_pct = peer_percentile
        self._peer_margin = peer_margin
        self._demote = demote_score
        self._removal = removal_score
        # Absolute floors (slow.broker.bytes.in.rate.detection.threshold /
        # slow.broker.log.flush.time.threshold.ms): idle brokers (tiny
        # bytes-in denominators) and sub-threshold flush times never become
        # suspects regardless of relative excursions.
        self._min_bytes_in = bytes_in_rate_detection_threshold
        self._min_flush_ms = log_flush_time_threshold_ms
        self._scores: Dict[int, int] = {}

    def configure(self, config: Dict[str, object]) -> None:
        """Plugin-style init (metric.anomaly.finder.class): reads the eight
        slow.broker.* threshold keys (AnomalyDetectorConfig.java)."""
        from cruise_control_tpu.config import constants as C
        key_attr = {
            C.SLOW_BROKER_METRIC_HISTORY_PERCENTILE_THRESHOLD_CONFIG: "_pct",
            C.SLOW_BROKER_METRIC_HISTORY_MARGIN_CONFIG: "_hist_margin",
            C.SLOW_BROKER_PEER_METRIC_PERCENTILE_THRESHOLD_CONFIG: "_peer_pct",
            C.SLOW_BROKER_PEER_METRIC_MARGIN_CONFIG: "_peer_margin",
            C.SLOW_BROKER_BYTES_IN_RATE_DETECTION_THRESHOLD_CONFIG: "_min_bytes_in",
            C.SLOW_BROKER_LOG_FLUSH_TIME_THRESHOLD_MS_CONFIG: "_min_flush_ms",
        }
        for key, attr in key_attr.items():
            if key in config:
                setattr(self, attr, float(config[key]))
        if C.SLOW_BROKER_DEMOTION_SCORE_CONFIG in config:
            self._demote = int(config[C.SLOW_BROKER_DEMOTION_SCORE_CONFIG])
        if C.SLOW_BROKER_DECOMMISSION_SCORE_CONFIG in config:
            self._removal = int(config[C.SLOW_BROKER_DECOMMISSION_SCORE_CONFIG])

    def _suspects(self, res, mid: int, bytes_mid: int) -> Set[int]:
        vals = res.values[:, :, mid]
        bts = np.maximum(res.values[:, :, bytes_mid], 1e-9)
        norm = vals / bts
        suspects: Set[int] = set()
        latest_all = []
        for row in range(vals.shape[0]):
            if res.window_valid[row, -1]:
                latest_all.append(vals[row, -1])
        peer_anchor = (np.percentile(latest_all, self._peer_pct)
                       if latest_all else 0.0)
        for row, broker in enumerate(res.entities):
            if not res.window_valid[row, -1] or vals.shape[1] < 3:
                continue
            hist_ok = res.window_valid[row, :-1]
            if not hist_ok.any():
                continue
            raw_now, norm_now = vals[row, -1], norm[row, -1]
            if bts[row, -1] < self._min_bytes_in or raw_now < self._min_flush_ms:
                continue
            raw_hist = np.percentile(vals[row, :-1][hist_ok], self._pct)
            norm_hist = np.percentile(norm[row, :-1][hist_ok], self._pct)
            own_slow = raw_now > raw_hist * self._hist_margin and \
                norm_now > norm_hist * self._hist_margin
            peer_slow = peer_anchor > 0 and raw_now > peer_anchor * self._peer_margin
            if own_slow and peer_slow:
                suspects.add(broker)
        return suspects

    def detect(self, broker_agg, now_ms: int) -> Optional[SlowBrokers]:
        res = broker_agg.aggregate()
        if res.values.shape[0] == 0 or res.values.shape[1] < 3:
            return None
        mid = KAFKA_METRIC_DEF.metric_info(self.METRIC).metric_id
        bmid = KAFKA_METRIC_DEF.metric_info(self.BYTES_METRIC).metric_id
        suspects = self._suspects(res, mid, bmid)
        for b in list(self._scores):
            if b not in suspects:
                self._scores[b] = max(self._scores[b] - 1, 0)
                if self._scores[b] == 0:
                    del self._scores[b]
        for b in suspects:
            self._scores[b] = self._scores.get(b, 0) + 1

        to_remove = {b: float(s) for b, s in self._scores.items() if s >= self._removal}
        to_demote = {b: float(s) for b, s in self._scores.items()
                     if self._demote <= s < self._removal}
        num_brokers = res.values.shape[0]
        if len(suspects) > num_brokers // 2:
            # Too many suspects ⇒ systemic (not per-broker) slowness; fixing
            # by demotion/removal would destroy capacity — report nothing
            # (the reference marks such anomalies unfixable).
            return None
        if to_remove:
            return SlowBrokers(detection_time_ms=now_ms, slow_brokers=to_remove,
                               fix_by_removal=True)
        if to_demote:
            return SlowBrokers(detection_time_ms=now_ms, slow_brokers=to_demote,
                               fix_by_removal=False)
        return None


class MetricAnomalyDetector:
    """Runs pluggable metric-anomaly finders over the broker metric history
    (detector/MetricAnomalyDetector.java:28; finder classes from
    metric.anomaly.finder.class).  A finder is anything with
    ``detect(broker_agg, now_ms) -> Anomaly | list[Anomaly] | None``
    (SlowBrokerFinder is the default, as in the reference)."""

    def __init__(self, load_monitor: LoadMonitor, finders: Sequence[object]):
        self._lm = load_monitor
        self.finders = list(finders)

    def detect(self, now_ms: int) -> List[Anomaly]:
        out: List[Anomaly] = []
        for finder in self.finders:
            found = finder.detect(self._lm.broker_aggregator, now_ms)
            if found is None:
                continue
            out.extend(found if isinstance(found, list) else [found])
        return out


class TopicReplicationFactorAnomalyFinder:
    """detector/TopicReplicationFactorAnomalyFinder.java: topics whose RF
    differs from the desired RF (self.healing.target.topic.replication.factor)."""

    def __init__(self, desired_rf: int = 3):
        self.desired_rf = desired_rf

    def configure(self, config: Dict[str, object]) -> None:
        from cruise_control_tpu.config import constants as C
        if C.SELF_HEALING_TARGET_TOPIC_REPLICATION_FACTOR_CONFIG in config:
            self.desired_rf = int(
                config[C.SELF_HEALING_TARGET_TOPIC_REPLICATION_FACTOR_CONFIG])

    def find(self, cluster, load_monitor, excluded: Set[str],
             now_ms: int) -> List[Anomaly]:
        bad: Dict[str, int] = {}
        for p in cluster.partitions:
            if p.topic in excluded:
                continue
            if len(p.replicas) != self.desired_rf:
                bad[p.topic] = len(p.replicas)
        if not bad:
            return []
        return [TopicReplicationFactorAnomaly(
            detection_time_ms=now_ms, bad_topics=bad, desired_rf=self.desired_rf)]


class PartitionSizeAnomalyFinder:
    """detector/PartitionSizeAnomalyFinder: partitions whose disk footprint
    exceeds a threshold."""

    def __init__(self, size_threshold_mb: float = float("inf")):
        self.size_threshold_mb = size_threshold_mb

    def configure(self, config: Dict[str, object]) -> None:
        from cruise_control_tpu.config import constants as C
        if C.SELF_HEALING_PARTITION_SIZE_THRESHOLD_MB_CONFIG in config:
            self.size_threshold_mb = float(
                config[C.SELF_HEALING_PARTITION_SIZE_THRESHOLD_MB_CONFIG])

    def find(self, cluster, load_monitor, excluded: Set[str],
             now_ms: int) -> List[Anomaly]:
        if load_monitor is None or not np.isfinite(self.size_threshold_mb):
            return []
        agg = load_monitor.partition_aggregator.aggregate()
        mid = KAFKA_METRIC_DEF.metric_info("DISK_USAGE").metric_id
        oversized = {}
        for row, tp in enumerate(agg.entities):
            if tp[0] in excluded:
                continue
            if agg.entity_valid[row] and agg.collapsed[row, mid] > self.size_threshold_mb:
                oversized[f"{tp[0]}-{tp[1]}"] = float(agg.collapsed[row, mid])
        if not oversized:
            return []
        return [TopicPartitionSizeAnomaly(
            detection_time_ms=now_ms, oversized=oversized,
            size_threshold_mb=self.size_threshold_mb)]


class TopicAnomalyDetector:
    """Runs pluggable topic-anomaly finders (TopicAnomalyDetector.java:24;
    classes from topic.anomaly.finder.class) against the metadata view."""

    def __init__(self, metadata_client, desired_rf: int = 3,
                 excluded_topics: Sequence[str] = (),
                 partition_size_threshold_mb: float = float("inf"),
                 load_monitor: Optional[LoadMonitor] = None,
                 finders: Optional[Sequence[object]] = None):
        self._md = metadata_client
        self._excluded = set(excluded_topics)
        self._lm = load_monitor
        self.finders = (list(finders) if finders is not None else
                        [TopicReplicationFactorAnomalyFinder(desired_rf),
                         PartitionSizeAnomalyFinder(partition_size_threshold_mb)])

    def detect(self, now_ms: int) -> List[Anomaly]:
        cluster = self._md.cluster()
        out: List[Anomaly] = []
        for finder in self.finders:
            out.extend(finder.find(cluster, self._lm, self._excluded, now_ms))
        return out


class MaintenanceEventReader:
    """Queue-backed plan source (MaintenanceEventTopicReader analogue);
    operators publish plans via the API layer."""

    def __init__(self):
        self._lock = threading.Lock()
        self._queue: deque = deque()

    def publish(self, event: MaintenanceEvent) -> None:
        with self._lock:
            self._queue.append(event)

    def drain(self) -> List[MaintenanceEvent]:
        with self._lock:
            out = list(self._queue)
            self._queue.clear()
            return out


class MaintenanceEventDetector:
    def __init__(self, reader: MaintenanceEventReader,
                 idempotence_ttl_ms: int = 3600_000):
        self._reader = reader
        self._ttl = idempotence_ttl_ms
        self._seen: Dict[Tuple, int] = {}

    def detect(self, now_ms: int) -> List[MaintenanceEvent]:
        for k, t in list(self._seen.items()):
            if now_ms - t > self._ttl:
                del self._seen[k]
        out = []
        for ev in self._reader.drain():
            key = ev.dedup_key()
            if key in self._seen:
                continue  # IdempotenceCache drop
            self._seen[key] = now_ms
            out.append(ev)
        return out
