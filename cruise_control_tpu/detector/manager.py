"""Anomaly detection manager.

Parity with ``AnomalyDetectorManager`` (detector/AnomalyDetectorManager.java:52):
owns all detectors, runs them at per-type intervals, feeds a priority queue
(priority = anomaly type, broker failures first), and drains it through the
notifier — FIX runs ``anomaly.fix(facade)``, CHECK re-queues with a delay,
IGNORE records and drops.  Handling defers while the executor is busy
(:342-430).  ``AnomalyDetectorState`` keeps recent-anomaly ring buffers per
type, self-healing flags, and counters for the /state endpoint
(AnomalyDetectorState.java).

Deterministic by design: ``run_detectors_once(now_ms)`` and
``handle_anomalies_once(now_ms)`` advance the loop one tick — the service
layer drives them from a scheduler thread; tests drive them directly.
"""

from __future__ import annotations

import dataclasses
import heapq
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.common.timeseries import (HEAL_DURATION_SERIES,
                                                  HEAL_STARTED_SERIES,
                                                  TELEMETRY)
from cruise_control_tpu.common.tracing import TRACE
from cruise_control_tpu.detector.anomalies import Anomaly, AnomalyType
from cruise_control_tpu.detector.notifier import (AnomalyNotificationAction,
                                                  AnomalyNotifier, SelfHealingNotifier)


@dataclasses.dataclass
class AnomalyState:
    anomaly: Anomaly
    status: str  # DETECTED / IGNORED / FIX_STARTED / FIX_FAILED_TO_START / CHECK_WITH_DELAY / DENIED (executor busy)
    status_time_ms: int


class AnomalyDetectorState:
    """Ring buffers + counters (detector/AnomalyDetectorState.java)."""

    def __init__(self, history_size: int = 10):
        self._history: Dict[AnomalyType, deque] = {
            t: deque(maxlen=history_size) for t in AnomalyType}
        self.metrics: Dict[str, int] = {f"num_{t.name.lower()}": 0 for t in AnomalyType}
        self.ongoing_self_healing: Optional[str] = None

    def record(self, anomaly: Anomaly, status: str, now_ms: int) -> None:
        self._history[anomaly.anomaly_type].append(AnomalyState(anomaly, status, now_ms))
        if status == "DETECTED":
            self.metrics[f"num_{anomaly.anomaly_type.name.lower()}"] += 1

    def update_status(self, anomaly: Anomaly, status: str, now_ms: int) -> None:
        for st in self._history[anomaly.anomaly_type]:
            if st.anomaly.anomaly_id == anomaly.anomaly_id:
                st.status = status
                st.status_time_ms = now_ms
                return
        self.record(anomaly, status, now_ms)

    def recent(self, anomaly_type: AnomalyType) -> List[AnomalyState]:
        return list(self._history[anomaly_type])

    def to_dict(self, notifier: AnomalyNotifier,
                balancedness_score: Optional[float] = None) -> Dict[str, object]:
        return {
            # Quantifies how well the load distribution satisfies the
            # detection goals (AnomalyDetectorState.java:384); absent until a
            # GoalViolationDetector is registered.
            **({"balancednessScore": balancedness_score}
               if balancedness_score is not None else {}),
            "selfHealingEnabled": {t.name: v for t, v in
                                   notifier.self_healing_enabled().items()},
            "recentAnomalies": {
                t.name: [dict(anomalyId=s.anomaly.anomaly_id, status=s.status,
                              statusTimeMs=s.status_time_ms,
                              reason=s.anomaly.reason())
                         for s in self.recent(t)]
                for t in AnomalyType},
            "metrics": dict(self.metrics),
            "ongoingSelfHealing": self.ongoing_self_healing,
        }


@dataclasses.dataclass(order=True)
class _QueueEntry:
    priority: Tuple[int, int, int]
    anomaly: Anomaly = dataclasses.field(compare=False)
    not_before_ms: int = dataclasses.field(compare=False, default=0)


class AnomalyDetectorManager:
    def __init__(self, notifier: Optional[AnomalyNotifier] = None,
                 facade=None,
                 executor_busy: Optional[Callable[[], bool]] = None,
                 history_size: int = 10):
        self._notifier = notifier or SelfHealingNotifier()
        self._facade = facade
        self._executor_busy = executor_busy or (lambda: False)
        self.state = AnomalyDetectorState(history_size)
        self._queue: List[_QueueEntry] = []  # guarded-by: _lock
        self._lock = threading.RLock()
        # (detector, interval_ms, last_run_ms, is_multi) registered sources.
        self._detectors: List[List] = []  # guarded-by: _lock
        # Heal-pipeline sensors registered eagerly so the /metrics catalog is
        # deterministic (the per-anomaly-class rate counters stay
        # conditional — documented in prose, not table rows).
        self._heal_hist = SENSORS.histogram(
            "AnomalyDetector.heal-duration-seconds",
            help="Wall time of each self-healing fix, detection to "
                 "executor dispatch")
        self._heals_started = SENSORS.counter(
            "AnomalyDetector.heals-started",
            help="Self-healing fixes that started an execution")
        self._heals_failed = SENSORS.counter(
            "AnomalyDetector.heals-failed",
            help="Self-healing fixes that failed to start (including "
                 "exceptions raised by the fix)")

    @property
    def notifier(self) -> AnomalyNotifier:
        return self._notifier

    def balancedness_score(self) -> Optional[float]:
        """The goal-violation detector's rolling balancedness score
        (AnomalyDetectorManager.java:180 registers it as a gauge)."""
        for detector, _, _ in self._detectors:
            score = getattr(detector, "balancedness_score", None)
            if score is not None:
                return float(score)
        return None

    def state_dict(self) -> Dict[str, object]:
        """The /state AnomalyDetectorState payload."""
        return self.state.to_dict(self._notifier, self.balancedness_score())

    def register_detector(self, detector, interval_ms: int) -> None:
        """detector.detect(now_ms) -> Anomaly | list[Anomaly] | None."""
        with self._lock:
            self._detectors.append([detector, int(interval_ms), None])

    def enqueue(self, anomaly: Anomaly, now_ms: int, not_before_ms: int = 0) -> None:
        with self._lock:
            heapq.heappush(self._queue, _QueueEntry(
                priority=(int(anomaly.anomaly_type), not_before_ms, anomaly.anomaly_id),
                anomaly=anomaly, not_before_ms=not_before_ms))
            self.state.record(anomaly, "DETECTED", now_ms)

    # -- one scheduler tick --------------------------------------------------
    def run_detectors_once(self, now_ms: int) -> int:
        """Run every detector whose interval elapsed; queue findings."""
        found = 0
        for entry in self._detectors:
            detector, interval, last = entry
            if last is not None and now_ms - last < interval:
                continue
            entry[2] = now_ms
            kind = type(detector).__name__
            hist = SENSORS.histogram(
                "AnomalyDetector.detection-duration-seconds",
                labels={"detector": kind},
                help="Wall time spent in each detector's detect() call")
            with TRACE.span("detector.detect", detector=kind) as sp, hist.time():
                result = detector.detect(now_ms)
                anomalies = result if isinstance(result, list) else \
                    ([result] if result is not None else [])
                sp.annotate(anomalies=len(anomalies))
            for a in anomalies:
                self.enqueue(a, now_ms)
                found += 1
        # Detector-tick publish boundary: the finding count and the
        # goal-violation detector's rolling balancedness (a cached host
        # float — its sweep already ran inside detect()) become series
        # points stamped with the tick's own clock.
        TELEMETRY.record("detector.anomalies-found", float(found),
                         t_ms=now_ms)
        score = self.balancedness_score()
        if score is not None and score >= 0.0:
            # Negative is the offline-replicas sentinel
            # (BALANCEDNESS_SCORE_WITH_OFFLINE_REPLICAS): the score is
            # *undefined* during a failure window, not low — publishing it
            # would poison the SLA floor, so the series simply has a gap
            # there (the heal series carries the failure evidence).
            TELEMETRY.record("detector.balancedness", score, t_ms=now_ms)
        return found

    def handle_anomalies_once(self, now_ms: int) -> int:
        """Drain ready queue entries through the notifier (AnomalyHandlerTask
        loop, AnomalyDetectorManager.java:344).  Returns #handled."""
        handled = 0
        deferred: List[_QueueEntry] = []
        with self._lock:
            while self._queue:
                entry = heapq.heappop(self._queue)
                if entry.not_before_ms > now_ms:
                    deferred.append(entry)
                    continue
                handled += self._handle(entry.anomaly, now_ms)
            for entry in deferred:
                heapq.heappush(self._queue, entry)
        TELEMETRY.record("detector.anomalies-handled", float(handled),
                         t_ms=now_ms)
        return handled

    def _handle(self, anomaly: Anomaly, now_ms: int) -> int:  # holds-lock: _lock
        SENSORS.counter(
            f"AnomalyDetector.{type(anomaly).__name__}-rate",
            help="Anomalies of this type handled by the notifier").inc()
        result = self._notifier.on_anomaly(anomaly, now_ms)
        if result.action == AnomalyNotificationAction.IGNORE:
            self.state.update_status(anomaly, "IGNORED", now_ms)
            return 1
        if result.action == AnomalyNotificationAction.CHECK:
            self.state.update_status(anomaly, "CHECK_WITH_DELAY", now_ms)
            heapq.heappush(self._queue, _QueueEntry(
                priority=(int(anomaly.anomaly_type),
                          now_ms + result.delay_ms, anomaly.anomaly_id),
                anomaly=anomaly, not_before_ms=now_ms + result.delay_ms))
            return 1
        # FIX — defer while an execution is in flight (:342-430).
        if self._executor_busy():
            self.state.update_status(anomaly, "DENIED", now_ms)
            heapq.heappush(self._queue, _QueueEntry(
                priority=(int(anomaly.anomaly_type), now_ms + 30_000,
                          anomaly.anomaly_id),
                anomaly=anomaly, not_before_ms=now_ms + 30_000))
            return 1
        started = False
        if self._facade is not None:
            heal_t0 = time.monotonic()
            self.state.ongoing_self_healing = anomaly.reason()
            # A raising fix() must behave like a failed one: clear the
            # ongoing flag, record FIX_FAILED_TO_START, and keep draining
            # the queue — the drain loop holds the manager lock, so a
            # propagating exception would wedge every later detection.
            with TRACE.span("detector.heal",
                            anomaly=type(anomaly).__name__) as sp, \
                    self._heal_hist.time():
                try:
                    started = bool(anomaly.fix(self._facade))
                except Exception as exc:  # noqa: BLE001
                    sp.annotate(error=type(exc).__name__)
                finally:
                    self.state.ongoing_self_healing = None
                sp.annotate(started=started)
            (self._heals_started if started else self._heals_failed).inc()
            # Heal publish boundary: latency (detect→dispatch wall, the
            # same value the heal histogram observed) and the outcome flag
            # the SLA rollup's all-heals-completed check reads.
            TELEMETRY.record(HEAL_DURATION_SERIES,
                             time.monotonic() - heal_t0, t_ms=now_ms)
            TELEMETRY.record(HEAL_STARTED_SERIES,
                             1.0 if started else 0.0, t_ms=now_ms)
        self.state.update_status(
            anomaly, "FIX_STARTED" if started else "FIX_FAILED_TO_START", now_ms)
        return 1
