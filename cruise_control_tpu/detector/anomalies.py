"""Anomaly types.

Parity with the reference's anomaly hierarchy (detector/*.java):
``KafkaAnomaly`` base with typed subclasses — ``BrokerFailures``,
``DiskFailures``, ``GoalViolations``, ``SlowBrokers`` (metric anomaly),
``TopicReplicationFactorAnomaly`` / ``TopicPartitionSizeAnomaly``,
``MaintenanceEvent`` — each carrying enough context for its ``fix()`` to
run the matching self-healing operation through the facade (the reference
delegates to servlet runnables: RemoveBrokersRunnable, RebalanceRunnable,
FixOfflineReplicasRunnable, DemoteBrokerRunnable — GoalViolations.java:84).
Anomaly priority drives the handler queue (AnomalyType ordinals,
notifier/AnomalyType.java: broker failure first).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import Dict, List, Optional, Sequence, Tuple


class AnomalyType(enum.IntEnum):
    """Priority order — lower value handled first
    (detector/notifier/KafkaAnomalyType.java)."""

    BROKER_FAILURE = 0
    DISK_FAILURE = 1
    METRIC_ANOMALY = 2
    GOAL_VIOLATION = 3
    TOPIC_ANOMALY = 4
    MAINTENANCE_EVENT = 5


_ids = itertools.count()


@dataclasses.dataclass
class Anomaly:
    """Base anomaly (core detector/Anomaly SPI + KafkaAnomaly)."""

    detection_time_ms: int
    anomaly_id: int = dataclasses.field(default_factory=lambda: next(_ids))

    @property
    def anomaly_type(self) -> AnomalyType:
        raise NotImplementedError

    def fix(self, context) -> bool:
        """Run the self-healing operation; returns True if a fix started.
        ``context`` is the CruiseControl facade."""
        raise NotImplementedError

    def reason(self) -> str:
        return self.__class__.__name__

    def to_dict(self) -> Dict[str, object]:
        return {"anomalyId": self.anomaly_id, "type": self.anomaly_type.name,
                "detectionTimeMs": self.detection_time_ms, "reason": self.reason()}


@dataclasses.dataclass
class BrokerFailures(Anomaly):
    """detector/BrokerFailures: brokers gone from the cluster."""

    failed_brokers: Dict[int, int] = dataclasses.field(default_factory=dict)  # id → failure time
    fix_by_removal: bool = True

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.BROKER_FAILURE

    def reason(self) -> str:
        return f"Broker failures detected: {sorted(self.failed_brokers)}"

    def fix(self, context) -> bool:
        if not self.failed_brokers:
            return False
        return context.remove_brokers(sorted(self.failed_brokers),
                                      reason=self.reason(), self_healing=True)


@dataclasses.dataclass
class DiskFailures(Anomaly):
    """detector/DiskFailures: offline logdirs on live brokers."""

    failed_disks: Dict[int, Tuple[str, ...]] = dataclasses.field(default_factory=dict)

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.DISK_FAILURE

    def reason(self) -> str:
        return f"Disk failures detected: {self.failed_disks}"

    def fix(self, context) -> bool:
        return context.fix_offline_replicas(reason=self.reason(),
                                            self_healing=True)


@dataclasses.dataclass
class GoalViolations(Anomaly):
    """detector/GoalViolations.java: fixable/unfixable violated goals."""

    fixable_goals: List[str] = dataclasses.field(default_factory=list)
    unfixable_goals: List[str] = dataclasses.field(default_factory=list)

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.GOAL_VIOLATION

    def reason(self) -> str:
        return (f"Goal violations: fixable={self.fixable_goals} "
                f"unfixable={self.unfixable_goals}")

    def fix(self, context) -> bool:
        if not self.fixable_goals:
            return False
        # Heal with the FULL configured stack, not just the violated goals:
        # a solve constrained only by the violated goal is free to break the
        # rest of the stack (e.g. a DiskCapacityGoal-only fix un-racks
        # replicas), turning one violation into a detect→fix flap.  The
        # reference's GOAL_VIOLATION self-healing likewise runs the
        # configured self-healing goals, which default to the whole stack.
        return context.rebalance(reason=self.reason(), self_healing=True)


@dataclasses.dataclass
class SlowBrokers(Anomaly):
    """detector/SlowBrokers (a metric anomaly): broker → slowness score;
    escalation: demote first, remove persistent offenders
    (SlowBrokerFinder.java:33-105)."""

    slow_brokers: Dict[int, float] = dataclasses.field(default_factory=dict)
    fix_by_removal: bool = False

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.METRIC_ANOMALY

    def reason(self) -> str:
        action = "remove" if self.fix_by_removal else "demote"
        return f"Slow brokers ({action}): {self.slow_brokers}"

    def fix(self, context) -> bool:
        brokers = sorted(self.slow_brokers)
        if not brokers:
            return False
        if self.fix_by_removal:
            return context.remove_brokers(brokers, reason=self.reason())
        return context.demote_brokers(brokers, reason=self.reason())


@dataclasses.dataclass
class TopicReplicationFactorAnomaly(Anomaly):
    """detector/TopicReplicationFactorAnomaly: topics off the desired RF."""

    bad_topics: Dict[str, int] = dataclasses.field(default_factory=dict)  # topic → current RF
    desired_rf: int = 3

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.TOPIC_ANOMALY

    def reason(self) -> str:
        return f"Topics violating RF={self.desired_rf}: {self.bad_topics}"

    def fix(self, context) -> bool:
        if not self.bad_topics:
            return False
        return context.update_topic_replication_factor(
            dict.fromkeys(self.bad_topics, self.desired_rf), reason=self.reason())


@dataclasses.dataclass
class TopicPartitionSizeAnomaly(Anomaly):
    """detector/TopicPartitionSizeAnomaly: oversized partitions (report-only)."""

    oversized: Dict[str, float] = dataclasses.field(default_factory=dict)
    size_threshold_mb: float = 1024.0

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.TOPIC_ANOMALY

    def reason(self) -> str:
        return f"Partitions above {self.size_threshold_mb} MB: {sorted(self.oversized)}"

    def fix(self, context) -> bool:
        return False  # reference: unfixable, surfaced for operators


class MaintenancePlanType(enum.Enum):
    """detector/MaintenancePlan types (MaintenancePlan.java)."""

    ADD_BROKER = "add_broker"
    REMOVE_BROKER = "remove_broker"
    DEMOTE_BROKER = "demote_broker"
    FIX_OFFLINE_REPLICAS = "fix_offline_replicas"
    REBALANCE = "rebalance"
    TOPIC_REPLICATION_FACTOR = "topic_replication_factor"


@dataclasses.dataclass
class MaintenanceEvent(Anomaly):
    """detector/MaintenanceEvent: operator-published plan consumed from the
    maintenance topic/queue."""

    plan_type: MaintenancePlanType = MaintenancePlanType.REBALANCE
    brokers: Tuple[int, ...] = ()
    topics_rf: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def anomaly_type(self) -> AnomalyType:
        return AnomalyType.MAINTENANCE_EVENT

    def reason(self) -> str:
        return f"Maintenance plan {self.plan_type.value} brokers={list(self.brokers)}"

    def dedup_key(self) -> Tuple:
        """IdempotenceCache key (detector/IdempotenceCache.java)."""
        return (self.plan_type, self.brokers, tuple(sorted(self.topics_rf.items())))

    def fix(self, context) -> bool:
        t = self.plan_type
        if t == MaintenancePlanType.ADD_BROKER:
            return context.add_brokers(list(self.brokers), reason=self.reason())
        if t == MaintenancePlanType.REMOVE_BROKER:
            return context.remove_brokers(list(self.brokers), reason=self.reason())
        if t == MaintenancePlanType.DEMOTE_BROKER:
            return context.demote_brokers(list(self.brokers), reason=self.reason())
        if t == MaintenancePlanType.FIX_OFFLINE_REPLICAS:
            return context.fix_offline_replicas(reason=self.reason(),
                                            self_healing=True)
        if t == MaintenancePlanType.TOPIC_REPLICATION_FACTOR:
            return context.update_topic_replication_factor(self.topics_rf,
                                                           reason=self.reason())
        return context.rebalance(goals=None, reason=self.reason())
