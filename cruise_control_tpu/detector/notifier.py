"""Anomaly notification / self-healing policy.

Parity with the ``AnomalyNotifier`` SPI + ``SelfHealingNotifier``
(detector/notifier/AnomalyNotifier.java, SelfHealingNotifier.java:58-80):
maps each anomaly to {FIX, CHECK(delay), IGNORE}; per-type self-healing
enable flags; broker failures get a two-stage policy — alert after
``broker_failure_alert_threshold_ms`` since the failure, self-heal only
after ``broker_failure_self_healing_threshold_ms``.  An Alerta-style hook
(AlertaSelfHealingNotifier.java) is a callback here.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, List, Optional

from cruise_control_tpu.detector.anomalies import Anomaly, AnomalyType, BrokerFailures


class AnomalyNotificationAction(enum.Enum):
    FIX = "fix"
    CHECK = "check"
    IGNORE = "ignore"


@dataclasses.dataclass(frozen=True)
class AnomalyNotificationResult:
    action: AnomalyNotificationAction
    delay_ms: int = 0

    @classmethod
    def fix(cls) -> "AnomalyNotificationResult":
        return cls(AnomalyNotificationAction.FIX)

    @classmethod
    def check(cls, delay_ms: int) -> "AnomalyNotificationResult":
        return cls(AnomalyNotificationAction.CHECK, delay_ms)

    @classmethod
    def ignore(cls) -> "AnomalyNotificationResult":
        return cls(AnomalyNotificationAction.IGNORE)


class AnomalyNotifier:
    """SPI: decide what to do about an anomaly."""

    def on_anomaly(self, anomaly: Anomaly, now_ms: int) -> AnomalyNotificationResult:
        raise NotImplementedError

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return {t: False for t in AnomalyType}

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        return False


class SelfHealingNotifier(AnomalyNotifier):
    """SelfHealingNotifier.java semantics."""

    def __init__(self,
                 self_healing_enabled: Optional[Dict[AnomalyType, bool]] = None,
                 broker_failure_alert_threshold_ms: int = 15 * 60 * 1000,
                 broker_failure_self_healing_threshold_ms: int = 30 * 60 * 1000,
                 alert_hook: Optional[Callable[[Anomaly, bool], None]] = None):
        enabled = dict.fromkeys(AnomalyType, False)
        enabled.update(self_healing_enabled or {})
        self._enabled = enabled
        self._alert_ms = broker_failure_alert_threshold_ms
        self._heal_ms = broker_failure_self_healing_threshold_ms
        self._alert_hook = alert_hook
        self.alerts: List[Anomaly] = []

    def configure(self, config: Dict[str, object]) -> None:
        """Plugin-style init (anomaly.notifier.class): reads the
        broker-failure alert/self-heal thresholds and the master
        self-healing switch from the merged config."""
        from cruise_control_tpu.config import constants as C
        if C.BROKER_FAILURE_ALERT_THRESHOLD_MS_CONFIG in config:
            self._alert_ms = int(config[C.BROKER_FAILURE_ALERT_THRESHOLD_MS_CONFIG])
        if C.BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS_CONFIG in config:
            self._heal_ms = int(
                config[C.BROKER_FAILURE_SELF_HEALING_THRESHOLD_MS_CONFIG])
        if config.get(C.SELF_HEALING_ENABLED_CONFIG):
            self._enabled = dict.fromkeys(AnomalyType, True)

    def self_healing_enabled(self) -> Dict[AnomalyType, bool]:
        return dict(self._enabled)

    def set_self_healing_for(self, anomaly_type: AnomalyType, enabled: bool) -> bool:
        old = self._enabled[anomaly_type]
        self._enabled[anomaly_type] = enabled
        return old

    def _alert(self, anomaly: Anomaly, auto_fix: bool) -> None:
        self.alerts.append(anomaly)
        if self._alert_hook:
            self._alert_hook(anomaly, auto_fix)

    def on_anomaly(self, anomaly: Anomaly, now_ms: int) -> AnomalyNotificationResult:
        t = anomaly.anomaly_type
        if t == AnomalyType.BROKER_FAILURE:
            return self._on_broker_failure(anomaly, now_ms)
        if not self._enabled[t]:
            self._alert(anomaly, auto_fix=False)
            return AnomalyNotificationResult.ignore()
        self._alert(anomaly, auto_fix=True)
        return AnomalyNotificationResult.fix()

    def _on_broker_failure(self, anomaly: BrokerFailures,
                           now_ms: int) -> AnomalyNotificationResult:
        """Two-stage policy (SelfHealingNotifier.onBrokerFailure): wait out
        the alert threshold (transient restarts), then the self-heal
        threshold, measured from the *earliest* still-failed broker."""
        if not anomaly.failed_brokers:
            return AnomalyNotificationResult.ignore()
        earliest = min(anomaly.failed_brokers.values())
        if now_ms < earliest + self._alert_ms:
            return AnomalyNotificationResult.check(earliest + self._alert_ms - now_ms)
        if not self._enabled[AnomalyType.BROKER_FAILURE]:
            self._alert(anomaly, auto_fix=False)
            return AnomalyNotificationResult.ignore()
        if now_ms < earliest + self._heal_ms:
            self._alert(anomaly, auto_fix=False)
            return AnomalyNotificationResult.check(earliest + self._heal_ms - now_ms)
        self._alert(anomaly, auto_fix=True)
        return AnomalyNotificationResult.fix()


class AlertaSelfHealingNotifier(SelfHealingNotifier):
    """SelfHealingNotifier that additionally posts every alert to an
    Alerta.io endpoint (detector/notifier/AlertaSelfHealingNotifier.java:
    POST {api_url}/alert with an Authorization: Key header; severity maps
    from whether self-healing will fire)."""

    def __init__(self, api_url: str, api_key: str = "",
                 environment: str = "Production", origin: str = "cruise-control",
                 http_post: Optional[Callable[[str, Dict, Dict], None]] = None,
                 **kwargs):
        super().__init__(**kwargs)
        self._api_url = api_url.rstrip("/")
        self._api_key = api_key
        self._environment = environment
        self._origin = origin
        self._http_post = http_post or self._default_post
        self.post_failures = 0

    @staticmethod
    def _default_post(url: str, payload: Dict, headers: Dict) -> None:
        import json as _json
        import urllib.request
        req = urllib.request.Request(
            url, data=_json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **headers},
            method="POST")
        urllib.request.urlopen(req, timeout=10)

    def _alert(self, anomaly: Anomaly, auto_fix: bool) -> None:
        super()._alert(anomaly, auto_fix)
        payload = {
            "resource": anomaly.anomaly_type.name,
            "event": type(anomaly).__name__,
            "environment": self._environment,
            "severity": "warning" if auto_fix else "critical",
            "service": ["cruise-control-tpu"],
            "origin": self._origin,
            "text": anomaly.reason(),
            "attributes": {"selfHealing": auto_fix,
                           "anomalyId": anomaly.anomaly_id},
        }
        headers = {"Authorization": f"Key {self._api_key}"} if self._api_key else {}
        try:
            self._http_post(f"{self._api_url}/alert", payload, headers)
        except Exception:  # noqa: BLE001 — alerting must never break detection
            self.post_failures += 1
