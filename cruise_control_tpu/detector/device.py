"""Tensor-native anomaly detection: the whole fleet scored per tick as ONE
batched device program.

The scalar finders in ``detector/detectors.py`` walk brokers in Python and
call ``np.percentile`` per row — fine at 5 brokers, hopeless at 7,000.  This
module keeps their exact semantics (they remain the oracle, see below) but
vectorizes the hot scoring path over the load monitor's
(broker × window × metric) history tensor:

- ``DeviceScorer`` runs one jitted program per aggregation generation that
  answers BOTH finder families at once — percentile-excursion flags/ratios
  for the metric-anomaly finder and own-history ∧ peer-anchor suspect flags
  for the slow-broker finder.  Variable-length valid-window histories are
  handled by a masked sort-based percentile that reproduces numpy's linear
  interpolation exactly, so host and device agree bit-for-bit on engineered
  integer histories.
- ``DeviceMetricAnomalyFinder`` / ``DeviceSlowBrokerFinder`` subclass their
  scalar counterparts and override only the flagging stage; streak/score
  escalation, systemic guards, and ``configure()`` are inherited unchanged.
- ``DeviceGoalViolationDetector`` answers "which goals are violated" with
  the fused stack-satisfied sweep from ``analyzer/optimizer.py`` — one
  dispatch for the whole detection stack (the exact confirm-sweep machinery
  cruise mode uses on standing proposals), instead of one kernel dispatch
  per goal.

``CRUISE_DETECTOR_ORACLE=1`` makes every device flagging pass re-run the
scalar oracle on the same aggregate and raise on any divergence — the same
differential-harness pattern as ``CRUISE_REPAIR_ORACLE``.

Dispatch economy is observable: ``DEVICE_COUNTERS["dispatches"]`` counts
compiled scoring dispatches (one per generation regardless of fleet size —
pinned by tests/test_device_detector.py) and both finder families sharing
one ``DeviceScorer`` share the dispatch.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.detector.detectors import (GoalViolationDetector,
                                                   PercentileMetricAnomalyFinder,
                                                   SlowBrokerFinder)
from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF

#: Compiled scoring dispatches (module counter, FETCH_COUNTERS-style).
DEVICE_COUNTERS = {"dispatches": 0}


def oracle_enabled() -> bool:
    return os.environ.get("CRUISE_DETECTOR_ORACLE", "0") == "1"


def _masked_percentile(x, valid, pct):
    """Row-wise ``np.percentile(x[row][valid[row]], pct)`` (linear
    interpolation) without a Python loop: invalid entries sort to the top as
    +inf, the fractional rank indexes only the first ``n_valid`` slots.
    Rows with zero valid entries return 0 (callers mask them out)."""
    big = jnp.asarray(jnp.finfo(jnp.float32).max, x.dtype)
    xs = jnp.sort(jnp.where(valid, x, big), axis=1)
    n = valid.sum(axis=1)
    rank = (pct / 100.0) * jnp.maximum(n - 1, 0).astype(x.dtype)
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(n - 1, 0))
    frac = rank - lo.astype(x.dtype)
    x_lo = jnp.take_along_axis(xs, lo[:, None], axis=1)[:, 0]
    x_hi = jnp.take_along_axis(xs, hi[:, None], axis=1)[:, 0]
    return jnp.where(n > 0, x_lo + frac * (x_hi - x_lo), jnp.zeros_like(x_lo))


def _device_scores(vals, bts, wvalid, *, a_pct, a_margin, pct, hist_margin,
                   peer_pct, peer_margin, min_bytes, min_flush):
    """The one-dispatch fleet scorer: metric-anomaly excursion flags/ratios
    AND slow-broker suspect flags over f32[E, W] history slices.

    Mirrors ``PercentileMetricAnomalyFinder.anomalies`` and
    ``SlowBrokerFinder._suspects`` element-for-element — any semantic change
    here must keep the ``CRUISE_DETECTOR_ORACLE=1`` differential green."""
    latest = vals[:, -1]
    latest_valid = wvalid[:, -1]
    hist_valid = wvalid[:, :-1]
    has_hist = hist_valid.any(axis=1)
    scorable = latest_valid & has_hist

    # Metric anomaly: latest exceeds own-history percentile × margin.
    a_thr = _masked_percentile(vals[:, :-1], hist_valid, a_pct) * a_margin
    a_flag = scorable & (latest > a_thr) & (latest > 0)
    a_ratio = latest / jnp.maximum(a_thr, 1e-9)

    # Slow broker: raw AND bytes-normalized flush above own history, plus
    # the peer anchor (percentile of all valid latest values) × margin.
    b = jnp.maximum(bts, 1e-9)
    norm = vals / b
    raw_hist = _masked_percentile(vals[:, :-1], hist_valid, pct)
    norm_hist = _masked_percentile(norm[:, :-1], hist_valid, pct)
    peer = _masked_percentile(latest[None, :], latest_valid[None, :],
                              peer_pct)[0]
    own_slow = (latest > raw_hist * hist_margin) \
        & (norm[:, -1] > norm_hist * hist_margin)
    floors = (b[:, -1] >= min_bytes) & (latest >= min_flush)
    peer_slow = (peer > 0) & (latest > peer * peer_margin)
    suspect = scorable & floors & own_slow & peer_slow
    return a_flag, a_ratio, suspect


_PARAM_NAMES = ("a_pct", "a_margin", "pct", "hist_margin", "peer_pct",
                "peer_margin", "min_bytes", "min_flush")
_score_cache: Dict[Tuple[float, ...], object] = {}
_gauge_fn = lambda: DEVICE_COUNTERS["dispatches"]  # noqa: E731 — stable
# callback identity so repeat registrations are recognized as the same one


def _register_dispatch_gauge() -> None:
    SENSORS.gauge("AnomalyDetector.device-score-dispatches", fn=_gauge_fn,
                  help="Compiled device scoring dispatches (one per "
                       "aggregation generation, fleet-size independent)")


def _get_score_fn(params: Tuple[float, ...]):
    """jit-cached scorer per threshold tuple (mirrors ``_get_sweep_fn``):
    thresholds are config-static, so baking them in keeps the compiled
    program branch-free and the cache key tiny."""
    fn = _score_cache.get(params)
    if fn is None:
        fn = jax.jit(partial(_device_scores,
                             **dict(zip(_PARAM_NAMES, params))))
        _score_cache[params] = fn
    return fn


class DeviceScorer:
    """Shared per-tick scorer: one dispatch per (generation, thresholds),
    consumed by both device finder families.

    Holds the merged threshold set — finders sync their configured values in
    before each read — and caches the fetched host arrays keyed on the
    aggregator generation, so two finders scoring the same tick share one
    compiled dispatch and one device fetch."""

    def __init__(self):
        # Metric-anomaly thresholds (PercentileMetricAnomalyFinder).
        self.a_pct, self.a_margin = 95.0, 1.5
        # Slow-broker thresholds (SlowBrokerFinder).
        self.pct, self.hist_margin = 90.0, 3.0
        self.peer_pct, self.peer_margin = 50.0, 3.0
        self.min_bytes, self.min_flush = 0.0, 0.0
        self._cache: Optional[Tuple] = None
        _register_dispatch_gauge()

    def _params(self) -> Tuple[float, ...]:
        return (float(self.a_pct), float(self.a_margin), float(self.pct),
                float(self.hist_margin), float(self.peer_pct),
                float(self.peer_margin), float(self.min_bytes),
                float(self.min_flush))

    def scores(self, res, mid: int, bytes_mid: int):
        """Score an ``AggregationResult`` → host dict of per-broker arrays.
        ``res.generation`` keys the cache: re-reads within one tick are
        free, a new window invalidates."""
        key = (res.generation, self._params(), res.values.shape, mid,
               bytes_mid)
        if self._cache is not None and self._cache[0] == key:
            return self._cache[1]
        vals = jnp.asarray(res.values[:, :, mid])
        bts = jnp.asarray(res.values[:, :, bytes_mid])
        wvalid = jnp.asarray(res.window_valid)
        fn = _get_score_fn(self._params())
        DEVICE_COUNTERS["dispatches"] += 1
        a_flag, a_ratio, suspect = jax.device_get(fn(vals, bts, wvalid))
        out = {"metric_flag": a_flag, "metric_ratio": a_ratio,
               "suspect": suspect}
        self._cache = (key, out)
        return out


class DeviceMetricAnomalyFinder(PercentileMetricAnomalyFinder):
    """Batched ``PercentileMetricAnomalyFinder``: identical detect()
    escalation (streaks, systemic guard) over device-computed flags."""

    def __init__(self, *args, scorer: Optional[DeviceScorer] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._scorer = scorer or DeviceScorer()

    def anomalies(self, broker_agg) -> Dict[int, float]:
        res = broker_agg.aggregate()
        if res.values.shape[1] < 3 or res.values.shape[0] == 0:
            return {}
        self._scorer.a_pct, self._scorer.a_margin = self._pct, self._margin
        mid = KAFKA_METRIC_DEF.metric_info(self.metric).metric_id
        bmid = KAFKA_METRIC_DEF.metric_info(
            SlowBrokerFinder.BYTES_METRIC).metric_id
        s = self._scorer.scores(res, mid, bmid)
        out = {int(broker): float(s["metric_ratio"][row])
               for row, broker in enumerate(res.entities)
               if s["metric_flag"][row]}
        if oracle_enabled():
            want = super().anomalies(broker_agg)
            if set(want) != set(out):
                raise AssertionError(
                    f"device metric-anomaly flags {sorted(out)} diverge "
                    f"from scalar oracle {sorted(want)}")
        return out


class DeviceSlowBrokerFinder(SlowBrokerFinder):
    """Batched ``SlowBrokerFinder``: identical score escalation
    (demote/removal thresholds, systemic guard) over device suspects."""

    def __init__(self, *args, scorer: Optional[DeviceScorer] = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self._scorer = scorer or DeviceScorer()

    def _suspects(self, res, mid: int, bytes_mid: int) -> Set[int]:
        sc = self._scorer
        sc.pct, sc.hist_margin = self._pct, self._hist_margin
        sc.peer_pct, sc.peer_margin = self._peer_pct, self._peer_margin
        sc.min_bytes, sc.min_flush = self._min_bytes_in, self._min_flush_ms
        s = sc.scores(res, mid, bytes_mid)
        out = {int(broker) for row, broker in enumerate(res.entities)
               if s["suspect"][row]}
        if oracle_enabled():
            want = super()._suspects(res, mid, bytes_mid)
            if want != out:
                raise AssertionError(
                    f"device slow-broker suspects {sorted(out)} diverge "
                    f"from scalar oracle {sorted(want)}")
        return out


def build_device_finders(config: Optional[Dict[str, object]] = None):
    """The default device finder pair sharing ONE scorer (and therefore one
    scoring dispatch per tick); ``app._build`` registers these under
    ``MetricAnomalyDetector`` when ``anomaly.detector.device.scoring`` is
    on."""
    scorer = DeviceScorer()
    metric = DeviceMetricAnomalyFinder(scorer=scorer)
    slow = DeviceSlowBrokerFinder(scorer=scorer)
    if config:
        metric.configure(config)
        slow.configure(config)
    return metric, slow


class DeviceGoalViolationDetector(GoalViolationDetector):
    """Goal-violation detection through the fused stack-satisfied sweep.

    The scalar parent costs one ``kernels.goal_satisfied`` dispatch per
    detection goal plus a separate offline-replica fetch; this subclass
    reuses ``optimizer._get_sweep_fn`` — the PR-8 standing-proposal confirm
    sweep — so ONE dispatch returns every goal's verdict and the
    any-offline flag together."""

    def _goal_satisfactions(self, model):
        from cruise_control_tpu.analyzer import optimizer as opt
        from cruise_control_tpu.analyzer.goals.specs import goals_by_priority
        specs = tuple(goals_by_priority(self._goals))
        sweep_fn = opt._get_sweep_fn(specs, self._constraint)
        opt.SWEEP_COUNTERS["dispatches"] += 1
        sat_np, off_np = jax.device_get(sweep_fn(model))
        if bool(off_np):
            return None, True
        sat = [bool(v) for v in np.asarray(sat_np)]
        if oracle_enabled():
            want, want_off = super()._goal_satisfactions(model)
            if want != sat or want_off:
                raise AssertionError(
                    f"fused-sweep goal verdicts {sat} diverge from scalar "
                    f"oracle {want} (offline={want_off})")
        return sat, False
