"""Provisioner SPI: the cluster-rightsizing hook.

Parity with ``Provisioner`` (detector/Provisioner.java — "the interface for
adding or removing resources to/from the cluster") and its default
``NoopProvisioner``: after a goal-violation detection pass aggregates a
``ProvisionResponse``, the detector hands UNDER/OVER_PROVISIONED
recommendations to the configured provisioner, whose ``rightsize`` returns
what it did with them (GoalViolationDetector.java:160-237 →
Provisioner.rightsize).  Real deployments plug a cloud autoscaler here;
the framework ships Noop (ignore) and InMemory (record, for tests/ops
introspection).
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Dict, List, Sequence

from cruise_control_tpu.analyzer.provisioning import (ProvisionRecommendation,
                                                      ProvisionStatus)


class ProvisionerState(enum.Enum):
    """Provisioner.ProvisionerState analogue."""

    COMPLETED = "completed"
    COMPLETED_WITH_ERROR = "completed_with_error"
    IN_PROGRESS = "in_progress"
    IGNORED = "ignored"


@dataclasses.dataclass(frozen=True)
class RightsizeResult:
    state: ProvisionerState
    summary: str = ""


class Provisioner:
    """SPI: act on provisioning recommendations."""

    def rightsize(self, recommendations: Sequence[ProvisionRecommendation]
                  ) -> RightsizeResult:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NoopProvisioner(Provisioner):
    """Default: acknowledge and ignore (detector/NoopProvisioner)."""

    def rightsize(self, recommendations: Sequence[ProvisionRecommendation]
                  ) -> RightsizeResult:
        return RightsizeResult(ProvisionerState.IGNORED,
                               f"ignored {len(recommendations)} recommendation(s)")


class InMemoryProvisioner(Provisioner):
    """Records every rightsize request; tests and /state introspection read
    ``history`` — the in-memory analogue of a cloud autoscaler binding."""

    def __init__(self):
        self._lock = threading.Lock()
        self.history: List[List[ProvisionRecommendation]] = []

    def rightsize(self, recommendations: Sequence[ProvisionRecommendation]
                  ) -> RightsizeResult:
        recs = list(recommendations)
        with self._lock:
            self.history.append(recs)
        under = sum(1 for r in recs
                    if r.status == ProvisionStatus.UNDER_PROVISIONED)
        over = sum(1 for r in recs
                   if r.status == ProvisionStatus.OVER_PROVISIONED)
        return RightsizeResult(
            ProvisionerState.COMPLETED,
            f"recorded {under} under-provisioned / {over} over-provisioned")
