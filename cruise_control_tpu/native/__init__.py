"""ctypes bindings for the native kernels, with build-on-first-use.

pybind11 is not available in this environment; the C++ side exposes a plain
C ABI (cc_native.cpp) and is compiled once with g++ into a cached shared
library.  Every entry point has a pure-Python/numpy fallback — ``available()``
reports whether the native path loaded, and callers fall back transparently.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "cc_native.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build_dir() -> str:
    d = os.environ.get("CC_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "cruise_control_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        try:
            with open(_SRC, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
            so = os.path.join(_build_dir(), f"cc_native-{digest}.so")
            if not os.path.exists(so):
                tmp = so + ".tmp"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", tmp, _SRC],
                    check=True, capture_output=True)
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
            lib.build_partition_replicas.restype = ctypes.c_int32
            lib.build_partition_replicas.argtypes = [
                _i32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                _i32p, _i32p]
            lib.diff_partitions.restype = ctypes.c_int64
            lib.diff_partitions.argtypes = [
                _i32p, ctypes.c_int64, ctypes.c_int64,
                _i32p, _i32p, _i32p, _i32p, _u8p, _u8p,
                _i32p, _i32p, _i32p, _i32p, _i32p]
            lib.ingest_samples.restype = None
            lib.ingest_samples.argtypes = [
                _f64p, _f64p, _f64p, _i64p, _i64p,
                ctypes.c_int64, ctypes.c_int64,
                _i64p, _i64p, _i64p, _f64p, _u8p, ctypes.c_int64]
            lib.crc32c_update.restype = ctypes.c_uint32
            lib.crc32c_update.argtypes = [
                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_int64]
            _LIB = lib
        except (OSError, subprocess.CalledProcessError):
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def build_partition_replicas(replica_partition: np.ndarray, num_partitions: int,
                             max_rf: int) -> np.ndarray:
    """[P, max_rf] replica-id table (-1 pad); native with numpy fallback."""
    r = int(replica_partition.shape[0])
    lib = _load()
    if lib is not None and r:
        out = np.full((num_partitions, max_rf), -1, np.int32)
        scratch = np.zeros(num_partitions, np.int32)
        rp = np.ascontiguousarray(replica_partition, np.int32)
        rc = lib.build_partition_replicas(rp, r, num_partitions, max_rf, out, scratch)
        if rc >= 0:
            return out
    out = np.full((num_partitions, max_rf), -1, np.int32)
    slot = np.zeros(num_partitions, np.int64)
    for i in range(r):
        p = replica_partition[i]
        out[p, slot[p]] = i
        slot[p] += 1
    return out


def diff_partitions(partition_replicas: np.ndarray,
                    rb0, rb1, rd0, rd1, ld0, ld1):
    """Native proposal diff.  Returns (changed_part_ids, old_brokers,
    new_brokers, old_disks, new_disks) trimmed to the changed rows, or None
    when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    p, max_rf = partition_replicas.shape
    pr = np.ascontiguousarray(partition_replicas, np.int32)
    changed = np.empty(p, np.int32)
    ob = np.empty((p, max_rf), np.int32)
    nb = np.empty((p, max_rf), np.int32)
    od = np.empty((p, max_rf), np.int32)
    nd = np.empty((p, max_rf), np.int32)
    n = lib.diff_partitions(
        pr, p, max_rf,
        np.ascontiguousarray(rb0, np.int32), np.ascontiguousarray(rb1, np.int32),
        np.ascontiguousarray(rd0, np.int32), np.ascontiguousarray(rd1, np.int32),
        np.ascontiguousarray(ld0, np.uint8), np.ascontiguousarray(ld1, np.uint8),
        changed, ob, nb, od, nd)
    return changed[:n].copy(), ob[:n].copy(), nb[:n].copy(), od[:n].copy(), nd[:n].copy()


def ingest_samples(sum_arr, max_arr, latest_arr, latest_ts, count,
                   rows, slots, times_ms, values, value_mask) -> bool:
    """Batched aggregator ingestion; returns False if native is unavailable
    (caller then takes the per-sample Python path)."""
    lib = _load()
    if lib is None:
        return False
    cap, w1, m = sum_arr.shape
    lib.ingest_samples(
        sum_arr.reshape(-1), max_arr.reshape(-1), latest_arr.reshape(-1),
        latest_ts.reshape(-1), count.reshape(-1), w1, m,
        np.ascontiguousarray(rows, np.int64), np.ascontiguousarray(slots, np.int64),
        np.ascontiguousarray(times_ms, np.int64),
        np.ascontiguousarray(values, np.float64),
        np.ascontiguousarray(value_mask, np.uint8),
        int(rows.shape[0]))
    return True


def crc32c(data: bytes, crc: int = 0) -> Optional[int]:
    """CRC-32C via the native slicing-by-8 kernel; None when unavailable
    (callers fall back to the Python table loop)."""
    lib = _load()
    if lib is None:
        return None
    return int(lib.crc32c_update(crc, data, len(data)))
