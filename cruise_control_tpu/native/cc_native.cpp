// Native kernels for the host-bound hot paths (SURVEY.md §7 item 7).
//
// The TPU owns candidate scoring; these cover the CPU-side work that scales
// with the replica axis and is Python-loop-bound at the 1M-replica ladder
// rung (the reference's "native obligation" attaches to the optimizer core
// rather than ported code — there is no native code anywhere in the
// reference, SURVEY.md "Languages"):
//
//   1. build_partition_replicas — the partition → replica-id table that
//      model construction needs (tensor_model.build_model), O(R).
//   2. diff_partitions — the proposal diff over initial vs final
//      placements (analyzer/proposals.diff; AnalyzerUtils.getDiff
//      analogue), O(P · max_rf).
//   3. ingest_samples — batched aggregator ingestion (sum/max/latest/count
//      ring-buffer update; aggregator/RawMetricValues addSample hot loop),
//      O(samples · metrics).
//
// Plain C ABI (ctypes binding — pybind11 is not available in this image).
// All buffers are caller-allocated numpy arrays; no allocation happens here.

#include <cstdint>
#include <cstring>

extern "C" {

// 1. partition→replica table.  out[P * max_rf] pre-filled with -1.
//    Returns max replication factor actually seen (≤ max_rf), or -1 if a
//    partition exceeds max_rf slots.
int32_t build_partition_replicas(const int32_t* replica_partition, int64_t num_replicas,
                                 int64_t num_partitions, int64_t max_rf,
                                 int32_t* out, int32_t* slot_scratch /* P zeros */) {
    int32_t seen_rf = 0;
    for (int64_t i = 0; i < num_replicas; ++i) {
        int32_t p = replica_partition[i];
        if (p < 0 || p >= num_partitions) return -1;
        int32_t s = slot_scratch[p]++;
        if (s >= max_rf) return -1;
        out[(int64_t)p * max_rf + s] = (int32_t)i;
        if (s + 1 > seen_rf) seen_rf = s + 1;
    }
    return seen_rf;
}

// 2. Proposal diff.  For each partition, compare (broker, disk, leader) of
//    its replicas between the initial and final model and emit the changed
//    partitions with ordered (leader-first) old/new broker+disk lists.
//
//    partition_replicas: [P, max_rf] replica ids (-1 pad), initial table.
//    rb0/rb1: replica→broker, rd0/rd1: replica→disk, ld0/ld1: leader flags.
//    Outputs (capacity P rows): changed_parts[P],
//    old_brokers/new_brokers/old_disks/new_disks: [P, max_rf] (-1 pad).
//    Returns the number of changed partitions.
int64_t diff_partitions(const int32_t* partition_replicas, int64_t num_partitions,
                        int64_t max_rf,
                        const int32_t* rb0, const int32_t* rb1,
                        const int32_t* rd0, const int32_t* rd1,
                        const uint8_t* ld0, const uint8_t* ld1,
                        int32_t* changed_parts,
                        int32_t* old_brokers, int32_t* new_brokers,
                        int32_t* old_disks, int32_t* new_disks) {
    int64_t n_changed = 0;
    for (int64_t p = 0; p < num_partitions; ++p) {
        const int32_t* slots = partition_replicas + p * max_rf;
        bool changed = false;
        for (int64_t s = 0; s < max_rf; ++s) {
            int32_t r = slots[s];
            if (r < 0) break;
            if (rb0[r] != rb1[r] || rd0[r] != rd1[r] || ld0[r] != ld1[r]) {
                changed = true;
                break;
            }
        }
        if (!changed) continue;
        // Emit ordered lists: leader first, then table order.
        int32_t* ob = old_brokers + n_changed * max_rf;
        int32_t* nb = new_brokers + n_changed * max_rf;
        int32_t* od = old_disks + n_changed * max_rf;
        int32_t* nd = new_disks + n_changed * max_rf;
        for (int64_t s = 0; s < max_rf; ++s) { ob[s] = nb[s] = od[s] = nd[s] = -1; }
        int64_t rf = 0;
        for (int64_t s = 0; s < max_rf; ++s) {
            if (slots[s] < 0) break;
            ++rf;
        }
        // old ordering
        int64_t lead_pos = 0;
        for (int64_t s = 0; s < rf; ++s) if (ld0[slots[s]]) { lead_pos = s; break; }
        int64_t w = 0;
        ob[w] = rb0[slots[lead_pos]]; od[w] = rd0[slots[lead_pos]]; ++w;
        for (int64_t s = 0; s < rf; ++s) {
            if (s == lead_pos) continue;
            ob[w] = rb0[slots[s]]; od[w] = rd0[slots[s]]; ++w;
        }
        // new ordering
        lead_pos = 0;
        for (int64_t s = 0; s < rf; ++s) if (ld1[slots[s]]) { lead_pos = s; break; }
        w = 0;
        nb[w] = rb1[slots[lead_pos]]; nd[w] = rd1[slots[lead_pos]]; ++w;
        for (int64_t s = 0; s < rf; ++s) {
            if (s == lead_pos) continue;
            nb[w] = rb1[slots[s]]; nd[w] = rd1[slots[s]]; ++w;
        }
        changed_parts[n_changed++] = (int32_t)p;
    }
    return n_changed;
}

// 3. Batched sample ingestion into the aggregator ring buffers.
//    Arrays are the aggregator's [cap, W+1, M] (sum/max/latest) and
//    [cap, W+1] (count, latest_ts) tensors, flattened C-order.  Each sample
//    i carries row, slot, time_ms and M metric values with a validity mask.
void ingest_samples(double* sum, double* maxv, double* latest, int64_t* latest_ts,
                    int64_t* count,
                    int64_t w1, int64_t m,
                    const int64_t* rows, const int64_t* slots,
                    const int64_t* times_ms,
                    const double* values,      // [n, m]
                    const uint8_t* value_mask, // [n, m]
                    int64_t n) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t base2 = rows[i] * w1 + slots[i];
        int64_t base3 = base2 * m;
        const double* v = values + i * m;
        const uint8_t* msk = value_mask + i * m;
        bool newest = times_ms[i] >= latest_ts[base2];
        for (int64_t j = 0; j < m; ++j) {
            if (!msk[j]) continue;
            sum[base3 + j] += v[j];
            if (v[j] > maxv[base3 + j]) maxv[base3 + j] = v[j];
            if (newest) latest[base3 + j] = v[j];
        }
        if (newest) latest_ts[base2] = times_ms[i];
        count[base2] += 1;
    }
}

}  // extern "C"

// CRC-32C (Castagnoli), slicing-by-8 — the Kafka record-batch checksum.
// The stdlib-Python table loop costs ~1 µs/byte; this runs ~1 GB/s, which
// matters on the reporter/sample-store produce/fetch path.
struct CrcTables {
    uint32_t t[8][256];
    CrcTables() {
        const uint32_t poly = 0x82F63B78u;
        for (uint32_t n = 0; n < 256; ++n) {
            uint32_t c = n;
            for (int k = 0; k < 8; ++k) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
            t[0][n] = c;
        }
        for (uint32_t n = 0; n < 256; ++n) {
            uint32_t c = t[0][n];
            for (int s = 1; s < 8; ++s) {
                c = t[0][c & 0xFF] ^ (c >> 8);
                t[s][n] = c;
            }
        }
    }
};

static const uint32_t (&crc_tables())[8][256] {
    // C++11 magic static: thread-safe one-time construction.
    static const CrcTables tables;
    return tables.t;
}

extern "C" uint32_t crc32c_update(uint32_t crc, const uint8_t* data, int64_t n) {
    const uint32_t (&kCrcTables)[8][256] = crc_tables();
    crc ^= 0xFFFFFFFFu;
    int64_t i = 0;
    for (; i + 8 <= n; i += 8) {
        uint64_t word;
        __builtin_memcpy(&word, data + i, 8);
        word ^= crc;
        crc = kCrcTables[7][word & 0xFF] ^
              kCrcTables[6][(word >> 8) & 0xFF] ^
              kCrcTables[5][(word >> 16) & 0xFF] ^
              kCrcTables[4][(word >> 24) & 0xFF] ^
              kCrcTables[3][(word >> 32) & 0xFF] ^
              kCrcTables[2][(word >> 40) & 0xFF] ^
              kCrcTables[1][(word >> 48) & 0xFF] ^
              kCrcTables[0][(word >> 56) & 0xFF];
    }
    for (; i < n; ++i)
        crc = kCrcTables[0][(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}
