"""Operational command-line tools shipped with the package."""
