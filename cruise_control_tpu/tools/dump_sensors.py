"""Dump the sensor catalog as a markdown table.

Usage: python -m cruise_control_tpu.tools.dump_sensors [--prometheus]

Boots an in-memory stack (synthetic metadata + sampler, no network, no
accelerator requirements beyond what the analyzer already needs), exercises
the API endpoints so every lazily-registered sensor family exists, then
prints the registry catalog sorted by name.  The table is what
docs/OBSERVABILITY.md's catalog section is generated from — re-run and diff
after adding sensors.

With --prometheus, prints the full /metrics exposition instead.
"""

from __future__ import annotations

import sys


def build_stack():
    """In-memory service stack mirroring tests/test_api.py::build_stack."""
    import numpy as np

    from cruise_control_tpu.api.facade import CruiseControl
    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    from cruise_control_tpu.detector.notifier import SelfHealingNotifier
    from cruise_control_tpu.executor.admin import InMemoryClusterAdmin
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.monitor.capacity import StaticCapacityResolver
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor
    from cruise_control_tpu.monitor.metadata import (BrokerInfo, ClusterMetadata,
                                                     MetadataClient, PartitionInfo)
    from cruise_control_tpu.monitor.sampling import SyntheticWorkloadSampler

    window_ms = 300_000
    rng = np.random.default_rng(19)
    num_brokers = 5
    brokers = tuple(BrokerInfo(b, rack=f"r{b % 3}", host=f"h{b}")
                    for b in range(num_brokers))
    w = np.linspace(1, 4, num_brokers)
    w /= w.sum()
    parts = []
    for t in range(3):
        for p in range(8):
            reps = tuple(int(x) for x in
                         rng.choice(num_brokers, 2, replace=False, p=w))
            parts.append(PartitionInfo(f"t{t}", p, leader=reps[0], replicas=reps))
    mc = MetadataClient(ClusterMetadata(brokers=brokers, partitions=tuple(parts)))
    lm = LoadMonitor(mc, StaticCapacityResolver(), num_partition_windows=3,
                     partition_window_ms=window_ms)
    lm.start_up()
    sampler = SyntheticWorkloadSampler()
    for wdx in range(4):
        lm.fetch_once(sampler, wdx * window_ms, wdx * window_ms + 1)
    admin = InMemoryClusterAdmin(mc, latency_polls=1)
    ex = Executor(admin, mc)
    cc = CruiseControl(lm, ex, admin,
                       goals=["RackAwareGoal", "DiskCapacityGoal",
                              "ReplicaDistributionGoal",
                              "LeaderReplicaDistributionGoal"],
                       hard_goals=["RackAwareGoal", "DiskCapacityGoal"])
    mgr = AnomalyDetectorManager(SelfHealingNotifier(), cc,
                                 executor_busy=lambda: ex.has_ongoing_execution)
    from cruise_control_tpu.detector.detectors import BrokerFailureDetector
    mgr.register_detector(BrokerFailureDetector(mc), interval_ms=1)
    return CruiseControlApi(cc, detector_manager=mgr, sampler=sampler), mgr


def exercise(api, mgr) -> None:
    """Hit enough endpoints that every sensor family registers.  The
    non-dryrun rebalance drives the executor phases (in-memory admin, so it
    completes in milliseconds); the detector tick registers the per-detector
    duration histogram."""
    for method, endpoint, query in [
        ("GET", "state", {}),
        ("GET", "load", {}),
        ("GET", "kafka_cluster_state", {}),
        ("POST", "rebalance", {"dryrun": "true", "max_wait_s": "300"}),
        ("POST", "rebalance", {"dryrun": "false", "max_wait_s": "300"}),
        ("GET", "user_tasks", {}),
        ("GET", "trace", {}),
        ("GET", "metrics", {}),
    ]:
        status, _, _ = api.handle(method, endpoint, query)
        if status >= 400:
            print(f"warning: {method} /{endpoint} -> {status}", file=sys.stderr)
    mgr.run_detectors_once(now_ms=1)


def catalog_markdown(catalog) -> str:
    lines = ["| sensor | kind | labels | prometheus family | help |",
             "|---|---|---|---|---|"]
    for entry in sorted(catalog, key=lambda e: (e["name"], e["prometheus"])):
        labels = ", ".join(entry["labels"]) if entry["labels"] else "—"
        lines.append(f"| `{entry['name']}` | {entry['kind']} | {labels} "
                     f"| `{entry['prometheus']}` | {entry['help'] or '—'} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from cruise_control_tpu.common.sensors import SENSORS

    api, mgr = build_stack()
    exercise(api, mgr)
    if "--prometheus" in argv:
        print(SENSORS.prometheus_text(), end="")
    else:
        print(catalog_markdown(SENSORS.catalog()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
