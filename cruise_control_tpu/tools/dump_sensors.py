"""Dump the sensor catalog as a markdown table; check the docs against it.

Usage: python -m cruise_control_tpu.tools.dump_sensors
           [--prometheus | --check-docs]

Boots an in-memory stack (synthetic metadata + sampler, no network, no
accelerator requirements beyond what the analyzer already needs), exercises
the API endpoints so every lazily-registered sensor family exists — one
rebalance runs with CRUISE_FLIGHT_RECORDER=1 so the flight-recorder
families register too — then prints the registry catalog sorted by name.
The table is what docs/OBSERVABILITY.md's catalog section is generated
from.

With --prometheus, prints the full /metrics exposition instead.

With --check-docs, diffs the live catalog against the table in
docs/OBSERVABILITY.md and exits non-zero on drift, both directions: a
sensor added without a docs row, a docs row whose sensor is gone, or help
text that no longer matches the code.  Families that only register under
special conditions (``GoalOptimizer.compile-ceiling-clamps`` needs the
compile ceiling to actually clamp; ``AnomalyDetector.<Class>-rate`` needs
a handled anomaly of that class — the exercise drives exactly one broker
failure through the heal pipeline, so ``BrokerFailures-rate`` and the heal
counters ARE table rows while the other class rates stay prose) are
documented in prose below the table, not as rows — the check compares
exactly what this deterministic exercise registers.
Run by tests/test_sensor_docs.py, so the docs cannot drift silently.
"""

from __future__ import annotations

import difflib
import os
import sys

DOCS_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "docs", "OBSERVABILITY.md")


def build_stack():
    """In-memory service stack mirroring tests/test_api.py::build_stack."""
    import numpy as np

    from cruise_control_tpu.api.facade import CruiseControl
    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    from cruise_control_tpu.detector.notifier import SelfHealingNotifier
    from cruise_control_tpu.executor.admin import InMemoryClusterAdmin
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.monitor.capacity import StaticCapacityResolver
    from cruise_control_tpu.monitor.load_monitor import LoadMonitor
    from cruise_control_tpu.monitor.metadata import (BrokerInfo, ClusterMetadata,
                                                     MetadataClient, PartitionInfo)
    from cruise_control_tpu.monitor.sampling import SyntheticWorkloadSampler

    window_ms = 300_000
    rng = np.random.default_rng(19)
    num_brokers = 5
    brokers = tuple(BrokerInfo(b, rack=f"r{b % 3}", host=f"h{b}")
                    for b in range(num_brokers))
    w = np.linspace(1, 4, num_brokers)
    w /= w.sum()
    parts = []
    for t in range(3):
        for p in range(8):
            reps = tuple(int(x) for x in
                         rng.choice(num_brokers, 2, replace=False, p=w))
            parts.append(PartitionInfo(f"t{t}", p, leader=reps[0], replicas=reps))
    mc = MetadataClient(ClusterMetadata(brokers=brokers, partitions=tuple(parts)))
    lm = LoadMonitor(mc, StaticCapacityResolver(), num_partition_windows=3,
                     partition_window_ms=window_ms)
    lm.start_up()
    sampler = SyntheticWorkloadSampler()
    for wdx in range(4):
        lm.fetch_once(sampler, wdx * window_ms, wdx * window_ms + 1)
    admin = InMemoryClusterAdmin(mc, latency_polls=1)
    ex = Executor(admin, mc)
    # Warm start enabled with a permissive delta threshold so the exercise
    # below deterministically drives BOTH standing-proposal outcomes (a
    # zero-delta standing hit and a delta-seeded warm solve) and their
    # sensor families register.
    cc = CruiseControl(lm, ex, admin,
                       goals=["RackAwareGoal", "DiskCapacityGoal",
                              "ReplicaDistributionGoal",
                              "LeaderReplicaDistributionGoal"],
                       hard_goals=["RackAwareGoal", "DiskCapacityGoal"],
                       warm_start_enabled=True,
                       warm_start_delta_threshold=1.0)
    # Self-healing enabled with zero thresholds so the exercise below can
    # drive one broker failure through the full heal pipeline (detect →
    # notifier FIX → warm-seeded remove) and its sensor families register.
    from cruise_control_tpu.detector.anomalies import AnomalyType
    notifier = SelfHealingNotifier(
        self_healing_enabled=dict.fromkeys(AnomalyType, True),
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0)
    mgr = AnomalyDetectorManager(notifier, cc,
                                 executor_busy=lambda: ex.has_ongoing_execution)
    from cruise_control_tpu.detector import device as dd
    from cruise_control_tpu.detector.detectors import (BrokerFailureDetector,
                                                       MetricAnomalyDetector)
    mgr.register_detector(BrokerFailureDetector(mc), interval_ms=1)
    # The tensor-native finders share one DeviceScorer, so constructing them
    # registers the device-score-dispatches gauge and one detector tick
    # scores the whole fleet in a single batched dispatch.
    scorer = dd.DeviceScorer()
    mgr.register_detector(
        MetricAnomalyDetector(lm, [dd.DeviceMetricAnomalyFinder(scorer=scorer),
                                   dd.DeviceSlowBrokerFinder(scorer=scorer)]),
        interval_ms=1)
    return CruiseControlApi(cc, detector_manager=mgr, sampler=sampler), mgr


def exercise(api, mgr) -> None:
    """Hit enough endpoints that every sensor family registers.  The
    non-dryrun rebalance drives the executor phases (in-memory admin, so it
    completes in milliseconds); the detector tick registers the per-detector
    duration histogram.  One dryrun rebalance runs with the flight recorder
    forced on (distinct query so it cannot join an earlier task) so the
    recorder's convergence sensors register; the env var is restored after."""
    for method, endpoint, query in [
        ("GET", "state", {}),
        ("GET", "load", {}),
        ("GET", "kafka_cluster_state", {}),
        ("POST", "rebalance", {"dryrun": "true", "max_wait_s": "300"}),
        ("POST", "rebalance", {"dryrun": "false", "max_wait_s": "300"}),
        ("GET", "user_tasks", {}),
        ("GET", "trace", {}),
        ("GET", "metrics", {}),
    ]:
        status, _, _ = api.handle(method, endpoint, query)
        if status >= 400:
            print(f"warning: {method} /{endpoint} -> {status}", file=sys.stderr)
    saved = os.environ.get("CRUISE_FLIGHT_RECORDER")
    os.environ["CRUISE_FLIGHT_RECORDER"] = "1"
    try:
        status, _, _ = api.handle(
            "POST", "rebalance", {"dryrun": "true", "max_wait_s": "301"})
        if status >= 400:
            print(f"warning: recorder-on rebalance -> {status}",
                  file=sys.stderr)
    finally:
        if saved is None:
            os.environ.pop("CRUISE_FLIGHT_RECORDER", None)
        else:
            os.environ["CRUISE_FLIGHT_RECORDER"] = saved
    # Standing-proposal / warm-start families.  The first proposals call
    # stores the standing entry; a metadata refresh with identical content
    # bumps the model generation without a load delta, so the next call is
    # a zero-delta standing hit (CruiseControl.standing-hits); one more
    # sampler window (from a sampler with a nudged mean — the stock one is
    # hash-stable, so a new window would be a zero delta) then perturbs the
    # loads and — with the stack's permissive delta threshold — the final
    # call runs a delta-seeded warm solve, registering the
    # GoalOptimizer.warm-start-* families.
    from cruise_control_tpu.monitor.sampling import SyntheticWorkloadSampler
    cc = api.cc
    lm = cc.load_monitor
    cc.proposals()
    lm._metadata.refresh(lm._metadata.cluster())
    cc.proposals()
    window_ms = lm.partition_aggregator.window_ms
    nudged = SyntheticWorkloadSampler(mean_nw_kb=108.0)
    # Two windows: the in-progress window is excluded from aggregation, so
    # the first nudged window only becomes visible once the second starts.
    for wdx in (4, 5):
        lm.fetch_once(nudged, wdx * window_ms, wdx * window_ms + 1)
    cc.proposals(warm=True)
    # Small simulated execution (virtual fleet, synthetic health feed):
    # registers the execution-ledger families — Executor.* progress gauges,
    # adjuster-decision counters (both directions), per-type task-duration
    # histograms — so doc drift on them fails --check-docs.
    from cruise_control_tpu.executor import simulate as sim
    model = api.cc.load_monitor.cluster_model()
    proposals = sim.sample_move_proposals(model, moves=2, leadership=1)
    sim.run_simulated_execution(model, proposals, tick_ms=200)
    # Interruptible-execution families: one journaled run against a chaos
    # admin (seeded transient failures drive the Executor.admin-retry
    # envelope), patched by a keep-everything replan round
    # (Executor.replan-*), killed mid-phase and resumed from the journal
    # (Executor.resume-*) — so every family the interruptible executor owns
    # carries exercised values, not just eager-registration zeros.
    import tempfile

    from cruise_control_tpu.executor.executor import (ReplanDirective,
                                                      SimulatedCrash)
    jp = os.path.join(tempfile.gettempdir(), "_cc_dump_sensors.journal")
    ex2, _admin2, pnames, _ = sim.build_simulated_execution(
        model, proposals, tick_ms=200, rate_bytes_per_sec=1_000_000.0,
        faults=sim.FaultInjection(transient_failure_rate=0.3, seed=5))
    try:
        ex2.execute_proposals(
            proposals, pnames, poll_interval_s=0.0,
            journal_path=jp, crash_after_polls=2,
            replanner=lambda landed, inflight: ReplanDirective(list(proposals)),
            replan_interval_polls=1)
        print("warning: interruptible exercise completed before the "
              "simulated crash", file=sys.stderr)
    except SimulatedCrash:
        ex2.resume(jp, poll_interval_s=0.0)
    try:
        os.remove(jp)
    except OSError:
        pass
    # Inter-goal pipelining families: the 5-broker stack sits far below
    # the auto-pipeline floor, so one explicitly pipelined pass registers
    # GoalOptimizer.goals-overlapped / goals-fused / pipeline-fill-ratio /
    # speculative-goal-chunks-wasted.
    from cruise_control_tpu.analyzer import optimizer as opt
    opt.optimize(model, ["ReplicaDistributionGoal",
                         "LeaderReplicaDistributionGoal"],
                 raise_on_hard_failure=False, fused=True, pipeline=True)
    # AOT prelower/shipping families: one flag-on pipelined pass
    # (CRUISE_AOT_PRELOWER is part of every dispatch-cache key, so this
    # pass AOT-lowers its own chunk executables ahead of dispatch and
    # ships the serialized artifacts into a throwaway store) — registers
    # GoalOptimizer.aot-prelowered / executables-shipped-bytes /
    # aot-dispatches.  The per-shard dispatch-economy counters
    # (boundary-fetch-bytes / mesh-collective-ops) register from the
    # pipelined passes' boundary accounting.
    import shutil
    saved_aot = os.environ.get("CRUISE_AOT_PRELOWER")
    saved_xdg = os.environ.get("XDG_CACHE_HOME")
    tmp_store = tempfile.mkdtemp(prefix="cc_dump_sensors_aot_")
    os.environ["CRUISE_AOT_PRELOWER"] = "1"
    os.environ["XDG_CACHE_HOME"] = tmp_store
    try:
        opt.optimize(model, ["ReplicaDistributionGoal"],
                     raise_on_hard_failure=False, fused=True, pipeline=True)
    finally:
        if saved_aot is None:
            os.environ.pop("CRUISE_AOT_PRELOWER", None)
        else:
            os.environ["CRUISE_AOT_PRELOWER"] = saved_aot
        if saved_xdg is None:
            os.environ.pop("XDG_CACHE_HOME", None)
        else:
            os.environ["XDG_CACHE_HOME"] = saved_xdg
        shutil.rmtree(tmp_store, ignore_errors=True)
    mgr.run_detectors_once(now_ms=1)
    # Heal pipeline: kill one broker and let the detector → notifier(FIX) →
    # facade chain run a self-healing remove.  The standing proposal from the
    # warm rebalance above seeds the heal solve, so the families this
    # registers — CruiseControl.heal-warm-solves / heal-cold-solves and the
    # AnomalyDetector.BrokerFailures-rate counter — appear deterministically
    # (the other per-anomaly-class rates stay conditional).
    import dataclasses
    mc = lm._metadata
    cluster = mc.cluster()
    victim = max(b.broker_id for b in cluster.brokers)
    mc.refresh(dataclasses.replace(cluster, brokers=tuple(
        dataclasses.replace(b, is_alive=(b.broker_id != victim))
        for b in cluster.brokers)))
    mgr.run_detectors_once(now_ms=2)
    if not mgr.handle_anomalies_once(now_ms=2):
        print("warning: heal-pipeline exercise handled no anomaly",
              file=sys.stderr)
    # Telemetry time-series store: sample the sensor bridge (which ticks
    # the store and registers the Telemetry.* accounting gauges) and answer
    # one /timeseries and one /stream read — so the store's sensor family
    # lands in the drift-checked catalog alongside the surfaces that
    # publish into it.
    from cruise_control_tpu.common.timeseries import (SENSOR_SAMPLE_FAMILIES,
                                                      TELEMETRY)
    TELEMETRY.sample_sensors(SENSOR_SAMPLE_FAMILIES)
    for method, endpoint, query in [
        ("GET", "timeseries", {}),
        ("GET", "timeseries", {"series": "detector.balancedness",
                               "window": "3600", "step": "60"}),
        ("GET", "stream", {"since": "0"}),
    ]:
        status, _, _ = api.handle(method, endpoint, query)
        if status >= 400:
            print(f"warning: {method} /{endpoint} -> {status}",
                  file=sys.stderr)


def catalog_markdown(catalog) -> str:
    lines = ["| sensor | kind | labels | prometheus family | help |",
             "|---|---|---|---|---|"]
    for entry in sorted(catalog, key=lambda e: (e["name"], e["prometheus"])):
        labels = ", ".join(entry["labels"]) if entry["labels"] else "—"
        lines.append(f"| `{entry['name']}` | {entry['kind']} | {labels} "
                     f"| `{entry['prometheus']}` | {entry['help'] or '—'} |")
    return "\n".join(lines)


def docs_table_rows(docs_path: str = DOCS_PATH) -> list:
    """The catalog table rows (``| `sensor` | ...``) from the docs, in file
    order.  Only the first markdown table in the file is the catalog."""
    rows, in_table = [], False
    with open(docs_path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line.startswith("| sensor |"):
                in_table = True
                continue
            if in_table:
                if line.startswith("|---"):
                    continue
                if not line.startswith("| `"):
                    break
                rows.append(line)
    return rows


def check_docs(catalog, docs_path: str = DOCS_PATH) -> int:
    """Diff the live (exercised) catalog against the docs table.  Returns 0
    when they match row-for-row, 1 with a unified diff on drift."""
    live = catalog_markdown(catalog).splitlines()[2:]
    docs = docs_table_rows(docs_path)
    if live == docs:
        print(f"docs catalog table matches the live registry "
              f"({len(live)} sensors)")
        return 0
    diff = difflib.unified_diff(docs, live, fromfile="docs/OBSERVABILITY.md",
                                tofile="live registry", lineterm="")
    print("sensor catalog drift between docs/OBSERVABILITY.md and the live "
          "registry — regenerate the table with\n"
          "  python -m cruise_control_tpu.tools.dump_sensors\n",
          file=sys.stderr)
    for line in diff:
        print(line, file=sys.stderr)
    return 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    from cruise_control_tpu.common.sensors import SENSORS

    api, mgr = build_stack()
    exercise(api, mgr)
    if "--prometheus" in argv:
        print(SENSORS.prometheus_text(), end="")
    elif "--check-docs" in argv:
        return check_docs(SENSORS.catalog())
    else:
        print(catalog_markdown(SENSORS.catalog()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
