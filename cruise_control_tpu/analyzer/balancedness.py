"""Per-goal balancedness scoring.

Parity with ``KafkaCruiseControlUtils.balancednessCostByGoal``
(KafkaCruiseControlUtils.java:694): each goal in the priority-ordered stack
carries a violation *cost*; costs decay geometrically with priority
position (one level higher priority ⇒ ``priority_weight``× the cost) and
hard goals weigh ``strictness_weight``× more than soft goals.  Costs are
normalized so the full stack sums to ``MAX_BALANCEDNESS_SCORE`` (100): a
cluster violating nothing scores 100, violating everything scores 0.

The score surfaces in two places, matching the reference:

- ``OptimizerRun.balancedness_before/_after`` (OptimizerResult.java:117-118
  ``onDemandBalancednessScoreBefore/After``);
- the goal-violation detector's rolling score in the anomaly-detector
  /state payload (GoalViolationDetector.java:106 → AnomalyDetectorState
  ``balancednessScore``), pinned to ``-1.0`` while offline replicas exist
  (GoalViolationDetector.java:69,281 — failure detectors own that state).
"""

from __future__ import annotations

from typing import Dict, Iterable, Sequence

MAX_BALANCEDNESS_SCORE = 100.0
# Sentinel while offline replicas exist (GoalViolationDetector.java:69).
BALANCEDNESS_SCORE_WITH_OFFLINE_REPLICAS = -1.0

DEFAULT_PRIORITY_WEIGHT = 1.1
DEFAULT_STRICTNESS_WEIGHT = 1.5


def balancedness_cost_by_goal(goals: Sequence, priority_weight: float = DEFAULT_PRIORITY_WEIGHT,
                              strictness_weight: float = DEFAULT_STRICTNESS_WEIGHT
                              ) -> Dict[str, float]:
    """Violation cost per goal name; costs sum to MAX_BALANCEDNESS_SCORE.

    ``goals`` is the priority-ordered stack of GoalSpecs (highest priority
    first, as the optimizer runs them).  Mirrors the two-step weight/cost
    computation of KafkaCruiseControlUtils.java:694-719.
    """
    if not goals:
        raise ValueError("at least one goal is required for balancedness costs")
    if priority_weight <= 0 or strictness_weight <= 0:
        raise ValueError(
            f"balancedness weights must be positive "
            f"(priority:{priority_weight}, strictness:{strictness_weight})")
    # Dedupe by name, keeping the highest-priority occurrence (duplicated
    # request goals would otherwise inflate weight_sum while the dict keeps
    # one entry, deflating every normalized cost).
    seen = set()
    unique = [g for g in goals
              if not (g.name in seen or seen.add(g.name))]
    costs: Dict[str, float] = {}
    weight_sum = 0.0
    prev_priority_weight = 1.0 / priority_weight
    for spec in reversed(unique):  # lowest priority first
        current = priority_weight * prev_priority_weight
        cost = current * (strictness_weight if spec.is_hard else 1.0)
        weight_sum += cost
        costs[spec.name] = cost
        prev_priority_weight = current
    return {name: MAX_BALANCEDNESS_SCORE * c / weight_sum
            for name, c in costs.items()}


def balancedness_score(cost_by_goal: Dict[str, float],
                       violated_goals: Iterable[str]) -> float:
    """MAX_BALANCEDNESS_SCORE minus the cost of each violated goal
    (OptimizerResult.java:123-130; unknown names cost nothing)."""
    score = MAX_BALANCEDNESS_SCORE
    for name in set(violated_goals):
        score -= cost_by_goal.get(name, 0.0)
    return score
