"""Candidate action generation.

The reference generates candidate actions by iterating sorted replica views
per broker and probing candidate destination brokers through a PriorityQueue
(ResourceDistributionGoal.rebalanceForBroker, goals/ResourceDistributionGoal.java:383-535;
SortedReplicas, model/SortedReplicas.java:47).  Here generation is a pure
tensor program: a goal ranks every replica (``source_replica_relevance``) and
every broker (``dest_room``) in one pass, the top-S replicas are crossed
with the top-D destination brokers, and legitimacy (GoalUtils.legitMove
semantics plus ``OptimizationOptions`` exclusions) becomes a boolean mask
over the K = S·D candidate batch.  Leadership candidates pair the top
leader replicas with their partitions' follower siblings (max_rf wide).

Everything is shape-static: S, D are Python ints chosen from the padded
model shapes, so one compiled graph serves every step of a goal.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from cruise_control_tpu.analyzer.actions import (ActionType, Candidates,
                                                 make_candidates,
                                                 make_swap_candidates)
from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import kernels
from cruise_control_tpu.analyzer.goals.specs import GoalSpec
from cruise_control_tpu.analyzer.state import BrokerArrays, OptimizationOptions
from cruise_control_tpu.model.tensor_model import BrokerState, TensorClusterModel

_NEG = -1e29  # "irrelevant" sentinel threshold (relevance uses -1e30)


def shard_candidate_batch(cand: Candidates, mesh) -> Candidates:
    """Partition a candidate batch's K axis over the search mesh.

    Every ``Candidates`` leaf carries K as its leading dim, so one
    ``with_sharding_constraint`` with ``P(search)`` pins the whole batch to
    a by-candidate layout: each device owns K/n candidates end to end
    (legitimacy mask, delta math, scoring), and GSPMD propagates the
    partition backwards through the leg construction instead of
    replicating the batch per chip.  Values are untouched — sharding
    constraints change layout, never results — so the sharded solve stays
    bit-identical to the single-device one.  No-op without a mesh (or on a
    1-device mesh) so single-chip graphs stay byte-identical."""
    if mesh is None or mesh.devices.size <= 1:
        return cand
    sharding = jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
    return jax.tree.map(
        lambda x: jax.lax.with_sharding_constraint(x, sharding), cand)


def default_num_sources(model: TensorClusterModel) -> int:
    """Top-S source replicas per step.  Wide enough that every broker can
    shed several replicas per step, but no wider: at the 50-broker rung the
    per-step wall clock is dominated by the fixed op chain plus work linear
    in K, and halving S·D from 20k to 6.4k cut the full-stack wall 2.4x
    with hard goals still satisfied and soft-goal quality unchanged (the
    kept-action count per step is bounded by the band budgets, not by K —
    extra candidates were scored and discarded).  Never wider than the
    replica axis (top_k needs k ≤ R)."""
    want = max(64, 4 * model.num_brokers)
    return max(1, min(model.num_replicas_padded, min(want, 2048)))


def default_num_dests(model: TensorClusterModel) -> int:
    """Top-D destination brokers per step.  32 covers every rung up to a
    few hundred brokers; beyond that the destination set must widen with
    the fleet or it throttles throughput (at 7k brokers, 32 dests capped a
    step at ~200 actions and the 1M-replica fixpoint at 192 steps never
    converged — per-dest landings are bounded by the band budgets, so more
    actions per step require more destinations)."""
    b = model.num_brokers
    return max(1, min(b, max(32, min(b // 8, 1024))))


def _recv_ok(arrays: BrokerArrays, options: OptimizationOptions) -> Array:
    """bool[B] — brokers able to receive replicas for this request (alive,
    not move-excluded, inside the requested destination set when one is
    given)."""
    ok = arrays.alive & ~options.broker_excluded_replica_move
    any_requested = options.requested_dest_only.any()
    return ok & (~any_requested | options.requested_dest_only)


def _finish_move_legs(model: TensorClusterModel, arrays: BrokerArrays,
                      options: OptimizationOptions, replica: Array, dest: Array,
                      ok: Array) -> Candidates:
    """One legitimacy mask + ONE make_candidates over concatenated move legs.
    The per-builder versions each paid their own _legit_move_mask (~128 ops)
    and make_candidates (~177 ops); a step combining cross + matched batches
    pays them once over the concatenation instead."""
    k = replica.shape[0]
    action_type = jnp.full((k,), ActionType.INTER_BROKER_REPLICA_MOVEMENT, jnp.int32)
    dest_replica = jnp.full((k,), -1, jnp.int32)
    valid = ok & _legit_move_mask(model, arrays, options, replica, dest)
    return make_candidates(model, replica, dest, action_type, dest_replica, valid)


def _cross_move_legs(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                     constraint: BalancingConstraint, options: OptimizationOptions,
                     num_sources: int, num_dests: int,
                     relevance=None, bands=None, active=None):
    """(replica, dest, ok), each [S·D] — the top-S × top-D cross legs."""
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    rel_vals, src_replicas = jax.lax.top_k(relevance, num_sources)  # [S]
    room = kernels.dest_room(spec, model, arrays, constraint, bands=bands)
    # Destinations must be able to receive replicas at all.
    room = jnp.where(_recv_ok(arrays, options), room, -jnp.inf)
    if active is not None:
        room = jnp.where(active, room, -jnp.inf)
    _, dest_brokers = jax.lax.top_k(room, num_dests)  # [D]

    replica = jnp.repeat(src_replicas, num_dests)          # [K]
    dest = jnp.tile(dest_brokers, num_sources)             # [K]
    src_ok = jnp.repeat(rel_vals > _NEG, num_dests)
    return replica, dest, src_ok


def move_candidates(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                    constraint: BalancingConstraint, options: OptimizationOptions,
                    num_sources: int, num_dests: int,
                    relevance=None, bands=None) -> Candidates:
    """K = S·D inter-broker replica-move candidates for this goal."""
    replica, dest, src_ok = _cross_move_legs(
        spec, model, arrays, constraint, options, num_sources, num_dests,
        relevance=relevance, bands=bands)
    return _finish_move_legs(model, arrays, options, replica, dest, src_ok)


def _matched_move_legs(spec: GoalSpec, model: TensorClusterModel,
                       arrays: BrokerArrays, constraint: BalancingConstraint,
                       options: OptimizationOptions, num_out: int,
                       relevance=None, bands=None, active=None):
    """(replica, dest, ok), each [2·num_out] — the transport-matched legs
    (see matched_move_candidates for the semantics)."""
    B = model.num_brokers
    R = model.num_replicas_padded
    num_out = max(1, min(num_out, R))
    metric = kernels.broker_metric(spec, model, arrays, constraint)  # f32[B]
    lower, upper = bands if bands is not None else \
        kernels.limits(spec, model, arrays, constraint)
    # Shed target: down to the upper band normally; down to the band
    # midpoint while some broker sits below the lower band (the pull phase,
    # rebalanceByMovingLoadIn, ResourceDistributionGoal.java:446-535 —
    # in-band brokers above the midpoint donate too).  One threshold covers
    # both phases without double-counting an over-band broker's surplus.
    under_exists = (arrays.alive & (metric < lower)).any()
    shed_to = jnp.where(under_exists, (lower + upper) * 0.5, upper)
    src_n = jnp.ceil(jnp.maximum(metric - shed_to, 0.0)).astype(jnp.int32)
    recv_ok = _recv_ok(arrays, options)
    room_n = jnp.where(recv_ok,
                       jnp.floor(jnp.maximum(upper - metric, 0.0)), 0.0
                       ).astype(jnp.int32)
    # A shedding broker must not soak up its own surplus: its leftover room
    # under the upper band would claim transport slots whose self-moves the
    # legitimacy mask then discards, wasting matched throughput exactly at
    # the band edges the match exists for.
    room_n = jnp.where(src_n > 0, 0, room_n)
    if active is not None:
        # Frontier compaction: the transport match only sources from and
        # lands on the active set — inactive brokers are in-band with no
        # pull pressure, so they neither shed nor owe room this chunk.
        src_n = jnp.where(active, src_n, 0)
        room_n = jnp.where(active, room_n, 0)

    # Rank each replica within its broker (stable sort by broker; invalid
    # replicas sort last) so exactly the first over_n[b] replicas of broker
    # b become sources.
    rb = model.replica_broker
    key = jnp.where(model.replica_valid, rb, B)
    order = jnp.argsort(key, stable=True)
    sorted_key = key[order]
    # First sorted position of each present broker id via scatter-min (the
    # equivalent searchsorted lowers to ~21 ops; only present ids are ever
    # gathered below, where the two agree).
    start = jnp.full((B + 1,), R, jnp.int32).at[jnp.minimum(sorted_key, B)].min(
        jnp.arange(R, dtype=jnp.int32))
    rank_sorted = jnp.arange(R, dtype=jnp.int32) - \
        start[jnp.minimum(sorted_key, B)]
    rank = jnp.zeros((R,), jnp.int32).at[order].set(rank_sorted)
    is_src = model.replica_valid & (rank < src_n[rb])

    # Prioritize sources by the goal's own relevance ranking, then take the
    # top num_out (static shape).
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    rel = jnp.where(is_src, relevance, -jnp.inf)
    rel_vals, src_replicas = jax.lax.top_k(rel, num_out)           # [K]
    src_ok = jnp.isfinite(rel_vals)

    # Transport match: slot i lands on the broker covering position i of the
    # room prefix sum (biggest receivers first, so heavy room drains first).
    room_vals, room_order = jax.lax.top_k(room_n, B)               # desc [B]
    cum = jnp.cumsum(room_vals)
    slot = jnp.arange(num_out, dtype=cum.dtype)
    # pos[i] = #{cum <= i}: a histogram of the (ascending) prefix sums plus
    # one cumsum replaces searchsorted(cum, slot, "right").
    counts = jnp.zeros((num_out + 1,), jnp.int32).at[
        jnp.minimum(cum, num_out)].add(1)
    pos = jnp.cumsum(counts)[:num_out]
    dest1 = room_order[jnp.minimum(pos, B - 1)]                    # [K]
    dest_ok = slot < cum[B - 1]
    # Second leg: the next broker in room order.  A source whose matched
    # destination already hosts a sibling would otherwise retry the same
    # collision next step (the match is deterministic in the model state) —
    # the selection's partition pass keeps at most one leg per replica, so
    # this costs no throughput.
    dest2 = room_order[jnp.minimum(pos + 1, B - 1)]

    replica = jnp.concatenate([src_replicas, src_replicas])
    dest = jnp.concatenate([dest1, dest2])
    ok = jnp.concatenate([src_ok & dest_ok,
                          src_ok & dest_ok & (dest2 != dest1)])
    return replica, dest, ok


def matched_move_candidates(spec: GoalSpec, model: TensorClusterModel,
                            arrays: BrokerArrays, constraint: BalancingConstraint,
                            options: OptimizationOptions, num_out: int,
                            relevance=None, bands=None) -> Candidates:
    """K = num_out 1:1 MATCHED move candidates for the replica-count
    distribution goal: the surplus replicas of over-band brokers are
    assigned to under-band brokers' remaining room by a prefix-sum
    transport match, one candidate per replica.

    The S×D cross batch structurally throttles a hot broker: its many
    sources hash into shared (broker, lane) segments and duplicate replicas
    across lanes are deduped by the partition pass, so a broker sheds well
    under the lane width per step (the round-4 mid rung spent 26 of 78
    steps in this goal at ~120 accepts/step against a 3,120-replica
    surplus).  Here every candidate is a distinct replica with exactly one
    destination, chosen so no destination is offered more than its room —
    the conflict-free selection then keeps essentially the whole batch and
    the fixpoint collapses to a handful of steps.  The reference's
    per-broker rebalance loop reaches the same fixpoint one replica at a
    time (ReplicaDistributionGoal's rebalanceForBroker sweep,
    goals/ReplicaDistributionGoal.java); the matching is the batched
    equivalent, with the band budgets in select_batched still enforcing
    exactness.
    """
    replica, dest, ok = _matched_move_legs(
        spec, model, arrays, constraint, options, num_out,
        relevance=relevance, bands=bands)
    return _finish_move_legs(model, arrays, options, replica, dest, ok)


def _matched_topic_legs(spec: GoalSpec, model: TensorClusterModel,
                        arrays: BrokerArrays, constraint: BalancingConstraint,
                        options: OptimizationOptions, num_out: int,
                        relevance=None):
    """(replica, dest, ok), each [2·num_out] — the per-topic transport legs
    (see matched_topic_candidates for the semantics)."""
    B = model.num_brokers
    T = model.num_topics
    R = model.num_replicas_padded
    num_out = max(1, min(num_out, R))
    tbc = model.topic_broker_replica_counts().astype(jnp.float32)  # [T, B]
    lower_t, upper_t = kernels._topic_limits(model, arrays, constraint)
    recv = _recv_ok(arrays, options)[None, :]
    surplus = jnp.ceil(jnp.maximum(tbc - upper_t[:, None], 0.0)).astype(jnp.int32)
    deficit = jnp.where(recv, jnp.ceil(jnp.maximum(lower_t[:, None] - tbc, 0.0)),
                        0.0).astype(jnp.int32)
    # Donors (in-band pairs above the topic midpoint) supply ONLY the
    # deficit a topic's own surplus cannot cover — an uncapped donor pool
    # churned ~10x the needed moves toward the midpoints.
    need_t = jnp.maximum(deficit.sum(axis=1) - surplus.sum(axis=1), 0)  # [T]
    mid_t = (lower_t + upper_t) * 0.5
    donor_cap = jnp.floor(jnp.maximum(jnp.minimum(tbc, upper_t[:, None])
                                      - mid_t[:, None], 0.0)).astype(jnp.int32)
    # Admit donor capacity greedily (largest donors first) until the
    # topic's residual need is covered: per-topic prefix over the sorted
    # capacities, then map the admitted amounts back.
    d_order = jnp.argsort(-donor_cap, axis=1)                      # [T, B]
    d_sorted = jnp.take_along_axis(donor_cap, d_order, axis=1)
    d_cum = jnp.cumsum(d_sorted, axis=1)
    prev_cum = d_cum - d_sorted
    admit_sorted = jnp.clip(need_t[:, None] - prev_cum, 0, d_sorted)
    donor_n = jnp.zeros_like(donor_cap).at[
        jnp.arange(T)[:, None], d_order].set(admit_sorted)
    src_n = surplus + donor_n
    # Destination slots: deficit slots first (the pulls), then spare room
    # under the upper band for the surplus overflow.
    spare = jnp.where(recv, jnp.floor(jnp.maximum(
        upper_t[:, None] - jnp.maximum(tbc, lower_t[:, None]), 0.0)),
        0.0).astype(jnp.int32)

    # Rank each replica within its (topic, broker) pair.
    pair = model.replica_topic * B + model.replica_broker          # i32[R]
    key = jnp.where(model.replica_valid, pair, T * B)
    order = jnp.argsort(key, stable=True)
    sorted_key = key[order]
    # Scatter-min first-position table (present keys only are gathered;
    # cheaper than the equivalent searchsorted — see _matched_move_legs).
    start = jnp.full((T * B + 1,), R, jnp.int32).at[
        jnp.minimum(sorted_key, T * B)].min(jnp.arange(R, dtype=jnp.int32))
    rank_sorted = jnp.arange(R, dtype=jnp.int32) - \
        start[jnp.minimum(sorted_key, T * B)]
    rank = jnp.zeros((R,), jnp.int32).at[order].set(rank_sorted)
    is_src = model.replica_valid & (rank < src_n.reshape(-1)[jnp.minimum(pair, T * B - 1)])

    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint)
    rel = jnp.where(is_src, relevance, -jnp.inf)
    rel_vals, src_replicas = jax.lax.top_k(rel, num_out)           # [S]
    src_ok = jnp.isfinite(rel_vals)

    # Per-topic slot index of each source (position among sources of the
    # same topic, by stable sort).
    t_src = model.replica_topic[src_replicas]
    t_key = jnp.where(src_ok, t_src, T)
    s_order = jnp.argsort(t_key, stable=True)
    s_sorted_t = t_key[s_order]
    t_start = jnp.full((T + 1,), num_out, jnp.int32).at[
        jnp.minimum(s_sorted_t, T)].min(jnp.arange(num_out, dtype=jnp.int32))
    p_sorted = jnp.arange(num_out, dtype=jnp.int32) - \
        t_start[jnp.minimum(s_sorted_t, T)]
    p_in_topic = jnp.zeros((num_out,), jnp.int32).at[s_order].set(p_sorted)

    # Topic-major slot table [T, 2B]: each topic's deficit slots (largest
    # deficits first), then its spare room — one global cumsum + per-topic
    # base offsets assigns every topic's sources at once.
    def_order = jnp.argsort(-deficit, axis=1)                      # [T, B]
    sp_order = jnp.argsort(-spare, axis=1)
    slot_vals = jnp.concatenate([
        jnp.take_along_axis(deficit, def_order, axis=1),
        jnp.take_along_axis(spare, sp_order, axis=1)], axis=1)     # [T, 2B]
    slot_broker = jnp.concatenate([def_order, sp_order], axis=1)   # [T, 2B]
    W = 2 * B
    cum = jnp.cumsum(slot_vals.reshape(-1))                        # [T*W]
    base = jnp.where(t_src > 0, cum[jnp.maximum(t_src * W - 1, 0)], 0)
    total_t = cum[t_src * W + W - 1] - base
    target = base + p_in_topic
    j = jnp.searchsorted(cum, target, side="right")
    j = jnp.minimum(j, t_src * W + W - 1)
    dest1 = slot_broker.reshape(-1)[j]
    j2 = jnp.minimum(j + 1, t_src * W + W - 1)
    dest2 = slot_broker.reshape(-1)[j2]
    dest_ok = src_ok & (p_in_topic < total_t)

    replica = jnp.concatenate([src_replicas, src_replicas])
    dest = jnp.concatenate([dest1, dest2])
    ok = jnp.concatenate([dest_ok, dest_ok & (dest2 != dest1)])
    return replica, dest, ok


def matched_topic_candidates(spec: GoalSpec, model: TensorClusterModel,
                             arrays: BrokerArrays, constraint: BalancingConstraint,
                             options: OptimizationOptions, num_out: int,
                             relevance=None) -> Candidates:
    """K = 2·num_out matched move candidates for TopicReplicaDistribution:
    the per-(topic, broker) overages are matched onto the same topic's
    under-band pairs by a per-topic prefix-sum transport (the topic-major
    flattening keeps every topic's slots contiguous, so one global cumsum +
    searchsorted assigns all topics at once).  Same rationale as
    matched_move_candidates — the goal's S×D cross batch drains a hot pair
    at lane speed; here each surplus replica is its own candidate.
    Reference loop: TopicReplicaDistributionGoal.rebalanceForBroker."""
    replica, dest, ok = _matched_topic_legs(
        spec, model, arrays, constraint, options, num_out, relevance=relevance)
    return _finish_move_legs(model, arrays, options, replica, dest, ok)


def combined_move_candidates(spec: GoalSpec, model: TensorClusterModel,
                             arrays: BrokerArrays, constraint: BalancingConstraint,
                             options: OptimizationOptions, cross_sources: int,
                             num_dests: int, num_matched: int = 0,
                             relevance=None, bands=None, active=None,
                             mesh=None) -> Candidates:
    """ONE move batch combining the cross legs with the goal's matched legs
    (replica- or topic-distribution transport match, when ``num_matched`` >
    0).  Building them as one batch shares the relevance ranking, the
    legitimacy mask and make_candidates' delta math across all legs — the
    separate-builders path paid each of those twice per step.  ``active``
    (the frontier mask, bool[B]) restricts sources and destinations to the
    active broker set; topic legs never see it (topic goals are not band
    kinds, so the frontier never engages there).  ``mesh`` partitions the
    finished batch's K axis over the search mesh
    (``shard_candidate_batch``)."""
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    replica, dest, ok = _cross_move_legs(
        spec, model, arrays, constraint, options, cross_sources, num_dests,
        relevance=relevance, bands=bands, active=active)
    if num_matched > 0 and spec.kind == "replica_distribution":
        r2, d2, ok2 = _matched_move_legs(
            spec, model, arrays, constraint, options, num_matched,
            relevance=relevance, bands=bands, active=active)
    elif num_matched > 0 and spec.kind == "topic_replica_distribution":
        r2, d2, ok2 = _matched_topic_legs(
            spec, model, arrays, constraint, options, num_matched,
            relevance=relevance)
    else:
        r2 = None
    if r2 is not None:
        replica = jnp.concatenate([replica, r2])
        dest = jnp.concatenate([dest, d2])
        ok = jnp.concatenate([ok, ok2])
    return shard_candidate_batch(
        _finish_move_legs(model, arrays, options, replica, dest, ok), mesh)


def default_num_matched(model: TensorClusterModel, num_sources: int) -> int:
    """Width of the matched batch: wide enough to cover a rung's surplus
    in a couple of steps, but scale-aware — per-step wall grows with K, so
    small models shouldn't pay a 1M-sized batch (mid-rung surplus ~3k vs
    a flat 4096 floor doubled the per-step cost for no step win)."""
    r = model.num_replicas_padded
    return max(1, min(r, max(256, min(max(2048, r // 4), 16 * num_sources))))


def _legit_move_mask(model: TensorClusterModel, arrays: BrokerArrays,
                     options: OptimizationOptions, replica: Array, dest: Array) -> Array:
    """bool[K] — GoalUtils.legitMove semantics for inter-broker moves:
    destination alive and eligible, not already hosting the partition, and
    the replica is movable under the request's exclusions."""
    src = model.replica_broker[replica]
    part = model.replica_partition[replica]
    topic = model.replica_topic[replica]

    dest_alive = arrays.alive[dest]
    not_self = dest != src
    # Destination must not already host a replica of the partition
    # (checked via the partition's static sibling table, O(max_rf)).
    sib = model.partition_replicas[part]                       # [K, max_rf]
    sib_valid = (sib >= 0) & (sib != replica[:, None])
    sib_broker = model.replica_broker[jnp.where(sib >= 0, sib, 0)]
    already_there = (sib_valid & (sib_broker == dest[:, None])).any(axis=1)

    offline = model.replica_offline_now()[replica] | (~arrays.alive[src])
    topic_ok = ~options.topic_excluded[topic] | offline
    immigrant = model.replica_broker[replica] != model.replica_original_broker[replica]
    immigrant_ok = ~options.only_move_immigrants | immigrant | offline
    dest_ok = ~options.broker_excluded_replica_move[dest]
    any_requested = options.requested_dest_only.any()
    requested_ok = ~any_requested | options.requested_dest_only[dest]

    return (model.replica_valid[replica] & dest_alive & not_self & ~already_there
            & topic_ok & immigrant_ok & dest_ok & requested_ok)


def leadership_candidates(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                          constraint: BalancingConstraint, options: OptimizationOptions,
                          num_sources: int, relevance=None, bands=None) -> Candidates:
    """K = S·max_rf leadership-transfer candidates: each top-ranked leader
    replica paired with each follower sibling of its partition
    (relocateLeadership semantics, ClusterModel.java:406)."""
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    relevance = jnp.where(model.replica_is_leader, relevance, -jnp.inf)
    rel_vals, src_replicas = jax.lax.top_k(relevance, num_sources)  # [S]

    part = model.replica_partition[src_replicas]
    sib = model.partition_replicas[part]                       # [S, max_rf]
    max_rf = sib.shape[1]

    replica = jnp.repeat(src_replicas, max_rf)                 # [K]
    dest_replica = sib.reshape(-1)                             # [K]
    src_ok = jnp.repeat(rel_vals > _NEG, max_rf)

    safe_dest = jnp.where(dest_replica >= 0, dest_replica, 0)
    dest_broker = model.replica_broker[safe_dest]
    # Leadership may only land on an alive, non-demoted, non-excluded broker
    # hosting a valid online follower (PreferredLeaderElectionGoal /
    # GoalUtils eligibility).
    dest_state = model.broker_state[dest_broker]
    dest_ok = (
        (dest_replica >= 0)
        & (dest_replica != replica)
        & model.replica_valid[safe_dest]
        & ~model.replica_offline_now()[safe_dest]
        & arrays.alive[dest_broker]
        & (dest_state != BrokerState.DEMOTED)
        & ~options.broker_excluded_leadership[dest_broker]
    )
    is_leader = model.replica_is_leader[replica]

    k = replica.shape[0]
    action_type = jnp.full((k,), ActionType.LEADERSHIP_MOVEMENT, jnp.int32)
    valid = src_ok & is_leader & dest_ok & model.replica_valid[replica]
    # dest_brokers arg is unused for leadership (dest derives from
    # dest_replica inside make_candidates).
    return make_candidates(model, replica, jnp.zeros((k,), jnp.int32), action_type,
                           dest_replica, valid)


def intra_disk_candidates(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                          constraint: BalancingConstraint, options: OptimizationOptions,
                          num_sources: int, relevance=None, bands=None) -> Candidates:
    """K = S·max_disks_per_broker intra-broker disk-move candidates: each
    top-ranked replica paired with every disk of its own broker
    (IntraBrokerDiskUsageDistributionGoal's balanceBetweenDisks,
    goals/IntraBrokerDiskUsageDistributionGoal.java:47)."""
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    rel_vals, src_replicas = jax.lax.top_k(relevance, num_sources)  # [S]

    broker = model.replica_broker[src_replicas]
    disks = model.broker_disks[broker]                       # [S, max_dpb]
    max_dpb = disks.shape[1]

    replica = jnp.repeat(src_replicas, max_dpb)              # [K]
    dest_disk = disks.reshape(-1)                            # [K]
    src_ok = jnp.repeat(rel_vals > _NEG, max_dpb)

    safe_disk = jnp.where(dest_disk >= 0, dest_disk, 0)
    dest_alive = (dest_disk >= 0) & (model.disk_capacity[safe_disk] > 0.0) & \
        model.disk_valid[safe_disk]
    not_self = dest_disk != model.replica_disk[replica]

    k = replica.shape[0]
    action_type = jnp.full((k,), ActionType.INTRA_BROKER_REPLICA_MOVEMENT, jnp.int32)
    dest_replica = jnp.full((k,), -1, jnp.int32)
    valid = src_ok & dest_alive & not_self & model.replica_valid[replica]
    return make_candidates(model, replica, model.replica_broker[replica], action_type,
                           dest_replica, valid, dest_disks=dest_disk)


def default_num_swap_sources(model: TensorClusterModel) -> int:
    return max(1, min(model.num_replicas_padded, 256))


def default_num_swap_partners(model: TensorClusterModel) -> int:
    return max(1, min(model.num_replicas_padded, 16))


def swap_candidates(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                    constraint: BalancingConstraint, options: OptimizationOptions,
                    num_out: int, num_in: int,
                    relevance=None, bands=None, active=None,
                    mesh=None) -> Candidates:
    """K = S_out·S_in inter-broker replica-SWAP candidates.

    The reference's pairwise swap search walks an over-utilized broker's
    biggest replicas against an under-utilized broker's smallest
    (ResourceDistributionGoal.rebalanceForBroker :383-440 swap branch;
    KafkaAssignerDiskUsageDistributionGoal.java:48 is swap-only): here the
    top out-replicas (goal relevance = pressure × size) cross the top
    in-replicas (low-metric brokers, small size, so the net transfer sheds
    load from the over side) and all pairs are masked/scored at once.
    """
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    _, out_replicas = jax.lax.top_k(relevance, num_out)            # [S1]
    out_vals = relevance[out_replicas]

    # Swap-in ranking: replicas on brokers with the most headroom under the
    # goal metric, smaller first (maximizes the net shed of a pair).
    room = kernels.dest_room(spec, model, arrays, constraint, bands=bands)
    recv_ok = arrays.alive & ~options.broker_excluded_replica_move
    room = jnp.where(recv_ok, room, -jnp.inf)
    if active is not None:
        room = jnp.where(active, room, -jnp.inf)
    metric_res = spec.resource if spec.resource >= 0 else 3
    size = model.replica_load()[:, metric_res]
    size_scale = jnp.maximum(size.max(), 1e-9)
    in_rank = room[model.replica_broker] - size / size_scale
    in_rank = jnp.where(model.replica_valid, in_rank, -jnp.inf)
    _, in_replicas = jax.lax.top_k(in_rank, num_in)                # [S2]

    r1 = jnp.repeat(out_replicas, num_in)                          # [K]
    r2 = jnp.tile(in_replicas, num_out)                            # [K]
    src_ok = jnp.repeat(out_vals > _NEG, num_in)

    valid = src_ok & _legit_swap_mask(model, arrays, options, r1, r2)
    return shard_candidate_batch(
        make_swap_candidates(model, r1, r2, valid), mesh)


def _legit_swap_mask(model: TensorClusterModel, arrays: BrokerArrays,
                     options: OptimizationOptions, r1: Array, r2: Array) -> Array:
    """bool[K] — both swap legs are legit moves (GoalUtils.legitMove applied
    in both directions; swap-specific: distinct partitions, no sibling
    collisions either way)."""
    b1 = model.replica_broker[r1]
    b2 = model.replica_broker[r2]
    p1 = model.replica_partition[r1]
    p2 = model.replica_partition[r2]

    both_alive = arrays.alive[b1] & arrays.alive[b2]
    different = (b1 != b2) & (p1 != p2)

    def no_sibling_on(replica, broker):
        part = model.replica_partition[replica]
        sib = model.partition_replicas[part]
        sib_valid = (sib >= 0) & (sib != replica[:, None])
        sib_broker = model.replica_broker[jnp.where(sib >= 0, sib, 0)]
        return ~(sib_valid & (sib_broker == broker[:, None])).any(axis=1)

    topic_ok = ~options.topic_excluded[model.replica_topic[r1]] & \
        ~options.topic_excluded[model.replica_topic[r2]]
    dest_ok = ~options.broker_excluded_replica_move[b1] & \
        ~options.broker_excluded_replica_move[b2]
    # A swap makes BOTH brokers destinations: under a requested-destination
    # operation both must be in the requested set, and under
    # only_move_immigrants both replicas must be movable — the same gates
    # _legit_move_mask applies to one-way moves.
    any_requested = options.requested_dest_only.any()
    requested_ok = ~any_requested | (options.requested_dest_only[b1] &
                                     options.requested_dest_only[b2])
    immigrant1 = model.replica_broker[r1] != model.replica_original_broker[r1]
    immigrant2 = model.replica_broker[r2] != model.replica_original_broker[r2]
    immigrants_ok = ~options.only_move_immigrants | (immigrant1 & immigrant2)
    return (model.replica_valid[r1] & model.replica_valid[r2]
            & both_alive & different
            & no_sibling_on(r1, b2) & no_sibling_on(r2, b1)
            & topic_ok & dest_ok & requested_ok & immigrants_ok)


def intra_swap_candidates(spec: GoalSpec, model: TensorClusterModel,
                          arrays: BrokerArrays, constraint: BalancingConstraint,
                          options: OptimizationOptions, num_out: int,
                          num_in: int, relevance=None, bands=None) -> Candidates:
    """K = S_out·S_in intra-broker disk-SWAP candidates: replicas of the same
    broker on different disks exchange places (INTRA_BROKER_REPLICA_SWAP;
    the reference's intra-broker swap variant, AbstractGoal.java:345-424)."""
    if relevance is None:
        relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                     constraint, bands=bands)
    _, out_replicas = jax.lax.top_k(relevance, num_out)
    out_vals = relevance[out_replicas]

    # Partners: small replicas on low disks of the SAME broker — rank by
    # disk headroom, prefer small; same-broker is masked below.
    disk_load = model.disk_load()
    safe_disk = jnp.maximum(model.replica_disk, 0)
    size = model.replica_load()[:, 3]
    size_scale = jnp.maximum(size.max(), 1e-9)
    in_rank = -disk_load[safe_disk] - size / size_scale
    in_rank = jnp.where(model.replica_valid & (model.replica_disk >= 0),
                        in_rank, -jnp.inf)
    _, in_replicas = jax.lax.top_k(in_rank, num_in)

    r1 = jnp.repeat(out_replicas, num_in)
    r2 = jnp.tile(in_replicas, num_out)
    src_ok = jnp.repeat(out_vals > _NEG, num_in)

    same_broker = model.replica_broker[r1] == model.replica_broker[r2]
    diff_disk = (model.replica_disk[r1] != model.replica_disk[r2]) & \
        (model.replica_disk[r1] >= 0) & (model.replica_disk[r2] >= 0)
    valid = src_ok & same_broker & diff_disk & \
        model.replica_valid[r1] & model.replica_valid[r2] & (r1 != r2)
    return make_swap_candidates(model, r1, r2, valid, intra=True)


def concat_candidates(a: Candidates, b: Candidates) -> Candidates:
    return jax.tree.map(lambda x, y: jnp.concatenate([x, y], axis=0), a, b)


def take_candidates(cand: Candidates, idx: Array) -> Candidates:
    """Gather the candidate subset ``idx`` along the K axis (live-candidate
    compaction: select_batched packs the lanes surviving the score /
    feasibility / acceptance masks into a dense top-K prefix so the
    conflict and repair rounds run on live lanes only).  Every Candidates
    leaf is K-leading, so one tree-map covers move, leadership, intra-disk
    and swap legs alike — and the gathered candidates keep their FULL
    broker / partition / disk ids, so ``apply_candidates`` scatters into
    the full model unchanged."""
    return jax.tree.map(lambda x: x[idx], cand)
