"""Balancing actions, batched.

The reference represents one action as a ``BalancingAction`` object
(analyzer/BalancingAction.java:20) with an ``ActionType``
(analyzer/ActionType.java:24-29) and applies them one at a time.  Here a
*batch* of K candidate actions is a struct-of-arrays ``Candidates`` pytree
carrying precomputed load/count deltas, so every goal can score and veto all
K candidates with pure elementwise math — no per-action control flow.  The
accepted subset is applied to the tensor model in one vectorized scatter.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct
from jax import Array

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.tensor_model import TensorClusterModel


class ActionType:
    """Reference: analyzer/ActionType.java:24-29."""

    INTER_BROKER_REPLICA_MOVEMENT = 0
    LEADERSHIP_MOVEMENT = 1
    INTRA_BROKER_REPLICA_MOVEMENT = 2
    INTER_BROKER_REPLICA_SWAP = 3
    INTRA_BROKER_REPLICA_SWAP = 4


class ActionAcceptance:
    """Reference: analyzer/ActionAcceptance.java (ACCEPT / REPLICA_REJECT /
    BROKER_REJECT).  In the batched path acceptance is a bool mask; the
    tri-state is only used at the API edge."""

    ACCEPT = "ACCEPT"
    REPLICA_REJECT = "REPLICA_REJECT"
    BROKER_REJECT = "BROKER_REJECT"


@struct.dataclass
class Candidates:
    """K candidate actions with per-broker deltas (f32[K, 4] resource axes)."""

    action_type: Array  # i32[K]
    replica: Array  # i32[K] replica being moved / losing leadership
    src: Array  # i32[K] source broker
    dest: Array  # i32[K] destination broker
    # For leadership: the replica gaining leadership.  For swaps: the swap
    # partner moving dest→src (BalancingAction's destinationTp,
    # analyzer/BalancingAction.java:20).  -1 for plain moves.
    dest_replica: Array  # i32[K]
    partition: Array  # i32[K]
    # Swap partner's partition (== partition for non-swaps, so partition-
    # uniqueness selection passes treat every candidate uniformly).
    partition2: Array  # i32[K]
    valid: Array  # bool[K]
    delta_src: Array  # f32[K, 4] load change on src broker (≤ 0 typically)
    delta_dest: Array  # f32[K, 4] load change on dest broker
    d_replica_count: Array  # i32[K] replicas leaving src / arriving dest
    d_leader_count: Array  # i32[K] leaders leaving src / arriving dest
    d_potential_nw_out: Array  # f32[K] potential NW_OUT moved src→dest
    d_leader_bytes_in_src: Array  # f32[K] leader bytes-in removed from src
    d_leader_bytes_in_dest: Array  # f32[K] leader bytes-in added to dest
    src_disk: Array  # i32[K] disk the replica currently occupies (-1 non-JBOD)
    dest_disk: Array  # i32[K] landing disk (intra moves: target disk)

    @property
    def k(self) -> int:
        return self.action_type.shape[0]

    def is_move(self) -> Array:
        return self.action_type == ActionType.INTER_BROKER_REPLICA_MOVEMENT

    def is_leadership(self) -> Array:
        return self.action_type == ActionType.LEADERSHIP_MOVEMENT

    def is_intra_move(self) -> Array:
        return self.action_type == ActionType.INTRA_BROKER_REPLICA_MOVEMENT

    def is_swap(self) -> Array:
        return self.action_type == ActionType.INTER_BROKER_REPLICA_SWAP

    def is_intra_swap(self) -> Array:
        return self.action_type == ActionType.INTRA_BROKER_REPLICA_SWAP


def make_candidates(model: TensorClusterModel, replica_ids: Array, dest_brokers: Array,
                    action_type: Array, dest_replica: Array, valid: Array,
                    dest_disks: Array = None) -> Candidates:
    """Assemble the delta fields for a K-batch of raw (replica, dest) picks.

    For replica movement: src loses the replica's current load, dest gains it
    (ClusterModel.relocateReplica semantics, ClusterModel.java:377-393).
    For leadership movement: src loses (leader - follower) load of `replica`,
    the dest replica's broker gains (leader - follower) of `dest_replica`
    (Rack.makeLeader/makeFollower delta semantics, ClusterModel.java:406-431).
    Intra-broker movement (``dest_disks``) relocates the replica across its
    broker's disks: broker-axis deltas are zero; the disk goals read the
    replica's DISK contribution against src_disk/dest_disk.
    """
    is_lead = action_type == ActionType.LEADERSHIP_MOVEMENT
    is_intra = action_type == ActionType.INTRA_BROKER_REPLICA_MOVEMENT
    r = replica_ids
    r2 = jnp.where(dest_replica >= 0, dest_replica, 0)

    src = model.replica_broker[r]
    dest = jnp.where(is_lead, model.replica_broker[r2],
                     jnp.where(is_intra, src, dest_brokers))

    cur_load = jnp.where(model.replica_is_leader[r][:, None],
                         model.replica_load_leader[r], model.replica_load_follower[r])
    lead_delta_src = model.replica_load_follower[r] - model.replica_load_leader[r]
    lead_delta_dest = model.replica_load_leader[r2] - model.replica_load_follower[r2]

    zero = jnp.zeros_like(cur_load)
    delta_src = jnp.where(is_lead[:, None], lead_delta_src,
                          jnp.where(is_intra[:, None], zero, -cur_load))
    delta_dest = jnp.where(is_lead[:, None], lead_delta_dest,
                           jnp.where(is_intra[:, None], zero, cur_load))

    is_leader_replica = model.replica_is_leader[r]
    is_move = ~is_lead & ~is_intra
    d_replica_count = jnp.where(is_move, 1, 0).astype(jnp.int32)
    d_leader_count = jnp.where(is_lead | (is_move & is_leader_replica), 1, 0).astype(jnp.int32)
    d_potential = jnp.where(is_move, model.replica_load_leader[r, Resource.NW_OUT], 0.0)
    leader_nw_in_r = model.replica_load_leader[r, Resource.NW_IN]
    leader_nw_in_r2 = model.replica_load_leader[r2, Resource.NW_IN]
    d_lbi_src = jnp.where(is_lead | (is_move & is_leader_replica), leader_nw_in_r, 0.0)
    d_lbi_dest = jnp.where(is_lead, leader_nw_in_r2,
                           jnp.where(is_move & is_leader_replica, leader_nw_in_r, 0.0))

    src_disk = model.replica_disk[r]
    if dest_disks is None:
        dest_disks = model.broker_first_disk[jnp.where(dest >= 0, dest, 0)]
    dest_disk = jnp.where(is_lead, src_disk, dest_disks.astype(jnp.int32))

    return Candidates(
        action_type=action_type.astype(jnp.int32),
        replica=r.astype(jnp.int32),
        src=src.astype(jnp.int32),
        dest=dest.astype(jnp.int32),
        dest_replica=dest_replica.astype(jnp.int32),
        partition=model.replica_partition[r],
        partition2=model.replica_partition[r],
        valid=valid,
        delta_src=delta_src,
        delta_dest=delta_dest,
        d_replica_count=d_replica_count,
        d_leader_count=d_leader_count,
        d_potential_nw_out=d_potential,
        d_leader_bytes_in_src=d_lbi_src,
        d_leader_bytes_in_dest=d_lbi_dest,
        src_disk=src_disk,
        dest_disk=dest_disk,
    )


def make_swap_candidates(model: TensorClusterModel, replica_out: Array,
                         replica_in: Array, valid: Array,
                         intra: bool = False) -> Candidates:
    """K-batch of replica SWAPS: ``replica_out`` (on src) exchanges places
    with ``replica_in`` (on dest) — INTER_BROKER_REPLICA_SWAP, or the two
    exchange *disks* on one broker — INTRA_BROKER_REPLICA_SWAP
    (ActionType.java:24-29; swap application in AbstractGoal.java:281-332).

    Broker-axis delta fields carry the NET effect (out's load leaves src and
    in's load arrives, and vice versa on dest), so every delta-based kernel
    (band feasibility, budgets, capacity acceptance) works unchanged; swap-
    aware kernels special-case rack/topic/leader bookkeeping via
    ``is_swap()``."""
    r1 = replica_out
    r2 = jnp.where(replica_in >= 0, replica_in, 0)
    k = r1.shape[0]

    src = model.replica_broker[r1]
    dest = model.replica_broker[r2]

    def load_of(r):
        return jnp.where(model.replica_is_leader[r][:, None],
                         model.replica_load_leader[r],
                         model.replica_load_follower[r])

    l1, l2 = load_of(r1), load_of(r2)
    lead1 = model.replica_is_leader[r1]
    lead2 = model.replica_is_leader[r2]
    if intra:
        # Same broker: broker-axis deltas are zero; disk axis carries the
        # exchange (src_disk/dest_disk of r1; kernels read r2 via
        # dest_replica).
        delta_src = jnp.zeros_like(l1)
        delta_dest = jnp.zeros_like(l1)
        action = jnp.full((k,), ActionType.INTRA_BROKER_REPLICA_SWAP, jnp.int32)
        d_leader = jnp.zeros((k,), jnp.int32)
        d_pot = jnp.zeros((k,), jnp.float32)
        d_lbi_src = jnp.zeros((k,), jnp.float32)
        d_lbi_dest = jnp.zeros((k,), jnp.float32)
    else:
        delta_src = l2 - l1
        delta_dest = l1 - l2
        action = jnp.full((k,), ActionType.INTER_BROKER_REPLICA_SWAP, jnp.int32)
        d_leader = (lead1.astype(jnp.int32) - lead2.astype(jnp.int32))
        d_pot = model.replica_load_leader[r1, Resource.NW_OUT] - \
            model.replica_load_leader[r2, Resource.NW_OUT]
        lbi1 = jnp.where(lead1, model.replica_load_leader[r1, Resource.NW_IN], 0.0)
        lbi2 = jnp.where(lead2, model.replica_load_leader[r2, Resource.NW_IN], 0.0)
        d_lbi_src = lbi1 - lbi2
        d_lbi_dest = lbi1 - lbi2

    return Candidates(
        action_type=action,
        replica=r1.astype(jnp.int32),
        src=src.astype(jnp.int32),
        dest=dest.astype(jnp.int32),
        dest_replica=r2.astype(jnp.int32),
        partition=model.replica_partition[r1],
        partition2=model.replica_partition[r2],
        valid=valid & (replica_in >= 0),
        delta_src=delta_src,
        delta_dest=delta_dest,
        # Swaps exchange one replica for one replica: counts are unchanged.
        d_replica_count=jnp.zeros((k,), jnp.int32),
        d_leader_count=d_leader,
        d_potential_nw_out=d_pot,
        d_leader_bytes_in_src=d_lbi_src,
        d_leader_bytes_in_dest=d_lbi_dest,
        src_disk=model.replica_disk[r1],
        dest_disk=model.replica_disk[r2],
    )


def apply_candidates(model: TensorClusterModel, cand: Candidates, apply_mask: Array) -> TensorClusterModel:
    """Apply the masked subset of candidates (moves, disk moves,
    leaderships, swaps)."""
    move_mask = apply_mask & cand.is_move()
    model = model.relocate_replicas(cand.replica, cand.dest, move_mask)
    intra_mask = apply_mask & cand.is_intra_move()
    model = model.relocate_replicas_to_disk(cand.replica, cand.dest_disk, intra_mask)
    lead_mask = apply_mask & cand.is_leadership()
    safe_dest = jnp.where(cand.dest_replica >= 0, cand.dest_replica, cand.replica)
    model = model.relocate_leadership(cand.replica, safe_dest, lead_mask)
    # Swaps: two relocations per action (AbstractGoal.java:281-332 applies
    # both legs atomically; scatters are disjoint because selection enforces
    # partition uniqueness over BOTH partitions).
    swap_mask = apply_mask & cand.is_swap()
    model = model.relocate_replicas(cand.replica, cand.dest, swap_mask)
    model = model.relocate_replicas(safe_dest, cand.src, swap_mask)
    iswap_mask = apply_mask & cand.is_intra_swap()
    model = model.relocate_replicas_to_disk(cand.replica, cand.dest_disk, iswap_mask)
    model = model.relocate_replicas_to_disk(safe_dest, cand.src_disk, iswap_mask)
    return model
