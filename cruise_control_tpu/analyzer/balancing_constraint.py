"""Balancing thresholds bundle.

Parity with the reference's ``BalancingConstraint``
(analyzer/BalancingConstraint.java:20-75): per-resource balance percentages,
capacity thresholds, low-utilization thresholds, max replicas per broker,
over-provisioning bounds, and fast-mode timeout, all sourced from config.
Kept as a plain frozen dataclass of Python floats — these are *static* under
jit (they select compiled graphs, they are not traced).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.config import Config
from cruise_control_tpu.config import constants as C

# Reference: ResourceDistributionGoal.BALANCE_MARGIN = 0.9
# (goals/ResourceDistributionGoal.java:57) — the fraction of the configured
# balance headroom actually used, so proposals land safely inside limits.
BALANCE_MARGIN = 0.9


@dataclasses.dataclass(frozen=True)
class BalancingConstraint:
    resource_balance_threshold: Tuple[float, float, float, float]  # per Resource id
    capacity_threshold: Tuple[float, float, float, float]
    low_utilization_threshold: Tuple[float, float, float, float]
    replica_count_balance_threshold: float = 1.1
    leader_replica_count_balance_threshold: float = 1.1
    topic_replica_count_balance_threshold: float = 1.1
    max_replicas_per_broker: int = 10000
    overprovisioned_max_replicas_per_broker: int = 1500
    overprovisioned_min_brokers: int = 3
    overprovisioned_min_extra_racks: int = 2
    fast_mode_per_broker_move_timeout_ms: int = 500
    # Max actions one broker participates in per batched optimizer step
    # (moves.per.step; select_batched's rounds × subround lanes).
    moves_per_broker_step: int = 128
    # MinTopicLeadersPerBrokerGoal (config-static designated-topic ids +
    # required leaders per broker; reference: topics.with.min.leaders.per.broker).
    min_topic_leaders_per_broker: int = 1
    min_leader_topic_ids: Tuple[int, ...] = ()

    @classmethod
    def from_config(cls, cfg: Config) -> "BalancingConstraint":
        return cls(
            resource_balance_threshold=(
                cfg.get_double(C.CPU_BALANCE_THRESHOLD_CONFIG),
                cfg.get_double(C.NETWORK_INBOUND_BALANCE_THRESHOLD_CONFIG),
                cfg.get_double(C.NETWORK_OUTBOUND_BALANCE_THRESHOLD_CONFIG),
                cfg.get_double(C.DISK_BALANCE_THRESHOLD_CONFIG),
            ),
            capacity_threshold=(
                cfg.get_double(C.CPU_CAPACITY_THRESHOLD_CONFIG),
                cfg.get_double(C.NETWORK_INBOUND_CAPACITY_THRESHOLD_CONFIG),
                cfg.get_double(C.NETWORK_OUTBOUND_CAPACITY_THRESHOLD_CONFIG),
                cfg.get_double(C.DISK_CAPACITY_THRESHOLD_CONFIG),
            ),
            low_utilization_threshold=(
                cfg.get_double(C.CPU_LOW_UTILIZATION_THRESHOLD_CONFIG),
                cfg.get_double(C.NETWORK_INBOUND_LOW_UTILIZATION_THRESHOLD_CONFIG),
                cfg.get_double(C.NETWORK_OUTBOUND_LOW_UTILIZATION_THRESHOLD_CONFIG),
                cfg.get_double(C.DISK_LOW_UTILIZATION_THRESHOLD_CONFIG),
            ),
            replica_count_balance_threshold=cfg.get_double(C.REPLICA_COUNT_BALANCE_THRESHOLD_CONFIG),
            leader_replica_count_balance_threshold=cfg.get_double(
                C.LEADER_REPLICA_COUNT_BALANCE_THRESHOLD_CONFIG),
            topic_replica_count_balance_threshold=cfg.get_double(
                C.TOPIC_REPLICA_COUNT_BALANCE_THRESHOLD_CONFIG),
            max_replicas_per_broker=cfg.get_int(C.MAX_REPLICAS_PER_BROKER_CONFIG),
            overprovisioned_max_replicas_per_broker=cfg.get_int(
                C.OVERPROVISIONED_MAX_REPLICAS_PER_BROKER_CONFIG),
            overprovisioned_min_brokers=cfg.get_int(C.OVERPROVISIONED_MIN_BROKERS_CONFIG),
            overprovisioned_min_extra_racks=cfg.get_int(C.OVERPROVISIONED_MIN_EXTRA_RACKS_CONFIG),
            fast_mode_per_broker_move_timeout_ms=cfg.get_int(
                C.FAST_MODE_PER_BROKER_MOVE_TIMEOUT_MS_CONFIG),
            moves_per_broker_step=cfg.get_int(C.MOVES_PER_STEP_CONFIG),
        )

    @classmethod
    def default(cls) -> "BalancingConstraint":
        return cls(
            resource_balance_threshold=(1.1, 1.1, 1.1, 1.1),
            capacity_threshold=(0.7, 0.8, 0.8, 0.8),
            low_utilization_threshold=(0.0, 0.0, 0.0, 0.0),
        )

    def balance_percentage(self, resource: int) -> float:
        """Headroom fraction actually used: 1 + (threshold-1)·margin
        (GoalUtils.computeResourceUtilizationBalanceThreshold)."""
        t = self.resource_balance_threshold[resource]
        return (t - 1.0) * BALANCE_MARGIN + 1.0
