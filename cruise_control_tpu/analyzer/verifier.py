"""Post-hoc optimization invariant verification.

Parity with the reference's ``OptimizationVerifier``
(cruise-control/src/test/java/.../analyzer/OptimizationVerifier.java:53),
which validates optimizer output on randomized inputs by *invariant
checking* rather than golden outputs: proposals reachable, no
replication-factor change, goal satisfaction, stats not regressed.  Used by
the property tests and exposed to the API layer for dry-run validation.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import kernels
from cruise_control_tpu.analyzer.goals.specs import goals_by_priority
from cruise_control_tpu.analyzer.optimizer import OptimizerRun
from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.analyzer.state import BrokerArrays
from cruise_control_tpu.model.tensor_model import TensorClusterModel


class VerificationError(AssertionError):
    pass


def verify_run(initial: TensorClusterModel, run: OptimizerRun,
               goal_names: Sequence[str],
               constraint: Optional[BalancingConstraint] = None,
               proposals: Optional[List[ExecutionProposal]] = None) -> None:
    """Raise VerificationError on any violated invariant."""
    constraint = constraint or BalancingConstraint.default()
    final = run.model
    final.sanity_check()

    # Replication factor unchanged for every partition (the optimizer moves
    # replicas, it never creates/destroys them — verified like
    # OptimizationVerifier's RF check).
    rf0 = np.asarray(initial.partition_replication_factor())
    rf1 = np.asarray(final.partition_replication_factor())
    if not (rf0 == rf1).all():
        bad = np.nonzero(rf0 != rf1)[0][:5]
        raise VerificationError(f"replication factor changed for partitions {bad}")

    # Total cluster load is conserved (moves relocate load, never change it).
    load0 = np.asarray(initial.broker_load()).sum(axis=0)
    load1 = np.asarray(final.broker_load()).sum(axis=0)
    if not np.allclose(load0, load1, rtol=1e-4):
        raise VerificationError(f"total load changed: {load0} -> {load1}")

    # Hard goals must hold after optimization; soft goals must not have been
    # *introduced* as violations (satisfied before ⇒ satisfied after).
    arrays = BrokerArrays.from_model(final)
    for spec, res in zip(goals_by_priority(goal_names), run.goal_results):
        sat = bool(kernels.goal_satisfied(spec, final, arrays, constraint))
        if spec.is_hard and not sat:
            raise VerificationError(f"hard goal {spec.name} violated after optimization")
        if res.satisfied_before and not sat:
            raise VerificationError(f"goal {spec.name} regressed (was satisfied before)")

    # No replicas may remain on dead brokers once hard goals ran.
    dead = ~np.asarray(final.alive_broker_mask())
    rb = np.asarray(final.replica_broker)
    valid = np.asarray(final.replica_valid)
    any_hard = any(s.is_hard for s in goals_by_priority(goal_names))
    if any_hard and dead[rb[valid]].any():
        raise VerificationError("replicas remain on dead brokers after hard goals")

    if proposals is not None:
        _verify_proposals(initial, final, proposals)


def _verify_proposals(initial: TensorClusterModel, final: TensorClusterModel,
                      proposals: List[ExecutionProposal]) -> None:
    """Each proposal must be reachable from the initial distribution and
    produce the final one (AnalyzerUtils.getDiff correctness)."""
    for prop in proposals:
        if len(prop.old_replicas) != len(prop.new_replicas):
            raise VerificationError(
                f"proposal for partition {prop.partition} changes RF")
        old_brokers = sorted(p.broker for p in prop.old_replicas)
        if len(set(old_brokers)) != len(old_brokers):
            raise VerificationError(
                f"proposal for partition {prop.partition} has duplicate old brokers")
        new_brokers = sorted(p.broker for p in prop.new_replicas)
        if len(set(new_brokers)) != len(new_brokers):
            raise VerificationError(
                f"proposal for partition {prop.partition} has duplicate new brokers")

    # Final placement per partition matches what the proposals claim.
    pr = np.asarray(final.partition_replicas)
    rb1 = np.asarray(final.replica_broker)
    by_part = {p.partition: p for p in proposals}
    for part, prop in by_part.items():
        slots = pr[part][pr[part] >= 0]
        actual = sorted(int(rb1[r]) for r in slots)
        claimed = sorted(p.broker for p in prop.new_replicas)
        if actual != claimed:
            raise VerificationError(
                f"partition {part}: proposal claims brokers {claimed}, model has {actual}")
