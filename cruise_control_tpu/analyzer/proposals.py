"""Proposal diff: initial vs optimized replica distributions.

Parity with ``AnalyzerUtils.getDiff`` (analyzer/AnalyzerUtils.java:64-112)
and ``ExecutionProposal`` (executor/ExecutionProposal.java:26): compare the
pre-optimization and post-optimization placements partition by partition and
emit one proposal per changed partition carrying the old leader, old replica
list, and new replica list (leader first).  The executor consumes these.

The diff itself is a host-side numpy pass over the partition→replica table —
it runs once per optimization (not in the hot loop) and produces Python
objects for the control plane, so it deliberately lives off-device.  A C++
fast path takes over at the 1M-replica scale (see native/).
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np

from cruise_control_tpu.model.tensor_model import TensorClusterModel


class ReplicaPlacement(NamedTuple):
    """(broker, disk) placement (model/ReplicaPlacementInfo.java).
    A NamedTuple, not a frozen dataclass: a 100k-replica diff builds ~100k
    of these and frozen-dataclass __init__ (object.__setattr__ per field)
    was ~10x the construction cost."""

    broker: int
    disk: int = -1


class ExecutionProposal(NamedTuple):
    """One partition's reassignment (executor/ExecutionProposal.java:26)."""

    partition: int
    topic: int
    partition_size: float  # DISK footprint of the leader replica (MB)
    old_leader: ReplicaPlacement
    old_replicas: Tuple[ReplicaPlacement, ...]
    new_replicas: Tuple[ReplicaPlacement, ...]

    @property
    def new_leader(self) -> ReplicaPlacement:
        return self.new_replicas[0]

    @property
    def replicas_to_add(self) -> Tuple[int, ...]:
        old = {p.broker for p in self.old_replicas}
        return tuple(p.broker for p in self.new_replicas if p.broker not in old)

    @property
    def replicas_to_remove(self) -> Tuple[int, ...]:
        new = {p.broker for p in self.new_replicas}
        return tuple(p.broker for p in self.old_replicas if p.broker not in new)

    @property
    def has_replica_action(self) -> bool:
        return bool(self.replicas_to_add or self.replicas_to_remove
                    or self._intra_broker_moves())

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader.broker != self.new_leader.broker or \
            self.old_replicas[0].broker != self.new_replicas[0].broker

    def _intra_broker_moves(self) -> List[Tuple[int, int, int]]:
        """(broker, old_disk, new_disk) for replicas that changed disk only."""
        old_by_broker = {p.broker: p.disk for p in self.old_replicas}
        out = []
        for p in self.new_replicas:
            if p.broker in old_by_broker and old_by_broker[p.broker] != p.disk:
                out.append((p.broker, old_by_broker[p.broker], p.disk))
        return out

    def to_dict(self) -> Dict[str, object]:
        return {
            "partition": self.partition,
            "topic": self.topic,
            "partitionSize": self.partition_size,
            "oldLeader": self.old_leader.broker,
            "oldReplicas": [p.broker for p in self.old_replicas],
            "newReplicas": [p.broker for p in self.new_replicas],
        }


def renumber_brokers(proposals: List[ExecutionProposal],
                     broker_ids: List[int]) -> List[ExecutionProposal]:
    """Map dense model broker indices → external cluster broker ids.

    The tensor model addresses brokers by dense index 0..B-1 (sorted-id
    order, LoadMonitor._build_model); the cluster protocol uses real broker
    ids, which need not be contiguous.  The facade translates at this seam
    before proposals reach the executor / REST payloads — passing dense
    indices through (correct only when ids are exactly 0..B-1) was a
    round-1 advisory finding."""
    def pl(p: ReplicaPlacement) -> ReplicaPlacement:
        return ReplicaPlacement(int(broker_ids[p.broker]), p.disk)

    return [p._replace(
        old_leader=pl(p.old_leader),
        old_replicas=tuple(pl(x) for x in p.old_replicas),
        new_replicas=tuple(pl(x) for x in p.new_replicas)) for p in proposals]


def diff(initial: TensorClusterModel, final: TensorClusterModel) -> List[ExecutionProposal]:
    """Emit proposals for partitions whose placement or leadership changed.

    Replica-list order follows the reference's convention: the (new) leader
    first, then the remaining replicas in partition-table order — the order
    Kafka receives in the reassignment request.  The comparison walks the
    partition table in C++ when the native library is available (the
    1M-replica fast path); the Python path below is the fallback and oracle.
    """
    # ONE batched host fetch for every array the diff reads (per-leaf
    # np.asarray was ~10 sequential device round trips at ~0.5-1 s each over
    # a tunneled TPU); leaves already on host pass through untouched.
    (pr0, rb0, rd0, lead0, valid0, pr1, rb1, rd1, lead1, valid1,
     load_lead, load_foll, ptopic, pvalid_arr) = jax.device_get((
        initial.partition_replicas, initial.replica_broker,
        initial.replica_disk, initial.replica_is_leader, initial.replica_valid,
        final.partition_replicas, final.replica_broker, final.replica_disk,
        final.replica_is_leader, final.replica_valid,
        initial.replica_load_leader, initial.replica_load_follower,
        initial.partition_topic, initial.partition_valid))
    if pr0.shape != pr1.shape:
        raise ValueError("initial/final models have different partition tables")

    load = np.where(lead0[:, None], load_lead, load_foll)
    from cruise_control_tpu.common.resources import Resource

    from cruise_control_tpu import native
    nat = native.diff_partitions(pr0, rb0, rb1, rd0, rd1, lead0, lead1)
    if nat is not None:
        changed_ids, ob, nb, od, nd = nat
        pvalid = pvalid_arr
        proposals: List[ExecutionProposal] = []
        for i, p in enumerate(changed_ids):
            if not pvalid[p]:
                continue
            slots = pr0[p][pr0[p] >= 0]
            old = tuple(ReplicaPlacement(int(b), int(d))
                        for b, d in zip(ob[i], od[i]) if b >= 0)
            new = tuple(ReplicaPlacement(int(b), int(d))
                        for b, d in zip(nb[i], nd[i]) if b >= 0)
            if old == new:
                continue
            size = float(load[slots, Resource.DISK].max())
            proposals.append(ExecutionProposal(
                partition=int(p), topic=int(ptopic[p]), partition_size=size,
                old_leader=old[0], old_replicas=old, new_replicas=new))
        return proposals

    # Vectorized prefilter: only partitions with any change produce objects.
    sl = pr0 >= 0
    b0 = np.where(sl, rb0[np.where(sl, pr0, 0)], -1)
    b1 = np.where(sl, rb1[np.where(sl, pr1, 0)], -1)
    d0 = np.where(sl, rd0[np.where(sl, pr0, 0)], -1)
    d1 = np.where(sl, rd1[np.where(sl, pr1, 0)], -1)
    l0 = np.where(sl, lead0[np.where(sl, pr0, 0)], False)
    l1 = np.where(sl, lead1[np.where(sl, pr1, 0)], False)
    changed = ((b0 != b1) | (l0 != l1) | (d0 != d1)).any(axis=1)
    changed &= pvalid_arr

    proposals: List[ExecutionProposal] = []
    for p in np.nonzero(changed)[0]:
        slots = pr0[p][pr0[p] >= 0]
        if slots.size == 0:
            continue

        def ordered(rb, rd, lead):
            placements = [ReplicaPlacement(int(rb[r]), int(rd[r])) for r in slots]
            leader_pos = next((i for i, r in enumerate(slots) if lead[r]), 0)
            if leader_pos:
                placements = [placements[leader_pos]] + placements[:leader_pos] + \
                    placements[leader_pos + 1:]
            return tuple(placements)

        old = ordered(rb0, rd0, lead0)
        new = ordered(rb1, rd1, lead1)
        if old == new:
            continue
        size = float(load[slots, Resource.DISK].max())
        proposals.append(ExecutionProposal(
            partition=int(p), topic=int(ptopic[p]), partition_size=size,
            old_leader=old[0], old_replicas=old, new_replicas=new))
    return proposals
