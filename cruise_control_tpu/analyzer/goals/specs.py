"""Goal specifications.

Each of the reference's goal classes (SURVEY.md §2.3, all 21 listed at
analyzer/goals/*.java) is represented here as a small frozen ``GoalSpec``
selecting a *kind* (the vectorized kernel family in ``kernels.py``) plus
static parameters (resource binding, hardness).  This is the data-driven
replacement for the reference's class-per-goal hierarchy rooted at
``AbstractGoal`` (analyzer/goals/AbstractGoal.java:45): behavior lives in
pure kernel functions; a spec is just the dispatch key, so a full goal list
compiles to a handful of XLA graphs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.common.resources import Resource


@dataclasses.dataclass(frozen=True)
class GoalSpec:
    name: str
    kind: str
    is_hard: bool = False
    resource: int = -1  # Resource id for resource-bound kinds

    # Which action families the goal uses to improve itself.
    uses_moves: bool = True
    uses_leadership: bool = False
    uses_intra_moves: bool = False
    # Pairwise exchanges (ActionType INTER/INTRA_BROKER_REPLICA_SWAP): lets
    # two brokers both near capacity trade a big replica for a small one
    # when no single move is feasible (ResourceDistributionGoal.java:383-440;
    # KafkaAssignerDiskUsageDistributionGoal.java:48 is swap-based).
    uses_swaps: bool = False
    uses_intra_swaps: bool = False
    # kafka-assigner compatibility mode (kafkaassigner/*.java): same kernel
    # families, flagged so mode-specific goal lists can be assembled.
    kafka_assigner_mode: bool = False


def _capacity(name: str, resource: Resource) -> GoalSpec:
    # Reference: goals/CapacityGoal.java:41 + resource bindings
    # (CpuCapacityGoal.java:12, DiskCapacityGoal, NetworkIn/OutboundCapacityGoal).
    # uses_swaps goes beyond the reference (whose CapacityGoal only moves):
    # two brokers both near the cap can still trade big-for-small when no
    # one-way move fits — strictly more fixable states, same invariants.
    return GoalSpec(name=name, kind="capacity", is_hard=True, resource=int(resource),
                    uses_moves=True, uses_leadership=resource in (Resource.CPU, Resource.NW_OUT),
                    uses_swaps=True)


def _distribution(name: str, resource: Resource) -> GoalSpec:
    # Reference: goals/ResourceDistributionGoal.java:55 + bindings; the
    # third rebalance mechanism (pairwise swaps, :383-440) is uses_swaps.
    return GoalSpec(name=name, kind="resource_distribution", is_hard=False, resource=int(resource),
                    uses_moves=True, uses_leadership=resource in (Resource.CPU, Resource.NW_OUT),
                    uses_swaps=True)


GOAL_SPECS: Dict[str, GoalSpec] = {
    "RackAwareGoal": GoalSpec("RackAwareGoal", "rack", is_hard=True),
    # Relaxed rack distribution (goals/RackAwareDistributionGoal.java:65):
    # same kernel family with even-distribution limits.
    "RackAwareDistributionGoal": GoalSpec("RackAwareDistributionGoal", "rack_distribution",
                                          is_hard=True),
    "ReplicaCapacityGoal": GoalSpec("ReplicaCapacityGoal", "replica_capacity", is_hard=True),
    "DiskCapacityGoal": _capacity("DiskCapacityGoal", Resource.DISK),
    "NetworkInboundCapacityGoal": _capacity("NetworkInboundCapacityGoal", Resource.NW_IN),
    "NetworkOutboundCapacityGoal": _capacity("NetworkOutboundCapacityGoal", Resource.NW_OUT),
    "CpuCapacityGoal": _capacity("CpuCapacityGoal", Resource.CPU),
    "ReplicaDistributionGoal": GoalSpec("ReplicaDistributionGoal", "replica_distribution"),
    "PotentialNwOutGoal": GoalSpec("PotentialNwOutGoal", "potential_nw_out"),
    "DiskUsageDistributionGoal": _distribution("DiskUsageDistributionGoal", Resource.DISK),
    "NetworkInboundUsageDistributionGoal": _distribution(
        "NetworkInboundUsageDistributionGoal", Resource.NW_IN),
    "NetworkOutboundUsageDistributionGoal": _distribution(
        "NetworkOutboundUsageDistributionGoal", Resource.NW_OUT),
    "CpuUsageDistributionGoal": _distribution("CpuUsageDistributionGoal", Resource.CPU),
    "TopicReplicaDistributionGoal": GoalSpec("TopicReplicaDistributionGoal",
                                             "topic_replica_distribution"),
    "LeaderReplicaDistributionGoal": GoalSpec("LeaderReplicaDistributionGoal",
                                              "leader_replica_distribution",
                                              uses_moves=True, uses_leadership=True),
    "LeaderBytesInDistributionGoal": GoalSpec("LeaderBytesInDistributionGoal",
                                              "leader_bytes_in", uses_moves=False,
                                              uses_leadership=True),
    # Make replica[0] the leader (goals/PreferredLeaderElectionGoal.java:36).
    "PreferredLeaderElectionGoal": GoalSpec("PreferredLeaderElectionGoal",
                                            "preferred_leader", uses_moves=False,
                                            uses_leadership=True),
    # ≥ configured leaders of designated topics per broker
    # (goals/MinTopicLeadersPerBrokerGoal.java:50).
    "MinTopicLeadersPerBrokerGoal": GoalSpec("MinTopicLeadersPerBrokerGoal",
                                             "min_topic_leaders", is_hard=True,
                                             uses_moves=True, uses_leadership=True),
    # JBOD intra-broker disk goals (goals/IntraBrokerDiskCapacityGoal.java:42,
    # IntraBrokerDiskUsageDistributionGoal.java:47) — rebalance-disk mode.
    "IntraBrokerDiskCapacityGoal": GoalSpec("IntraBrokerDiskCapacityGoal",
                                            "intra_disk_capacity", is_hard=True,
                                            uses_moves=False, uses_intra_moves=True),
    "IntraBrokerDiskUsageDistributionGoal": GoalSpec(
        "IntraBrokerDiskUsageDistributionGoal", "intra_disk_distribution",
        uses_moves=False, uses_intra_moves=True, uses_intra_swaps=True),
    # kafka-assigner compatibility modes (kafkaassigner/
    # KafkaAssignerEvenRackAwareGoal.java:42, round-robin rack-aware placement;
    # KafkaAssignerDiskUsageDistributionGoal.java:48, SWAP-based disk
    # balancing — pure pairwise exchanges, no one-way moves).
    "KafkaAssignerEvenRackAwareGoal": GoalSpec("KafkaAssignerEvenRackAwareGoal",
                                               "rack", is_hard=True,
                                               kafka_assigner_mode=True),
    "KafkaAssignerDiskUsageDistributionGoal": GoalSpec(
        "KafkaAssignerDiskUsageDistributionGoal", "resource_distribution",
        resource=int(Resource.DISK), kafka_assigner_mode=True,
        uses_moves=False, uses_swaps=True),
}

KAFKA_ASSIGNER_GOALS = [n for n, s in GOAL_SPECS.items() if s.kafka_assigner_mode]

# Reference default priority order (config/cruisecontrol.properties:98-126).
DEFAULT_GOAL_ORDER = [
    "RackAwareGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]

DEFAULT_HARD_GOALS = [n for n in DEFAULT_GOAL_ORDER if GOAL_SPECS[n].is_hard]

INTRA_BROKER_GOAL_ORDER = [
    "IntraBrokerDiskCapacityGoal",
    "IntraBrokerDiskUsageDistributionGoal",
]


def goals_by_priority(names: Sequence[str]) -> List[GoalSpec]:
    """Resolve goal names (short or fully qualified) in priority order
    (KafkaCruiseControlUtils.goalsByPriority analogue)."""
    out = []
    for name in names:
        short = name.rsplit(".", 1)[-1]
        if short not in GOAL_SPECS:
            raise ValueError(f"Unknown goal {name!r}")
        out.append(GOAL_SPECS[short])
    return out
