"""Goal specifications.

Each of the reference's goal classes (SURVEY.md §2.3, all 21 listed at
analyzer/goals/*.java) is represented here as a small frozen ``GoalSpec``
selecting a *kind* (the vectorized kernel family in ``kernels.py``) plus
static parameters (resource binding, hardness).  This is the data-driven
replacement for the reference's class-per-goal hierarchy rooted at
``AbstractGoal`` (analyzer/goals/AbstractGoal.java:45): behavior lives in
pure kernel functions; a spec is just the dispatch key, so a full goal list
compiles to a handful of XLA graphs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.common.resources import Resource


@dataclasses.dataclass(frozen=True)
class GoalSpec:
    name: str
    kind: str
    is_hard: bool = False
    resource: int = -1  # Resource id for resource-bound kinds

    # Which action families the goal uses to improve itself.
    uses_moves: bool = True
    uses_leadership: bool = False


def _capacity(name: str, resource: Resource) -> GoalSpec:
    # Reference: goals/CapacityGoal.java:41 + resource bindings
    # (CpuCapacityGoal.java:12, DiskCapacityGoal, NetworkIn/OutboundCapacityGoal).
    return GoalSpec(name=name, kind="capacity", is_hard=True, resource=int(resource),
                    uses_moves=True, uses_leadership=resource in (Resource.CPU, Resource.NW_OUT))


def _distribution(name: str, resource: Resource) -> GoalSpec:
    # Reference: goals/ResourceDistributionGoal.java:55 + bindings.
    return GoalSpec(name=name, kind="resource_distribution", is_hard=False, resource=int(resource),
                    uses_moves=True, uses_leadership=resource in (Resource.CPU, Resource.NW_OUT))


GOAL_SPECS: Dict[str, GoalSpec] = {
    "RackAwareGoal": GoalSpec("RackAwareGoal", "rack", is_hard=True),
    # Relaxed rack distribution (goals/RackAwareDistributionGoal.java:65):
    # same kernel family with even-distribution limits.
    "RackAwareDistributionGoal": GoalSpec("RackAwareDistributionGoal", "rack_distribution",
                                          is_hard=True),
    "ReplicaCapacityGoal": GoalSpec("ReplicaCapacityGoal", "replica_capacity", is_hard=True),
    "DiskCapacityGoal": _capacity("DiskCapacityGoal", Resource.DISK),
    "NetworkInboundCapacityGoal": _capacity("NetworkInboundCapacityGoal", Resource.NW_IN),
    "NetworkOutboundCapacityGoal": _capacity("NetworkOutboundCapacityGoal", Resource.NW_OUT),
    "CpuCapacityGoal": _capacity("CpuCapacityGoal", Resource.CPU),
    "ReplicaDistributionGoal": GoalSpec("ReplicaDistributionGoal", "replica_distribution"),
    "PotentialNwOutGoal": GoalSpec("PotentialNwOutGoal", "potential_nw_out"),
    "DiskUsageDistributionGoal": _distribution("DiskUsageDistributionGoal", Resource.DISK),
    "NetworkInboundUsageDistributionGoal": _distribution(
        "NetworkInboundUsageDistributionGoal", Resource.NW_IN),
    "NetworkOutboundUsageDistributionGoal": _distribution(
        "NetworkOutboundUsageDistributionGoal", Resource.NW_OUT),
    "CpuUsageDistributionGoal": _distribution("CpuUsageDistributionGoal", Resource.CPU),
    "TopicReplicaDistributionGoal": GoalSpec("TopicReplicaDistributionGoal",
                                             "topic_replica_distribution"),
    "LeaderReplicaDistributionGoal": GoalSpec("LeaderReplicaDistributionGoal",
                                              "leader_replica_distribution",
                                              uses_moves=True, uses_leadership=True),
    "LeaderBytesInDistributionGoal": GoalSpec("LeaderBytesInDistributionGoal",
                                              "leader_bytes_in", uses_moves=False,
                                              uses_leadership=True),
    # PreferredLeaderElectionGoal, MinTopicLeadersPerBrokerGoal and the
    # kafka-assigner modes are added together with their kernels; the registry
    # only advertises goals whose kernel families exist.
}


def goals_by_priority(names: Sequence[str]) -> List[GoalSpec]:
    """Resolve goal names (short or fully qualified) in priority order
    (KafkaCruiseControlUtils.goalsByPriority analogue)."""
    out = []
    for name in names:
        short = name.rsplit(".", 1)[-1]
        if short not in GOAL_SPECS:
            raise ValueError(f"Unknown goal {name!r}")
        out.append(GOAL_SPECS[short])
    return out
