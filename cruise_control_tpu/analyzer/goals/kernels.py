"""Vectorized goal semantics.

Every goal family from the reference (SURVEY.md §2.3) is implemented here as
a set of pure functions over the tensor model + per-step broker aggregates:

- ``broker_metric`` / ``limits`` / ``violated_brokers`` — the goal's
  per-broker balance quantity and its [lower, upper] band (reference:
  GoalUtils.computeResourceUtilizationBalanceThreshold and each goal's
  ``initGoalState``).
- ``self_feasible`` — may *this* goal apply a candidate while optimizing
  itself (reference: ``selfSatisfied``, AbstractGoal.java:224-266).
- ``accepts`` — would this goal, already optimized, veto the candidate
  (reference: ``actionAcceptance``, Goal.java:39; evaluated for all
  previously-optimized goals at AnalyzerUtils.java:117).
- ``score`` — improvement the candidate brings to this goal (the batched
  generalization of the greedy accept-first-improvement loop: we score ALL
  candidates and apply the best non-conflicting subset).
- ``source_pressure`` / ``dest_room`` / ``source_replica_relevance`` —
  candidate-generation hints replacing the reference's sorted-replica /
  PriorityQueue broker selection (ResourceDistributionGoal.java:383-535).

All functions are shape-polymorphic over K (candidate count) and compile to
a single fused XLA graph per goal kind; ``GoalSpec`` fields are static.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import Array

from cruise_control_tpu.analyzer.actions import ActionType, Candidates
from cruise_control_tpu.analyzer.balancing_constraint import BALANCE_MARGIN, BalancingConstraint
from cruise_control_tpu.analyzer.goals.specs import GoalSpec
from cruise_control_tpu.analyzer.state import BrokerArrays
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.tensor_model import BrokerState, TensorClusterModel

_BIG = 1e30
_OFFLINE_BONUS = 1e12  # healing moves (offline replicas off dead brokers) dominate


def _margin_pct(threshold: float) -> float:
    """Margin-adjusted balance percentage (BalancingConstraint.balance_percentage
    semantics applied to count/byte thresholds)."""
    return (threshold - 1.0) * BALANCE_MARGIN + 1.0


# ---------------------------------------------------------------------------
# Per-broker metric and limits
# ---------------------------------------------------------------------------

def broker_metric(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                  constraint: BalancingConstraint) -> Array:
    """f32[B] — the quantity the goal balances / caps."""
    kind = spec.kind
    if kind == "capacity" or kind == "resource_distribution":
        return arrays.load[:, spec.resource]
    if kind == "replica_capacity" or kind == "replica_distribution":
        return arrays.replica_count.astype(jnp.float32)
    if kind == "leader_replica_distribution":
        return arrays.leader_count.astype(jnp.float32)
    if kind == "potential_nw_out":
        return arrays.potential_nw_out
    if kind == "leader_bytes_in":
        return arrays.leader_bytes_in
    if kind in ("rack", "rack_distribution"):
        # Number of rack-conflicted replicas hosted per broker.
        conflict = _replica_rack_conflict(spec, model)
        from cruise_control_tpu.ops.segment import masked_segment_count
        return masked_segment_count(model.replica_broker, model.num_brokers,
                                    model.replica_valid & conflict).astype(jnp.float32)
    if kind == "topic_replica_distribution":
        tbc = model.topic_broker_replica_counts().astype(jnp.float32)
        lower_t, upper_t = _topic_limits(model, arrays, constraint)
        excess = jnp.maximum(tbc - upper_t[:, None], 0.0) + jnp.maximum(lower_t[:, None] - tbc, 0.0)
        return excess.sum(axis=0)
    if kind == "preferred_leader":
        # Count of wrongly-led partitions whose current leader sits on the
        # broker (PreferredLeaderElectionGoal.java:36).
        wrong = _wrong_leader_mask(model)
        from cruise_control_tpu.ops.segment import masked_segment_count
        return masked_segment_count(model.replica_broker, model.num_brokers,
                                    wrong).astype(jnp.float32)
    if kind == "min_topic_leaders":
        return _min_topic_leader_shortfall(model, arrays, constraint)
    if kind in ("intra_disk_capacity", "intra_disk_distribution"):
        # Per-broker: total excess over its disks' bands.  Everything still
        # sitting on a dead disk (capacity < 0) is excess — the hard goal
        # must NOT report satisfied while replicas are stranded there.
        disk_load = model.disk_load()
        lo_d, up_d = _disk_limits(spec, model, constraint)
        excess = jnp.maximum(disk_load - up_d, 0.0) + jnp.maximum(lo_d - disk_load, 0.0)
        dead = model.disk_capacity < 0.0
        excess = jnp.where(dead, disk_load, excess)
        excess = jnp.where(model.disk_valid, excess, 0.0)
        from cruise_control_tpu.ops.segment import masked_segment_sum
        return masked_segment_sum(excess, model.disk_broker, model.num_brokers,
                                  model.disk_valid)
    raise NotImplementedError(f"goal kind {kind}")


def _wrong_leader_mask(model: TensorClusterModel) -> Array:
    """bool[R] — replica currently leads a partition whose preferred replica
    is a different, online, non-demoted replica; OR leads from a DEMOTED
    broker (the demote path: DemoteBrokerRunnable runs
    PreferredLeaderElectionGoal to force ALL leadership off demoted brokers,
    handler/async/runnable/DemoteBrokerRunnable.java)."""
    preferred = model.preferred_leader_replica()[model.replica_partition]
    r_idx = jnp.arange(model.num_replicas_padded, dtype=jnp.int32)
    safe_pref = jnp.maximum(preferred, 0)
    pref_broker = model.replica_broker[safe_pref]
    pref_ok = (model.replica_valid[safe_pref]
               & ~model.replica_offline_now()[safe_pref]
               & (model.broker_state[pref_broker] != BrokerState.DEMOTED)
               & (preferred >= 0))
    on_demoted = model.broker_state[model.replica_broker] == BrokerState.DEMOTED
    return (model.replica_is_leader & model.replica_valid
            & (((preferred != r_idx) & pref_ok) | on_demoted))


def _designated_topic_mask(model: TensorClusterModel,
                           constraint: BalancingConstraint) -> Array:
    """bool[T] — topics designated for min-leader enforcement.  The set is
    config-static in the reference (topics.with.min.leaders.per.broker), so
    it lives on the frozen constraint as topic ids."""
    mask = jnp.zeros((model.num_topics,), bool)
    ids = [t for t in constraint.min_leader_topic_ids if t < model.num_topics]
    if ids:
        mask = mask.at[jnp.asarray(ids, jnp.int32)].set(True)
    return mask


def _min_topic_leader_shortfall(model: TensorClusterModel, arrays: BrokerArrays,
                                constraint: BalancingConstraint) -> Array:
    """f32[B] — sum over designated topics of max(0, min - leaders(t, b))
    for alive brokers (MinTopicLeadersPerBrokerGoal.java:50)."""
    tlc = model.topic_leader_counts().astype(jnp.float32)  # [T, B]
    need = float(constraint.min_topic_leaders_per_broker)
    designated = _designated_topic_mask(model, constraint)[:, None]  # [T, 1]
    shortfall = jnp.where(designated, jnp.maximum(need - tlc, 0.0), 0.0).sum(axis=0)
    return jnp.where(arrays.alive, shortfall, 0.0)


def _disk_limits(spec: GoalSpec, model: TensorClusterModel,
                 constraint: BalancingConstraint):
    """(lower f32[D], upper f32[D]) bands on the disk axis.

    ``intra_disk_capacity`` (IntraBrokerDiskCapacityGoal.java:42): usage ≤
    capacity · threshold, no lower bound.  ``intra_disk_distribution``
    (IntraBrokerDiskUsageDistributionGoal.java:47): each disk within ± the
    DISK balance threshold of its broker's mean utilization percentage.
    """
    cap = jnp.maximum(model.disk_capacity, 1e-9)
    if spec.kind == "intra_disk_capacity":
        upper = cap * constraint.capacity_threshold[Resource.DISK]
        return jnp.zeros_like(upper), upper
    disk_load = model.disk_load()
    from cruise_control_tpu.ops.segment import masked_segment_sum
    ok = model.disk_valid & (model.disk_capacity > 0)
    broker_load_d = masked_segment_sum(disk_load, model.disk_broker,
                                       model.num_brokers, ok)
    broker_cap_d = jnp.maximum(masked_segment_sum(
        jnp.where(ok, model.disk_capacity, 0.0), model.disk_broker,
        model.num_brokers, ok), 1e-9)
    avg_pct = (broker_load_d / broker_cap_d)[model.disk_broker]
    bp = constraint.balance_percentage(Resource.DISK)
    return avg_pct * (2.0 - bp) * cap, avg_pct * bp * cap


def limits(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
           constraint: BalancingConstraint):
    """(lower f32[B], upper f32[B]) band for the goal metric."""
    kind = spec.kind
    B = arrays.load.shape[0]
    zero = jnp.zeros((B,), jnp.float32)
    if kind == "capacity":
        upper = arrays.capacity[:, spec.resource] * constraint.capacity_threshold[spec.resource]
        return zero, upper
    if kind == "potential_nw_out":
        upper = arrays.capacity[:, Resource.NW_OUT] * constraint.capacity_threshold[Resource.NW_OUT]
        return zero, upper
    if kind == "replica_capacity":
        return zero, jnp.full((B,), float(constraint.max_replicas_per_broker), jnp.float32)
    if kind == "resource_distribution":
        res = spec.resource
        bp = constraint.balance_percentage(res)
        total_util = jnp.where(arrays.alive, arrays.load[:, res], 0.0).sum()
        total_cap = jnp.maximum(jnp.where(arrays.alive, arrays.capacity[:, res], 0.0).sum(), 1e-9)
        avg_pct = total_util / total_cap
        # Low-utilization gating (ResourceDistributionGoal.initGoalState
        # :238-281): below the threshold the cluster counts as balanced.
        low = constraint.low_utilization_threshold[res]
        gated = avg_pct <= low
        upper = jnp.where(gated, _BIG, avg_pct * bp * arrays.capacity[:, res])
        lower = jnp.where(gated, 0.0, avg_pct * (2.0 - bp) * arrays.capacity[:, res])
        return jnp.maximum(lower, 0.0), upper
    if kind == "replica_distribution":
        bp = _margin_pct(constraint.replica_count_balance_threshold)
        avg = jnp.where(arrays.alive, arrays.replica_count, 0).sum() / arrays.num_alive
        return jnp.broadcast_to(jnp.floor(avg * (2.0 - bp)), (B,)), \
            jnp.broadcast_to(jnp.ceil(avg * bp), (B,))
    if kind == "leader_replica_distribution":
        bp = _margin_pct(constraint.leader_replica_count_balance_threshold)
        avg = jnp.where(arrays.alive, arrays.leader_count, 0).sum() / arrays.num_alive
        return jnp.broadcast_to(jnp.floor(avg * (2.0 - bp)), (B,)), \
            jnp.broadcast_to(jnp.ceil(avg * bp), (B,))
    if kind == "leader_bytes_in":
        bp = _margin_pct(constraint.resource_balance_threshold[Resource.NW_IN])
        avg = jnp.where(arrays.alive, arrays.leader_bytes_in, 0.0).sum() / arrays.num_alive
        # Cap-only goal: LeaderBytesInDistributionGoal balances the top end.
        return zero, jnp.broadcast_to(avg * bp, (B,))
    if kind in ("rack", "rack_distribution", "topic_replica_distribution",
                "preferred_leader", "min_topic_leaders",
                "intra_disk_capacity", "intra_disk_distribution"):
        # Metric is a violation count/excess; the band is exactly zero.
        return zero, zero
    raise NotImplementedError(f"goal kind {kind}")


def violated_brokers(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                     constraint: BalancingConstraint) -> Array:
    """bool[B] brokers currently violating the goal (incl. dead brokers that
    still host replicas — those must be healed by hard goals)."""
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = limits(spec, model, arrays, constraint)
    eps = _metric_epsilon(spec)
    out_of_band = (metric > upper + eps) | (metric < lower - eps)
    dead_with_replicas = (~arrays.alive) & arrays.valid & (arrays.replica_count > 0)
    if spec.is_hard:
        return (arrays.alive & out_of_band) | dead_with_replicas
    return arrays.alive & out_of_band


def goal_satisfied(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                   constraint: BalancingConstraint) -> Array:
    return ~violated_brokers(spec, model, arrays, constraint).any()


def _metric_epsilon(spec: GoalSpec) -> float:
    if spec.kind in ("capacity", "resource_distribution"):
        return Resource(spec.resource).epsilon * 1e-3
    if spec.kind in ("potential_nw_out", "leader_bytes_in"):
        return Resource.NW_OUT.epsilon * 1e-3
    if spec.kind in ("intra_disk_capacity", "intra_disk_distribution"):
        return Resource.DISK.epsilon * 1e-3
    return 1e-6  # count-based metrics are integral


# ---------------------------------------------------------------------------
# Candidate metric deltas
# ---------------------------------------------------------------------------

def _candidate_deltas(spec: GoalSpec, cand: Candidates):
    """(d_src f32[K], d_dest f32[K]) — change in the goal metric on the
    source / destination broker if the candidate applies."""
    kind = spec.kind
    if kind in ("capacity", "resource_distribution"):
        return cand.delta_src[:, spec.resource], cand.delta_dest[:, spec.resource]
    if kind in ("replica_capacity", "replica_distribution"):
        d = cand.d_replica_count.astype(jnp.float32)
        return -d, d
    if kind == "leader_replica_distribution":
        d = cand.d_leader_count.astype(jnp.float32)
        return -d, d
    if kind == "potential_nw_out":
        return -cand.d_potential_nw_out, cand.d_potential_nw_out
    if kind == "leader_bytes_in":
        return -cand.d_leader_bytes_in_src, cand.d_leader_bytes_in_dest
    raise NotImplementedError(f"goal kind {kind}")


# ---------------------------------------------------------------------------
# Rack machinery
# ---------------------------------------------------------------------------

def _sibling_info(model: TensorClusterModel, replica_ids: Array):
    """For each candidate replica: its siblings' replica ids / brokers /
    racks (i32[K, max_rf]) with a validity mask excluding itself and pads."""
    parts = model.replica_partition[replica_ids]
    sib = model.partition_replicas[parts]  # i32[K, max_rf]
    sib_valid = (sib >= 0) & (sib != replica_ids[:, None])
    sib_safe = jnp.where(sib >= 0, sib, 0)
    sib_broker = model.replica_broker[sib_safe]
    sib_rack = model.broker_rack[sib_broker]
    return sib, sib_broker, sib_rack, sib_valid


def _replica_rack_conflict(spec: GoalSpec, model: TensorClusterModel) -> Array:
    """bool[R] — replica violates rack placement.

    ``rack`` (RackAwareGoal.java:33): a replica conflicts when a sibling of
    its partition shares its rack; only the higher-id replica of each
    conflicting pair is flagged (so one of the pair stays put).
    ``rack_distribution`` (RackAwareDistributionGoal.java:65): a replica
    conflicts when its rack hosts more than ceil(RF / num_racks) replicas of
    the partition.
    """
    R = model.num_replicas_padded
    r_idx = jnp.arange(R, dtype=jnp.int32)
    sib, _, sib_rack, sib_valid = _sibling_info(model, r_idx)
    own_rack = model.broker_rack[model.replica_broker]
    same_rack = sib_valid & (sib_rack == own_rack[:, None])
    if spec.kind == "rack":
        conflict = (same_rack & (sib < r_idx[:, None])).any(axis=1)
    else:
        rf = model.partition_replication_factor()[model.replica_partition]
        allowed = jnp.ceil(rf / model.num_racks)
        # Keep the `allowed` lowest-id replicas per (partition, rack); any
        # replica ranked at or past the quota is excess and must move.
        rank_in_rack = (same_rack & (sib < r_idx[:, None])).sum(axis=1)
        conflict = rank_in_rack >= allowed
    return conflict & model.replica_valid


def _move_rack_ok(spec: GoalSpec, model: TensorClusterModel, cand: Candidates) -> Array:
    """bool[K] — replica move does not (re)create a rack violation."""
    return _rack_ok_for(spec, model, cand.replica, cand.dest, cand.partition)


def _rack_ok_for(spec: GoalSpec, model: TensorClusterModel, replica: Array,
                 dest: Array, partition: Array) -> Array:
    """Rack legality of moving ``replica`` onto ``dest`` (one swap leg or a
    plain move)."""
    sib, _, sib_rack, sib_valid = _sibling_info(model, replica)
    dest_rack = model.broker_rack[dest]
    same_as_dest = sib_valid & (sib_rack == dest_rack[:, None])
    if spec.kind == "rack":
        return ~same_as_dest.any(axis=1)
    rf = model.partition_replication_factor()[partition]
    allowed = jnp.ceil(rf / model.num_racks)
    return (1 + same_as_dest.sum(axis=1)) <= allowed


def _swap_rack_ok(spec: GoalSpec, model: TensorClusterModel, cand: Candidates) -> Array:
    """Both swap legs rack-legal (r1 → dest AND r2 → src)."""
    r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
    fwd = _rack_ok_for(spec, model, cand.replica, cand.dest, cand.partition)
    rev = _rack_ok_for(spec, model, r2, cand.src, cand.partition2)
    return fwd & rev


# ---------------------------------------------------------------------------
# Feasibility / acceptance / score
# ---------------------------------------------------------------------------

def _src_unhealthy(model: TensorClusterModel, cand: Candidates, arrays: BrokerArrays) -> Array:
    """Source broker dead or the replica itself offline — healing moves."""
    return (~arrays.alive[cand.src]) | model.replica_offline_now()[cand.replica]


def self_feasible(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                  cand: Candidates, constraint: BalancingConstraint,
                  bands=None) -> Array:
    """bool[K] — candidate is a legal self-improvement for this goal
    (selfSatisfied + per-goal move eligibility).  ``bands`` optionally
    supplies this goal's precomputed (lower, upper) limits — the band sides
    are step-invariant, so the fixpoint hoists them out of the loop body
    (optimizer.compute_step_invariants)."""
    kind = spec.kind
    unhealthy = _src_unhealthy(model, cand, arrays)
    if kind == "preferred_leader":
        # Leadership transfers to the partition's preferred replica — or,
        # when the source broker is DEMOTED, to ANY eligible non-demoted
        # sibling (the candidate generator already excludes demoted/dead/
        # excluded destinations).
        preferred = model.preferred_leader_replica()[cand.partition]
        wrong = _wrong_leader_mask(model)[cand.replica]
        src_demoted = model.broker_state[cand.src] == BrokerState.DEMOTED
        return (cand.is_leadership() & wrong
                & ((cand.dest_replica == preferred) | src_demoted))
    if kind == "min_topic_leaders":
        return _min_leader_feasible(model, arrays, cand, constraint, unhealthy)
    if kind in ("intra_disk_capacity", "intra_disk_distribution"):
        return _intra_disk_feasible(spec, model, cand, constraint)
    if kind in ("rack", "rack_distribution"):
        conflict = _replica_rack_conflict(spec, model)[cand.replica]
        ok_dest = _move_rack_ok(spec, model, cand)
        return cand.is_move() & (conflict | unhealthy) & ok_dest
    if kind == "topic_replica_distribution":
        lower_t, upper_t = _topic_limits(model, arrays, constraint)
        tbc = model.topic_broker_replica_counts()
        t = model.replica_topic[cand.replica]
        c_src = tbc[t, cand.src].astype(jnp.float32)
        c_dest = tbc[t, cand.dest].astype(jnp.float32)
        up = upper_t[t]
        lo = lower_t[t]
        helps = (c_src > up) | (c_dest < lo) | unhealthy
        stays = (c_dest + 1 <= up) & ((c_src - 1 >= lo) | unhealthy)
        return cand.is_move() & helps & stays
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = bands if bands is not None else \
        limits(spec, model, arrays, constraint)
    d_src, d_dest = _candidate_deltas(spec, cand)
    src_m, dest_m = metric[cand.src], metric[cand.dest]
    src_after, dest_after = src_m + d_src, dest_m + d_dest
    # The same epsilon tolerance as goal_satisfied/violated_brokers: a goal
    # that reads satisfied must have an EMPTY feasible set (the fixpoint's
    # satisfied-skip shortcut relies on that invariant exactly).
    eps = _metric_epsilon(spec)
    src_over = src_m > upper[cand.src] + eps
    dest_under = dest_m < lower[cand.dest] - eps
    helps = src_over | dest_under | unhealthy
    dest_ok = dest_after <= upper[cand.dest]
    src_ok = (src_after >= lower[cand.src]) | unhealthy
    moves_something = jnp.abs(d_dest) > 0
    return helps & dest_ok & src_ok & moves_something


def _min_leader_feasible(model: TensorClusterModel, arrays: BrokerArrays,
                         cand: Candidates, constraint: BalancingConstraint,
                         unhealthy: Array) -> Array:
    """Leadership transfer or leader-replica move of a designated topic into
    a broker short of leaders, without starving the source."""
    designated = _designated_topic_mask(model, constraint)
    t = model.replica_topic[cand.replica]
    tlc = model.topic_leader_counts()
    need = constraint.min_topic_leaders_per_broker
    gains_leader = cand.is_leadership() | (cand.is_move() & model.replica_is_leader[cand.replica])
    dest_short = tlc[t, cand.dest] < need
    src_ok = (tlc[t, cand.src] - 1 >= need) | unhealthy
    return designated[t] & gains_leader & dest_short & src_ok


def _intra_disk_feasible(spec: GoalSpec, model: TensorClusterModel,
                         cand: Candidates, constraint: BalancingConstraint) -> Array:
    """Intra-broker disk move out of an over-band (or dead) disk onto a disk
    of the same broker that stays within band after receiving the replica —
    or an intra-broker SWAP whose net exchange brings both disks in band."""
    disk_load = model.disk_load()
    lo_d, up_d = _disk_limits(spec, model, constraint)
    s = jnp.maximum(cand.src_disk, 0)
    d = jnp.maximum(cand.dest_disk, 0)
    contrib = model.replica_load()[cand.replica, Resource.DISK]
    src_dead = model.disk_capacity[s] < 0.0
    # Same epsilon as goal_satisfied: satisfied ⇒ empty feasible set (the
    # fixpoint's satisfied-skip relies on it).
    eps = _metric_epsilon(spec)
    src_over = disk_load[s] > up_d[s] + eps
    dest_under = disk_load[d] < lo_d[d] - eps
    helps = src_over | dest_under | src_dead
    same_broker = model.disk_broker[d] == cand.src
    valid_disks = (cand.src_disk >= 0) & (cand.dest_disk >= 0) & \
        (cand.src_disk != cand.dest_disk)
    dest_ok = (disk_load[d] + contrib <= up_d[d]) & (model.disk_capacity[d] > 0.0)
    src_stays = (disk_load[s] - contrib >= lo_d[s]) | src_dead | src_over
    move_ok = (cand.is_intra_move() & valid_disks & same_broker & helps
               & dest_ok & src_stays)
    # Intra-broker swap: r1 (src disk) exchanges with r2 (dest disk); net
    # transfer = contrib - contrib2 out of src disk into dest disk.
    r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
    contrib2 = model.replica_load()[r2, Resource.DISK]
    net = contrib - contrib2
    swap_dest_ok = (disk_load[d] + net <= up_d[d]) & (model.disk_capacity[d] > 0.0)
    swap_src_ok = ((disk_load[s] - net >= lo_d[s]) | src_dead | src_over) & \
        ((disk_load[s] - net <= up_d[s]) | (net > 0))
    swap_ok = (cand.is_intra_swap() & valid_disks & same_broker & helps
               & swap_dest_ok & swap_src_ok)
    return move_ok | swap_ok


def accepts(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
            cand: Candidates, constraint: BalancingConstraint) -> Array:
    """bool[K] — this (already optimized) goal does not veto the candidate
    (actionAcceptance; reference evaluates these for every previously
    optimized goal before applying an action, AnalyzerUtils.java:117)."""
    kind = spec.kind
    if kind == "preferred_leader":
        # Reference parity: PreferredLeaderElectionGoal.actionAcceptance
        # returns ACCEPT unconditionally (PreferredLeaderElectionGoal.java) —
        # it is a one-shot election pass, not a standing constraint, so later
        # leadership goals stay free to move leaders.
        return jnp.ones(cand.k, bool)
    if kind == "min_topic_leaders":
        # Veto actions that starve a designated topic's source broker.
        designated = _designated_topic_mask(model, constraint)
        t = model.replica_topic[cand.replica]
        loses_leader = cand.is_leadership() | \
            ((cand.is_move() | cand.is_swap()) & model.replica_is_leader[cand.replica])
        tlc = model.topic_leader_counts()
        need = constraint.min_topic_leaders_per_broker
        starves = designated[t] & loses_leader & \
            (tlc[t, cand.src] - 1 < need) & arrays.alive[cand.src]
        # Swap reverse leg: a designated leader r2 leaving dest.
        r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
        t2 = model.replica_topic[r2]
        starves2 = cand.is_swap() & designated[t2] & model.replica_is_leader[r2] & \
            (tlc[t2, cand.dest] - 1 < need) & arrays.alive[cand.dest]
        return ~(starves | starves2)
    if kind in ("intra_disk_capacity", "intra_disk_distribution"):
        # Veto moves landing on a disk that would overflow its band.
        disk_load = model.disk_load()
        _, up_d = _disk_limits(spec, model, constraint)
        d = jnp.maximum(cand.dest_disk, 0)
        contrib = model.replica_load()[cand.replica, Resource.DISK]
        r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
        contrib2 = model.replica_load()[r2, Resource.DISK]
        is_swap = cand.is_swap() | cand.is_intra_swap()
        # Swap legs: r1 lands on r2's disk (net contrib - contrib2) and r2
        # lands on r1's disk (net contrib2 - contrib).
        net_in = jnp.where(is_swap, contrib - contrib2, contrib)
        changes_disk = (cand.is_move() | cand.is_intra_move() | is_swap) & \
            (cand.dest_disk >= 0)
        over = disk_load[d] + net_in > up_d[d]
        s = jnp.maximum(cand.src_disk, 0)
        over_rev = is_swap & (cand.src_disk >= 0) & \
            (disk_load[s] + contrib2 - contrib > up_d[s])
        return ~((changes_disk & over) | over_rev)
    if kind in ("rack", "rack_distribution"):
        return jnp.where(cand.is_move(), _move_rack_ok(spec, model, cand),
                         jnp.where(cand.is_swap(),
                                   _swap_rack_ok(spec, model, cand), True))
    if kind == "topic_replica_distribution":
        lower_t, upper_t = _topic_limits(model, arrays, constraint)
        tbc = model.topic_broker_replica_counts()
        t = model.replica_topic[cand.replica]
        c_src = tbc[t, cand.src].astype(jnp.float32)
        c_dest = tbc[t, cand.dest].astype(jnp.float32)
        ok = (c_dest + 1 <= upper_t[t]) & (c_src - 1 >= lower_t[t])
        # Swap: r1's topic count shifts src→dest AND r2's dest→src.
        r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
        t2 = model.replica_topic[r2]
        c2_src = tbc[t2, cand.src].astype(jnp.float32)
        c2_dest = tbc[t2, cand.dest].astype(jnp.float32)
        swap_ok = ok & (c2_src + 1 <= upper_t[t2]) & (c2_dest - 1 >= lower_t[t2])
        return jnp.where(cand.is_move(), ok,
                         jnp.where(cand.is_swap(), swap_ok, True))
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = limits(spec, model, arrays, constraint)
    d_src, d_dest = _candidate_deltas(spec, cand)
    dest_after = metric[cand.dest] + d_dest
    src_after = metric[cand.src] + d_src
    # Both legs bound against the upper limit: a swap's net exchange can GAIN
    # load on the source broker (d_src = -d_dest > 0), which must not push it
    # over an already-optimized cap (CapacityGoal.actionAcceptance evaluates
    # both brokers of an INTER_BROKER_REPLICA_SWAP).  For plain moves
    # d_src <= 0, so the source-side check passes trivially.
    dest_ok = (dest_after <= upper[cand.dest]) | (d_dest <= 0)
    src_cap_ok = (src_after <= upper[cand.src]) | (d_src <= 0)
    if spec.is_hard or kind in ("potential_nw_out", "leader_bytes_in"):
        # Cap-style goals bound only the upper limit — on BOTH brokers.
        return dest_ok & src_cap_ok
    src_ok = (src_after >= lower[cand.src]) | (d_src >= 0) | (~arrays.alive[cand.src])
    dest_low_ok = (dest_after >= lower[cand.dest]) | (d_dest >= 0)
    return dest_ok & src_cap_ok & src_ok & dest_low_ok


def score(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
          cand: Candidates, constraint: BalancingConstraint,
          bands=None) -> Array:
    """f32[K] — improvement of the goal objective (higher is better; > 0
    required to apply).  Healing moves get a dominating bonus so offline
    replicas drain first (GoalUtils.ensureNoOfflineReplicas semantics).
    ``bands`` optionally supplies the precomputed (lower, upper) limits."""
    kind = spec.kind
    unhealthy = _src_unhealthy(model, cand, arrays)
    bonus = jnp.where(unhealthy & cand.is_move(), _OFFLINE_BONUS, 0.0)
    if kind == "preferred_leader":
        preferred = model.preferred_leader_replica()[cand.partition]
        wrong = _wrong_leader_mask(model)[cand.replica]
        src_demoted = model.broker_state[cand.src] == BrokerState.DEMOTED
        to_pref = cand.dest_replica == preferred
        fixes = cand.is_leadership() & wrong & (to_pref | src_demoted)
        # Prefer the preferred replica when eligible; any other sibling still
        # counts as a fix when draining a demoted broker.
        return jnp.where(fixes, jnp.where(to_pref, 1.0, 0.5), 0.0)
    if kind == "min_topic_leaders":
        tlc = model.topic_leader_counts().astype(jnp.float32)
        t = model.replica_topic[cand.replica]
        need = float(constraint.min_topic_leaders_per_broker)
        designated = _designated_topic_mask(model, constraint)[t]
        gain = jnp.minimum(jnp.maximum(need - tlc[t, cand.dest], 0.0), 1.0)
        loss = jnp.maximum(need - (tlc[t, cand.src] - 1.0), 0.0) \
            - jnp.maximum(need - tlc[t, cand.src], 0.0)
        return jnp.where(designated, gain - jnp.minimum(loss, 1.0), 0.0) + bonus
    if kind in ("intra_disk_capacity", "intra_disk_distribution"):
        disk_load = model.disk_load()
        lo_d, up_d = _disk_limits(spec, model, constraint)
        s = jnp.maximum(cand.src_disk, 0)
        d = jnp.maximum(cand.dest_disk, 0)
        contrib = model.replica_load()[cand.replica, Resource.DISK]
        r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
        contrib2 = model.replica_load()[r2, Resource.DISK]
        # Net disk transfer: full contribution for a move, the exchange
        # difference for an intra-broker swap.
        net = jnp.where(cand.is_intra_swap(), contrib - contrib2, contrib)

        def dev(load, disk):
            return jnp.maximum(load - up_d[disk], 0.0) + \
                jnp.maximum(lo_d[disk] - load, 0.0)

        before = dev(disk_load[s], s) + dev(disk_load[d], d)
        after = dev(disk_load[s] - net, s) + dev(disk_load[d] + net, d)
        dead_bonus = jnp.where(model.disk_capacity[s] < 0.0, _OFFLINE_BONUS, 0.0)
        return jnp.where(cand.is_intra_move() | cand.is_intra_swap(),
                         before - after + dead_bonus, 0.0)
    if kind in ("rack", "rack_distribution"):
        sib, _, sib_rack, sib_valid = _sibling_info(model, cand.replica)
        own_rack = model.broker_rack[cand.src]
        dest_rack = model.broker_rack[cand.dest]
        before = (sib_valid & (sib_rack == own_rack[:, None])).sum(axis=1)
        after = (sib_valid & (sib_rack == dest_rack[:, None])).sum(axis=1)
        return (before - after).astype(jnp.float32) + bonus
    if kind == "topic_replica_distribution":
        tbc = model.topic_broker_replica_counts().astype(jnp.float32)
        t = model.replica_topic[cand.replica]
        avg_t = _topic_avg(model, arrays)[t]
        c_src = tbc[t, cand.src]
        c_dest = tbc[t, cand.dest]
        before = (c_src - avg_t) ** 2 + (c_dest - avg_t) ** 2
        after = (c_src - 1 - avg_t) ** 2 + (c_dest + 1 - avg_t) ** 2
        return (before - after) + bonus
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = bands if bands is not None else \
        limits(spec, model, arrays, constraint)
    d_src, d_dest = _candidate_deltas(spec, cand)
    src_m, dest_m = metric[cand.src], metric[cand.dest]
    if kind in ("capacity", "potential_nw_out", "replica_capacity"):
        # Threshold goals: reduction in total excess over the cap.
        def excess(m, b):
            return jnp.maximum(m - upper[b], 0.0)
        before = excess(src_m, cand.src) + excess(dest_m, cand.dest)
        after = excess(src_m + d_src, cand.src) + excess(dest_m + d_dest, cand.dest)
        return before - after + bonus
    # Distribution goals: reduction in squared deviation from the per-broker
    # target (mean utilization scaled to broker capacity).
    target = (lower + upper) * 0.5
    target = jnp.where(upper >= _BIG, metric, target)  # gated: no preference
    before = (src_m - target[cand.src]) ** 2 + (dest_m - target[cand.dest]) ** 2
    after = (src_m + d_src - target[cand.src]) ** 2 + (dest_m + d_dest - target[cand.dest]) ** 2
    return before - after + bonus


# ---------------------------------------------------------------------------
# Candidate-generation hints
# ---------------------------------------------------------------------------

def source_pressure(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                    constraint: BalancingConstraint, bands=None) -> Array:
    """f32[B] — how urgently each broker needs to shed (goal metric above
    upper limit; dead brokers get a dominating value)."""
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = bands if bands is not None else \
        limits(spec, model, arrays, constraint)
    over = jnp.maximum(metric - upper, 0.0)
    scale = jnp.maximum(jnp.abs(upper), 1.0)
    pressure = over / scale
    # Pull mechanism (rebalanceByMovingLoadIn,
    # ResourceDistributionGoal.java:446-535): when some broker sits below the
    # lower limit, in-band brokers above the band midpoint become donors too
    # (weakly, so genuinely overloaded brokers still rank first).
    eps = _metric_epsilon(spec)
    under_exists = (arrays.alive & (metric < lower - eps)).any()
    # Low-utilization-gated goals (upper == _BIG) have no meaningful band
    # midpoint: neutralize the donor term there (same pattern as score()).
    target = jnp.where(upper >= _BIG, metric, (lower + upper) * 0.5)
    donor = jnp.maximum(metric - target, 0.0) / scale * 0.01
    pressure = pressure + jnp.where(under_exists, donor, 0.0)
    dead = (~arrays.alive) & arrays.valid & (arrays.replica_count > 0)
    return jnp.where(dead, _BIG, jnp.where(arrays.valid, pressure, -_BIG))


def dest_room(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
              constraint: BalancingConstraint, bands=None) -> Array:
    """f32[B] — headroom under the goal's upper limit (candidate dests)."""
    if spec.kind == "min_topic_leaders":
        # Destinations are exactly the brokers short of designated leaders.
        shortfall = _min_topic_leader_shortfall(model, arrays, constraint)
        return jnp.where(arrays.alive, shortfall, -_BIG)
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = bands if bands is not None else \
        limits(spec, model, arrays, constraint)
    room = jnp.minimum(upper, _BIG) - metric
    # Prefer brokers below the lower limit (they *need* load).
    room = room + jnp.maximum(lower - metric, 0.0) * 10.0
    return jnp.where(arrays.alive, room, -_BIG)


def source_replica_relevance(spec: GoalSpec, model: TensorClusterModel, arrays: BrokerArrays,
                             constraint: BalancingConstraint, bands=None) -> Array:
    """f32[R] — ranking for choosing which replicas to propose moving.
    Combines source-broker pressure with a per-replica tiebreak (bigger
    replicas first, mirroring the reference's load-sorted candidate replicas
    via SortedReplicas, model/SortedReplicas.java:47).  One evaluation is
    ~150 small ops — the step graph computes it ONCE and shares it across
    every candidate batch of the step (``bands`` as in source_pressure)."""
    kind = spec.kind
    if kind == "preferred_leader":
        wrong = _wrong_leader_mask(model)
        return jnp.where(wrong & model.replica_valid, 1.0, -_BIG)
    if kind == "min_topic_leaders":
        # Donor leaders of designated topics on brokers above the minimum
        # (plus any leader when a shortfall exists and the source is dead).
        designated = _designated_topic_mask(model, constraint)[model.replica_topic]
        tlc = model.topic_leader_counts()
        cnt = tlc[model.replica_topic, model.replica_broker]
        need = constraint.min_topic_leaders_per_broker
        donor = model.replica_is_leader & designated & (cnt > need)
        dead_src = ~arrays.alive[model.replica_broker]
        base = jnp.where(donor | (designated & model.replica_is_leader & dead_src),
                         1.0, -_BIG)
        return jnp.where(model.replica_valid, base, -_BIG)
    if kind in ("intra_disk_capacity", "intra_disk_distribution"):
        disk_load = model.disk_load()
        lo_d, up_d = _disk_limits(spec, model, constraint)
        s = jnp.maximum(model.replica_disk, 0)
        on_disk = model.replica_disk >= 0
        over = disk_load[s] > up_d[s]
        dead = model.disk_capacity[s] < 0.0
        # Donors also come from in-band disks when a sibling disk is under.
        broker_has_under = jnp.zeros((model.num_brokers,), bool).at[
            jnp.where(model.disk_valid, model.disk_broker, 0)].max(
            model.disk_valid & (disk_load < lo_d))
        donor = broker_has_under[model.replica_broker] & \
            (disk_load[s] > (lo_d[s] + up_d[s]) * 0.5)
        size = model.replica_load()[:, Resource.DISK]
        scale = jnp.maximum(size.max(), 1e-9)
        base = jnp.where(dead, _BIG,
                         jnp.where(over | donor, 1.0 + 1e-3 * size / scale, -_BIG))
        return jnp.where(model.replica_valid & on_disk, base, -_BIG)
    pressure = source_pressure(spec, model, arrays, constraint,
                               bands=bands)[model.replica_broker]
    if kind in ("rack", "rack_distribution"):
        conflict = _replica_rack_conflict(spec, model)
        base = jnp.where(conflict, 1.0, -_BIG)
    elif kind == "topic_replica_distribution":
        lower_t, upper_t = _topic_limits(model, arrays, constraint)
        tbc = model.topic_broker_replica_counts().astype(jnp.float32)
        t, b = model.replica_topic, model.replica_broker
        c = tbc[t, b]
        over = c > upper_t[t]
        # Donor sourcing: a topic with an under-filled pair must be able to
        # move replicas out of its fullest pairs even when none is over the
        # upper band — otherwise lower-band violations can never heal
        # (the reference's rebalanceByMovingLoadIn pulls from any eligible
        # broker, ResourceDistributionGoal.java:446-535).
        under_exists = ((tbc < lower_t[:, None]) &
                        arrays.alive[None, :]).any(axis=1)
        avg_t = _topic_avg(model, arrays)
        # Strictly-above-average pairs donate (ceil would collapse onto the
        # upper band for small topics, blocking the heal entirely).
        donor = under_exists[t] & (c > avg_t[t])
        relevant = over | donor
        # Rank within the (topic, broker) PAIR, not the broker: a broker
        # with many violating topics must surface one source per topic per
        # step, not its single worst topic (this was 90 of the mid rung's
        # 154 steps).  Scaling the rank by the pair's overage allocates
        # top-S slots PROPORTIONAL to how much each pair must shed, so one
        # step can drain a hot pair to its band instead of 1-2 replicas per
        # step per pair.
        pair = t * model.num_brokers + b
        rank = _within_group_rank(pair, jnp.where(relevant, c, -_BIG))
        overage = jnp.where(over, c - upper_t[t],
                            jnp.maximum(c - avg_t[t], 1.0))
        pnorm = pressure / jnp.maximum(jnp.abs(pressure).max(), 1e-9)
        base = jnp.where(relevant,
                         -(rank.astype(jnp.float32) + 1.0)
                         / jnp.maximum(overage, 1.0)
                         + 0.25 * over + 0.5 * pnorm,
                         -_BIG)
    else:
        relevant = pressure > 0
        if kind in ("leader_replica_distribution", "leader_bytes_in"):
            relevant = relevant & model.replica_is_leader
        tiebreak = _replica_metric_contribution(spec, model)
        scale = jnp.maximum(jnp.abs(tiebreak).max(), 1e-9)
        # Breadth-first source diversity: rank each replica WITHIN its broker
        # (biggest contribution first) and make the rank the dominant key —
        # the top-S batch then covers every pressured broker's best replicas
        # instead of one broker's entire replica list.  Pressure-major
        # ranking serialized shedding one broker at a time (the round-2
        # verdict's 200+-step ReplicaDistribution tail).
        rank = _within_broker_rank(model, jnp.where(relevant, tiebreak, -_BIG))
        pnorm = pressure / jnp.maximum(jnp.abs(pressure).max(), 1e-9)
        base = jnp.where(relevant,
                         -rank.astype(jnp.float32) + 0.5 * pnorm
                         + 1e-3 * tiebreak / scale, -_BIG)
    offline = model.replica_offline_now() | (~arrays.alive[model.replica_broker])
    base = jnp.where(offline, _BIG, base)
    return jnp.where(model.replica_valid, base, -_BIG)


def _within_broker_rank(model: TensorClusterModel, key_desc: Array) -> Array:
    """i32[R] — each replica's position among its broker's replicas when
    ordered by descending ``key_desc`` (0 = broker's best)."""
    return _within_group_rank(model.replica_broker, key_desc)


def _within_group_rank(group: Array, key_desc: Array) -> Array:
    """i32[N] — each row's position among its group's rows when ordered by
    descending ``key_desc`` (0 = group's best)."""
    b = group
    r = b.shape[0]
    order = jnp.lexsort((-key_desc, b))  # group-major, key-desc within
    b_sorted = b[order]
    idx = jnp.arange(r, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.ones((1,), bool),
                                b_sorted[1:] != b_sorted[:-1]])
    seg_start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    pos_sorted = idx - seg_start
    return jnp.zeros((r,), jnp.int32).at[order].set(pos_sorted)


def _replica_metric_contribution(spec: GoalSpec, model: TensorClusterModel) -> Array:
    """f32[R] — each replica's contribution to the goal metric."""
    kind = spec.kind
    load = model.replica_load()
    if kind in ("capacity", "resource_distribution"):
        return load[:, spec.resource]
    if kind == "potential_nw_out":
        return model.replica_load_leader[:, Resource.NW_OUT]
    if kind == "leader_bytes_in":
        return jnp.where(model.replica_is_leader, model.replica_load_leader[:, Resource.NW_IN], 0.0)
    return jnp.ones(load.shape[0], jnp.float32)


# ---------------------------------------------------------------------------
# Topic-level helpers (TopicReplicaDistributionGoal.java:58)
# ---------------------------------------------------------------------------

def _topic_avg(model: TensorClusterModel, arrays: BrokerArrays) -> Array:
    from cruise_control_tpu.ops.segment import masked_segment_count
    totals = masked_segment_count(model.replica_topic, model.num_topics,
                                  model.replica_valid).astype(jnp.float32)
    return totals / arrays.num_alive


def _topic_limits(model: TensorClusterModel, arrays: BrokerArrays,
                  constraint: BalancingConstraint):
    bp = _margin_pct(constraint.topic_replica_count_balance_threshold)
    avg = _topic_avg(model, arrays)
    return jnp.floor(avg * (2.0 - bp)), jnp.ceil(avg * bp)


_BAND_KINDS = ("capacity", "resource_distribution", "replica_capacity",
               "replica_distribution", "leader_replica_distribution",
               "potential_nw_out", "leader_bytes_in")


def frontier_active(spec: GoalSpec, model: TensorClusterModel,
                    arrays: BrokerArrays, constraint: BalancingConstraint) -> Array:
    """bool[B] — the brokers that can matter to this band goal's next steps.

    The active set mirrors the kernels that source and sink the goal's
    actions (band kinds only; structural kinds keep the dense path):

    - out-of-band brokers (``violated_brokers`` semantics, dead-with-replicas
      included) — the shedders and the needy;
    - pull donors: in-band brokers above the band midpoint while some broker
      sits below the lower limit (``source_pressure``'s donor term), taken
      in descending-surplus order until their cumulative surplus covers 2x
      the total under-band deficit — without this gate, ANY broker above
      the midpoint is a donor while one straggler sits under band, and the
      active set stays over half the cluster through the whole tail;
    - receivers: alive brokers with headroom under the upper limit, taken in
      descending-room order until their cumulative room covers 2x the total
      remaining surplus — bounding the receiver set by the remaining
      imbalance instead of the cluster size.

    The mask is a *performance* hint, not a correctness gate: the chunk
    driver (optimizer.frontier_fixpoint) always confirms a compacted
    convergence with a dense chunk before declaring the goal finished.
    """
    B = model.num_brokers
    metric = broker_metric(spec, model, arrays, constraint)
    lower, upper = limits(spec, model, arrays, constraint)
    eps = _metric_epsilon(spec)
    over = arrays.alive & (metric > upper + eps)
    under = arrays.alive & (metric < lower - eps)
    dead = (~arrays.alive) & arrays.valid & (arrays.replica_count > 0)
    under_exists = under.any()
    # Pull donors shed to the band midpoint (neutralized for cap-only goals
    # whose upper side is the _BIG sentinel, as in source_pressure).
    target = jnp.where(upper >= _BIG, metric, (lower + upper) * 0.5)
    shed_to = jnp.where(under_exists, jnp.minimum(target, upper), upper)
    donor = arrays.alive & (metric > shed_to + eps)
    # Remaining surplus: what the shedders (incl. dead brokers' full load)
    # still have to place somewhere.
    surplus = jnp.where(arrays.alive, jnp.maximum(metric - shed_to, 0.0), 0.0)
    surplus = surplus + jnp.where(dead, jnp.maximum(metric, 0.0), 0.0)
    total_surplus = surplus.sum()
    # Gate pull donors by the remaining under-band deficit: the biggest
    # donors whose cumulative surplus covers 2x what the under-band brokers
    # still need (over-band brokers stay active via `over` regardless).
    deficit = jnp.where(under, jnp.maximum(lower - metric, 0.0), 0.0)
    total_deficit = deficit.sum()
    dsur = jnp.where(donor, surplus, 0.0)
    dorder = jnp.argsort(-dsur)
    dsur_sorted = dsur[dorder]
    dcum_before = jnp.cumsum(dsur_sorted) - dsur_sorted
    donor_sorted = (dcum_before < 2.0 * total_deficit) & (dsur_sorted > 0.0)
    donor = jnp.zeros((B,), bool).at[dorder].set(donor_sorted)
    room = jnp.where(arrays.alive & ~over,
                     jnp.maximum(jnp.minimum(upper, _BIG) - metric, 0.0), 0.0)
    order = jnp.argsort(-room)
    room_sorted = room[order]
    cum_before = jnp.cumsum(room_sorted) - room_sorted
    recv_sorted = (cum_before < 2.0 * total_surplus) & (room_sorted > 0.0)
    receivers = jnp.zeros((B,), bool).at[order].set(recv_sorted)
    receivers = receivers & (total_surplus > 0.0)
    return over | under | dead | donor | receivers


def is_band_kind(spec: GoalSpec) -> bool:
    """Specs whose accepts() is the generic band check (metric/limits/delta
    math on the broker axis) — batchable across specs."""
    return spec.kind in _BAND_KINDS


def frontier_active_batch(specs, model: TensorClusterModel,
                          arrays: BrokerArrays,
                          constraint: BalancingConstraint) -> Array:
    """bool[S, B] — ``frontier_active`` for every spec in one fused graph.

    Non-band specs get an all-False row (they run the dense path; their
    "frontier" carries no information).  The stack sweep stacks these rows
    next to the satisfaction bits so ONE dispatch predicts every goal's
    frontier — the inter-goal pipeline's grouping and conflict masks are
    all derived from this matrix.  Like ``frontier_active`` itself the rows
    are performance hints, not correctness gates.
    """
    B = model.num_brokers
    return jnp.stack([
        frontier_active(s, model, arrays, constraint) if is_band_kind(s)
        else jnp.zeros((B,), bool)
        for s in specs])


def accepts_band_batch(specs, model: TensorClusterModel, arrays: BrokerArrays,
                       cand: Candidates, constraint: BalancingConstraint) -> Array:
    """bool[K] — AND of ``accepts`` over all band-kind ``specs``.

    Semantics identical to folding ``accepts`` per spec; the win is op
    count: the per-candidate gathers/compares run ONCE on stacked
    [S, ...] tensors instead of S separate K-sized chains — at goal 15 of
    the stack that's ~10 sequential mask chains collapsed into one, and the
    per-step op-dispatch floor is what bounds optimizer wall-clock on TPU
    (each accept chain is small, serial work).
    """
    specs = [s for s in specs if is_band_kind(s)]
    if not specs:
        return jnp.ones(cand.k, bool)
    metric_rows = [broker_metric(s, model, arrays, constraint) for s in specs]
    lower_rows, upper_rows = [], []
    for s in specs:
        lo, up = limits(s, model, arrays, constraint)
        lower_rows.append(lo)
        upper_rows.append(up)
    dsrc_rows, ddest_rows = [], []
    for s in specs:
        d_src, d_dest = _candidate_deltas(s, cand)
        dsrc_rows.append(d_src)
        ddest_rows.append(d_dest)
    metric = jnp.stack(metric_rows)            # [S, B]
    lower = jnp.stack(lower_rows)              # [S, B]
    upper = jnp.stack(upper_rows)              # [S, B]
    d_src = jnp.stack(dsrc_rows)               # [S, K]
    d_dest = jnp.stack(ddest_rows)             # [S, K]
    cap_style = jnp.asarray(
        [s.is_hard or s.kind in ("potential_nw_out", "leader_bytes_in")
         for s in specs])[:, None]             # [S, 1]

    dest_after = metric[:, cand.dest] + d_dest
    src_after = metric[:, cand.src] + d_src
    # Mirrors accepts(): upper-limit checks on BOTH legs (swap source gains),
    # lower-limit checks on both legs for band goals.
    dest_ok = (dest_after <= upper[:, cand.dest]) | (d_dest <= 0)
    src_cap_ok = (src_after <= upper[:, cand.src]) | (d_src <= 0)
    src_ok = (src_after >= lower[:, cand.src]) | (d_src >= 0) | \
        (~arrays.alive[cand.src])[None, :]
    dest_low_ok = (dest_after >= lower[:, cand.dest]) | (d_dest >= 0)
    return (dest_ok & src_cap_ok & (cap_style | (src_ok & dest_low_ok))).all(axis=0)


# ---------------------------------------------------------------------------
# Bounded-depth exact repair primitives (flat-wall repair)
# ---------------------------------------------------------------------------
# select_batched's budget repair used to be a data-dependent
# ``lax.while_loop`` (drop every violating broker's actions until no
# violation remains) behind ``lax.cond`` gates — its per-step cost grew with
# how close the model sits to the band edges (SHARDED_1M_r05: 167→454 s
# per chunk at constant shape).  These helpers replace it with a FIXED
# op count: per segment (a broker in one role, or a (topic, broker) key),
# binary-search the longest score-ranked prefix of kept candidates whose
# running channel totals stay inside [lo, hi] — log2(K) iterations over
# prefix sums computed once, every iteration a tiny gather/compare.


def bisect_depth(n: int) -> int:
    """Fixed iteration count that lets the prefix bisection resolve any cut
    in [0, n]: ceil(log2(n + 1))."""
    return max(1, math.ceil(math.log2(max(int(n), 1) + 1)))


def _sorted_prefix_tables(score: Array, seg: Array, deltas: Array,
                          kept: Array, cum_before: Array, lo: Array, hi: Array,
                          num_segments: int):
    """Shared precompute: segment-grouped score-DESC order, running channel
    totals, per-position fit flags and their running bad-counts.  The
    relative tolerance mirrors ``optimizer._prefix_admit_role`` exactly
    (bounds span bytes-scale channels where an absolute 1e-6 is below f32
    resolution and count channels near 0 where it is the right size)."""
    K = score.shape[0]
    o1 = jnp.argsort(-score, stable=True)
    o2 = jnp.argsort(seg[o1], stable=True)
    order = o1[o2]
    s_seg = seg[order]
    s_deltas = jnp.where(kept[order][:, None], deltas[order], 0.0)
    cs = jnp.cumsum(s_deltas, axis=0)                        # [K, C]
    seg_start = jnp.full((num_segments,), K, jnp.int32).at[s_seg].min(
        jnp.arange(K, dtype=jnp.int32))
    base = jnp.where((seg_start > 0)[:, None],
                     cs[jnp.maximum(seg_start - 1, 0)], 0.0)
    prefix = cum_before[s_seg] + cs - base[s_seg]            # incl. self
    hi_s = hi[s_seg]
    lo_s = lo[s_seg]
    scale = jnp.maximum(1.0, jnp.maximum(
        jnp.where(jnp.isfinite(hi_s), jnp.abs(hi_s), 0.0),
        jnp.where(jnp.isfinite(lo_s), jnp.abs(lo_s), 0.0)))
    eps = 1e-6 * scale
    ok = ((prefix <= hi_s + eps) & (prefix >= lo_s - eps)).all(axis=1)
    badc = jnp.cumsum((~ok).astype(jnp.int32))               # [K]
    bad_base = jnp.where(seg_start > 0,
                         badc[jnp.maximum(seg_start - 1, 0)], 0)
    seg_count = jnp.zeros((num_segments,), jnp.int32).at[s_seg].add(1)
    return order, s_seg, seg_start, seg_count, badc, bad_base


def prefix_cut_admit(score: Array, seg: Array, deltas: Array, kept: Array,
                     cum_before: Array, lo: Array, hi: Array,
                     num_segments: int) -> Array:
    """bool[K] — per segment, keep the longest score-ranked prefix of
    ``kept`` whose running cumulative channel totals (``cum_before`` plus
    the prefix sums of the kept deltas) stay inside [lo, hi] at EVERY
    position.  The cut index is found by binary search: ``bisect_depth(K)``
    *fixed* iterations over the precomputed per-segment prefix tables, each
    one a [num_segments]-sized gather + compare — bounded depth, no
    data-dependent trip counts, identical cut to the cumulative bad-count
    formulation (monotone predicate: "zero bad positions among the first c").
    """
    K = score.shape[0]
    order, s_seg, seg_start, seg_count, badc, bad_base = _sorted_prefix_tables(
        score, seg, deltas, kept, cum_before, lo, hi, num_segments)

    def _bisect(carry, _):
        lo_c, hi_c = carry
        mid = (lo_c + hi_c + 1) // 2
        pos = jnp.clip(seg_start + mid - 1, 0, K - 1)
        fit = (mid == 0) | ((badc[pos] - bad_base) == 0)
        return (jnp.where(fit, mid, lo_c),
                jnp.where(fit, hi_c, mid - 1)), None

    init = (jnp.zeros((num_segments,), jnp.int32), seg_count)
    (cut, _), _ = jax.lax.scan(_bisect, init, None, length=bisect_depth(K))
    local = jnp.arange(K, dtype=jnp.int32) - seg_start[s_seg]
    admit = jnp.zeros((K,), bool).at[order].set(local < cut[s_seg])
    return kept & admit


def prefix_admit_safe(score: Array, seg: Array, deltas: Array, kept: Array,
                      cum_before: Array, lo: Array, hi: Array,
                      num_segments: int) -> Array:
    """Subset-closed ("safe") prefix admit: split every delta into its
    positive and negative parts and bound each ONE-SIDED running sum
    separately (``cum_before + Σ d⁺ ≤ hi`` and ``cum_before + Σ d⁻ ≥ lo``).

    Any subset of the admitted set then keeps the segment inside [lo, hi]:
    one-sided sums only shrink under drops, so later rejections by OTHER
    segments (a candidate must be admitted under both its broker roles and
    every topic leg) can never flip this segment into violation.  That is
    what lets the terminal repair stage run in ONE pass with no fixpoint
    loop — the old drop loop existed exactly because dropping one leg of a
    compensating pair could push the partner broker back out of band.
    Every individually-fitting candidate passes alone (d⁺ ≤ hi − cum and
    d⁻ ≥ lo − cum whenever d ∈ [lo − cum, hi − cum] and cum respects the
    bounds), so a segment's best kept action is always admitted by its own
    cut."""
    dpos = jnp.maximum(deltas, 0.0)
    dneg = jnp.minimum(deltas, 0.0)
    inf = jnp.full_like(hi, jnp.inf)
    return prefix_cut_admit(
        score, seg, jnp.concatenate([dpos, dneg], axis=1), kept,
        jnp.concatenate([cum_before, cum_before], axis=1),
        jnp.concatenate([-inf, lo], axis=1),
        jnp.concatenate([hi, inf], axis=1), num_segments)
