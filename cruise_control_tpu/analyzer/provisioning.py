"""Provisioning verdicts: is the cluster right-sized?

Parity with ``ProvisionStatus``/``ProvisionRecommendation``/
``ProvisionResponse`` (analyzer/ProvisionRecommendation.java and the
per-goal provisionResponse plumbing, Goal.java:39): capacity goals that
cannot be satisfied yield UNDER_PROVISIONED with a recommended broker
count; distribution goals whose utilization sits below the low-utilization
threshold yield OVER_PROVISIONED with an allowed-removal count
(ResourceDistributionGoal.initGoalState :238-281).  Verdicts aggregate
across goals with UNDER dominating OVER (ProvisionResponse.aggregate).
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, List, Optional

import numpy as np

from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals.specs import GoalSpec
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.tensor_model import TensorClusterModel


class ProvisionStatus(enum.Enum):
    """analyzer/ProvisionStatus."""

    RIGHT_SIZED = "right_sized"
    UNDER_PROVISIONED = "under_provisioned"
    OVER_PROVISIONED = "over_provisioned"
    UNDECIDED = "undecided"


@dataclasses.dataclass(frozen=True)
class ProvisionRecommendation:
    """analyzer/ProvisionRecommendation.java (builder fields)."""

    status: ProvisionStatus
    num_brokers: int = -1          # brokers to add (UNDER) / removable (OVER)
    resource: Optional[int] = None
    reason: str = ""

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {"status": self.status.value, "reason": self.reason}
        if self.num_brokers >= 0:
            out["numBrokers"] = self.num_brokers
        if self.resource is not None:
            out["resource"] = Resource(self.resource).resource_name
        return out


@dataclasses.dataclass
class ProvisionResponse:
    """Aggregated verdict (ProvisionResponse.aggregate: UNDER > OVER >
    RIGHT_SIZED > UNDECIDED)."""

    status: ProvisionStatus = ProvisionStatus.UNDECIDED
    recommendations: List[ProvisionRecommendation] = dataclasses.field(default_factory=list)

    _RANK = {ProvisionStatus.UNDER_PROVISIONED: 3, ProvisionStatus.OVER_PROVISIONED: 2,
             ProvisionStatus.RIGHT_SIZED: 1, ProvisionStatus.UNDECIDED: 0}

    def aggregate(self, rec: ProvisionRecommendation) -> None:
        if rec.status != ProvisionStatus.RIGHT_SIZED:
            self.recommendations.append(rec)
        if self._RANK[rec.status] > self._RANK[self.status]:
            self.status = rec.status

    def to_dict(self) -> Dict[str, object]:
        return {"status": self.status.value,
                "recommendations": [r.to_dict() for r in self.recommendations]}


def provision_verdict_for_goal(spec: GoalSpec, model: TensorClusterModel,
                               constraint: BalancingConstraint,
                               satisfied_after: bool) -> ProvisionRecommendation:
    """Per-goal verdict after optimization."""
    alive = np.asarray(model.alive_broker_mask())
    num_alive = max(int(alive.sum()), 1)
    load = np.asarray(model.broker_load())[alive]
    cap = np.asarray(model.broker_capacity)[alive]

    if spec.kind in ("capacity", "potential_nw_out"):
        res = spec.resource if spec.resource >= 0 else int(Resource.NW_OUT)
        threshold = constraint.capacity_threshold[res]
        total_load = float(load[:, res].sum())
        per_broker_cap = float(cap[:, res].mean()) * threshold
        if not satisfied_after and per_broker_cap > 0:
            needed = math.ceil(total_load / per_broker_cap) - num_alive
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED, num_brokers=max(needed, 1),
                resource=res,
                reason=f"{spec.name}: total {Resource(res).resource_name} load "
                       f"{total_load:.1f} exceeds capacity at {num_alive} brokers")
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED, resource=res)

    if spec.kind == "resource_distribution":
        res = spec.resource
        low = constraint.low_utilization_threshold[res]
        total_load = float(load[:, res].sum())
        total_cap = max(float(cap[:, res].sum()), 1e-9)
        avg_pct = total_load / total_cap
        if low > 0 and avg_pct <= low:
            # Cluster could shed brokers and stay under the low threshold
            # (bounded by min-broker / rack constraints).
            per_cap = total_cap / num_alive
            min_needed = max(math.ceil(total_load / max(low * per_cap, 1e-9)),
                             constraint.overprovisioned_min_brokers)
            removable = max(num_alive - min_needed, 0)
            if removable > 0:
                return ProvisionRecommendation(
                    ProvisionStatus.OVER_PROVISIONED, num_brokers=removable,
                    resource=res,
                    reason=f"{spec.name}: avg {Resource(res).resource_name} "
                           f"utilization {avg_pct:.3f} below threshold {low}")
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED, resource=res)

    if spec.kind == "replica_capacity":
        counts = np.asarray(model.broker_replica_counts())[alive]
        if not satisfied_after:
            total = int(counts.sum())
            needed = math.ceil(total / constraint.max_replicas_per_broker) - num_alive
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED, num_brokers=max(needed, 1),
                reason=f"{spec.name}: {total} replicas exceed "
                       f"{constraint.max_replicas_per_broker}/broker at {num_alive} brokers")
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED)

    if spec.kind in ("rack", "rack_distribution") and not satisfied_after:
        rf = int(np.asarray(model.partition_replication_factor()).max(initial=0))
        if rf > model.num_racks:
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED, num_brokers=-1,
                reason=f"{spec.name}: max replication factor {rf} exceeds "
                       f"{model.num_racks} racks (add racks)")
    return ProvisionRecommendation(ProvisionStatus.UNDECIDED)
