"""Provisioning verdicts: is the cluster right-sized?

Parity with ``ProvisionStatus``/``ProvisionRecommendation``/
``ProvisionResponse`` (analyzer/ProvisionRecommendation.java and the
per-goal provisionResponse plumbing, Goal.java:39): capacity goals that
cannot be satisfied yield UNDER_PROVISIONED with a recommended broker
count; distribution goals whose utilization sits below the low-utilization
threshold yield OVER_PROVISIONED with an allowed-removal count
(ResourceDistributionGoal.initGoalState :238-281).  Verdicts aggregate
across goals with UNDER dominating OVER (ProvisionResponse.aggregate).
"""

from __future__ import annotations

import dataclasses
import enum
import math
from typing import Dict, List, Optional

import numpy as np

from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals.specs import GoalSpec
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.tensor_model import TensorClusterModel


class ProvisionStatus(enum.Enum):
    """analyzer/ProvisionStatus."""

    RIGHT_SIZED = "right_sized"
    UNDER_PROVISIONED = "under_provisioned"
    OVER_PROVISIONED = "over_provisioned"
    UNDECIDED = "undecided"


@dataclasses.dataclass(frozen=True)
class ProvisionRecommendation:
    """analyzer/ProvisionRecommendation.java (builder fields)."""

    status: ProvisionStatus
    num_brokers: int = -1          # brokers to add (UNDER) / removable (OVER)
    resource: Optional[int] = None
    reason: str = ""

    def to_dict(self) -> Dict[str, object]:
        out: Dict[str, object] = {"status": self.status.value, "reason": self.reason}
        if self.num_brokers >= 0:
            out["numBrokers"] = self.num_brokers
        if self.resource is not None:
            out["resource"] = Resource(self.resource).resource_name
        return out


@dataclasses.dataclass
class ProvisionResponse:
    """Aggregated verdict (ProvisionResponse.aggregate: UNDER > OVER >
    RIGHT_SIZED > UNDECIDED)."""

    status: ProvisionStatus = ProvisionStatus.UNDECIDED
    recommendations: List[ProvisionRecommendation] = dataclasses.field(default_factory=list)

    _RANK = {ProvisionStatus.UNDER_PROVISIONED: 3, ProvisionStatus.OVER_PROVISIONED: 2,
             ProvisionStatus.RIGHT_SIZED: 1, ProvisionStatus.UNDECIDED: 0}

    def aggregate(self, rec: ProvisionRecommendation) -> None:
        if rec.status != ProvisionStatus.RIGHT_SIZED:
            self.recommendations.append(rec)
        if self._RANK[rec.status] > self._RANK[self.status]:
            self.status = rec.status

    def to_dict(self) -> Dict[str, object]:
        return {"status": self.status.value,
                "recommendations": [r.to_dict() for r in self.recommendations]}


@dataclasses.dataclass
class _HostView:
    """Host copies of the model arrays every per-goal verdict reads —
    fetched ONCE per optimization/detection pass (each eager np.asarray is
    a device round trip on a tunneled TPU; 15 goals × 4 arrays was ~60)."""

    alive: np.ndarray
    load: np.ndarray
    cap: np.ndarray
    replica_counts: np.ndarray
    rf_max: int


def host_view(model: TensorClusterModel) -> _HostView:
    import jax
    alive, load, cap, counts, rf = jax.device_get((
        model.alive_broker_mask(), model.broker_load(), model.broker_capacity,
        model.broker_replica_counts(), model.partition_replication_factor()))
    return _HostView(alive=alive, load=load, cap=cap, replica_counts=counts,
                     rf_max=int(rf.max(initial=0)))


def provision_verdict_for_goal(spec: GoalSpec, model: TensorClusterModel,
                               constraint: BalancingConstraint,
                               satisfied_after: bool,
                               view: Optional[_HostView] = None
                               ) -> ProvisionRecommendation:
    """Per-goal verdict after optimization."""
    if view is None:
        view = host_view(model)
    alive = view.alive
    num_alive = max(int(alive.sum()), 1)
    load = view.load[alive]
    cap = view.cap[alive]

    if spec.kind in ("capacity", "potential_nw_out"):
        res = spec.resource if spec.resource >= 0 else int(Resource.NW_OUT)
        threshold = constraint.capacity_threshold[res]
        total_load = float(load[:, res].sum())
        per_broker_cap = float(cap[:, res].mean()) * threshold
        if not satisfied_after and per_broker_cap > 0:
            needed = math.ceil(total_load / per_broker_cap) - num_alive
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED, num_brokers=max(needed, 1),
                resource=res,
                reason=f"{spec.name}: total {Resource(res).resource_name} load "
                       f"{total_load:.1f} exceeds capacity at {num_alive} brokers")
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED, resource=res)

    if spec.kind == "resource_distribution":
        res = spec.resource
        low = constraint.low_utilization_threshold[res]
        total_load = float(load[:, res].sum())
        total_cap = max(float(cap[:, res].sum()), 1e-9)
        avg_pct = total_load / total_cap
        if low > 0 and avg_pct <= low:
            # Cluster could shed brokers and stay under the low threshold
            # (bounded by min-broker / rack constraints).
            per_cap = total_cap / num_alive
            min_needed = max(math.ceil(total_load / max(low * per_cap, 1e-9)),
                             constraint.overprovisioned_min_brokers)
            removable = max(num_alive - min_needed, 0)
            if removable > 0:
                return ProvisionRecommendation(
                    ProvisionStatus.OVER_PROVISIONED, num_brokers=removable,
                    resource=res,
                    reason=f"{spec.name}: avg {Resource(res).resource_name} "
                           f"utilization {avg_pct:.3f} below threshold {low}")
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED, resource=res)

    if spec.kind == "replica_capacity":
        counts = view.replica_counts[alive]
        if not satisfied_after:
            total = int(counts.sum())
            needed = math.ceil(total / constraint.max_replicas_per_broker) - num_alive
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED, num_brokers=max(needed, 1),
                reason=f"{spec.name}: {total} replicas exceed "
                       f"{constraint.max_replicas_per_broker}/broker at {num_alive} brokers")
        return ProvisionRecommendation(ProvisionStatus.RIGHT_SIZED)

    if spec.kind in ("rack", "rack_distribution") and not satisfied_after:
        rf = view.rf_max
        if rf > model.num_racks:
            return ProvisionRecommendation(
                ProvisionStatus.UNDER_PROVISIONED, num_brokers=-1,
                reason=f"{spec.name}: max replication factor {rf} exceeds "
                       f"{model.num_racks} racks (add racks)")
    return ProvisionRecommendation(ProvisionStatus.UNDECIDED)
