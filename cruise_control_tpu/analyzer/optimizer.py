"""The batched greedy goal optimizer.

TPU-native redesign of the reference's analyzer hot loop
(GoalOptimizer.optimizations, analyzer/GoalOptimizer.java:417-492 →
AbstractGoal.optimize, analyzer/goals/AbstractGoal.java:82-119 →
maybeApplyBalancingAction, AbstractGoal.java:224-266).  The reference walks
brokers and replicas one at a time, probing one action against every
previously-optimized goal before mutating the model.  Here each *step*:

1. generates a K-wide candidate batch for the current goal (top-S relevant
   replicas × top-D destination brokers, plus leadership pairs);
2. scores and masks all K candidates in one fused XLA graph —
   ``self_feasible`` for the current goal, ``accepts`` for every previously
   optimized goal (the cross-goal veto of AnalyzerUtils.java:117, evaluated
   as composable masks with zero Python round-trips);
3. selects a *conflict-free* accepted subset — at most one action per source
   broker, per destination broker, and per partition — via three segment-
   argmax passes, and applies them with one vectorized scatter.

Uniqueness of brokers across applied actions makes the per-candidate load
deltas exact (no two actions touch the same broker in the same role), so
every feasibility/acceptance decision holds after application; a broker that
is a source in one action and a destination in another only sees
conservative checks (source deltas are ≤ 0, destination deltas ≥ 0 on the
capped metrics).  Each applied action strictly decreases the goal's
potential (excess over cap, count of rack conflicts, or squared deviation
from the balance target), so the step loop terminates.

Steps repeat until a fixpoint (no candidate is both feasible and positively
scored).  Goals run in priority order exactly as the reference does; the
optimized set grows by one after each goal.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from cruise_control_tpu.analyzer import candidates as cgen
from cruise_control_tpu.analyzer.actions import Candidates, apply_candidates
from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import kernels
from cruise_control_tpu.analyzer.goals.specs import GoalSpec, goals_by_priority
from cruise_control_tpu.analyzer.state import BrokerArrays, OptimizationOptions
from cruise_control_tpu.model.stats import ClusterModelStats, compute_stats
from cruise_control_tpu.model.tensor_model import TensorClusterModel

_MIN_SCORE = 1e-9  # strictly-positive improvement required (greedy accept)


class OptimizationFailureException(Exception):
    """A hard goal could not be satisfied (reference:
    analyzer/goals/AbstractGoal.java OptimizationFailureException)."""


# ---------------------------------------------------------------------------
# Conflict-free selection
# ---------------------------------------------------------------------------

def _best_per_segment(score: Array, seg: Array, num_segments: int, eligible: Array) -> Array:
    """bool[K] — keep each segment's single highest-scored eligible candidate
    (ties broken by lowest candidate index)."""
    k = score.shape[0]
    masked = jnp.where(eligible, score, -jnp.inf)
    seg_safe = jnp.where(eligible, seg, 0)
    best = jnp.full((num_segments,), -jnp.inf, masked.dtype).at[seg_safe].max(
        jnp.where(eligible, masked, -jnp.inf))
    is_best = eligible & (masked >= best[seg_safe]) & jnp.isfinite(masked)
    idx = jnp.arange(k, dtype=jnp.int32)
    winner = jnp.full((num_segments,), k, jnp.int32).at[seg_safe].min(
        jnp.where(is_best, idx, k))
    return is_best & (idx == winner[seg_safe])


def select_nonconflicting(score: Array, cand: Candidates, eligible: Array,
                          num_brokers: int, num_partitions: int,
                          rounds: int = 4) -> Array:
    """bool[K] — greedy conflict-free subset: unique source broker, unique
    destination broker, unique partition across the whole kept set.

    A single (per-src → per-dest → per-partition) argmax cascade loses
    throughput when many sources' best candidates contend for one popular
    destination (only one survives and the losers' other destinations were
    already discarded by the per-src pass).  Running a few rounds of the
    cascade — masking out brokers/partitions claimed by earlier rounds —
    recovers a near-maximal matching while keeping every applied action's
    load deltas exact."""
    keep_total = jnp.zeros_like(eligible)
    used_src = jnp.zeros((num_brokers,), bool)
    used_dest = jnp.zeros((num_brokers,), bool)
    used_part = jnp.zeros((num_partitions,), bool)
    for _ in range(rounds):
        elig = (eligible & ~keep_total & ~used_src[cand.src]
                & ~used_dest[cand.dest] & ~used_part[cand.partition])
        keep = _best_per_segment(score, cand.src, num_brokers, elig)
        keep = _best_per_segment(score, cand.dest, num_brokers, keep)
        keep = _best_per_segment(score, cand.partition, num_partitions, keep)
        keep_total = keep_total | keep
        used_src = used_src.at[jnp.where(keep, cand.src, 0)].max(keep)
        used_dest = used_dest.at[jnp.where(keep, cand.dest, 0)].max(keep)
        used_part = used_part.at[jnp.where(keep, cand.partition, 0)].max(keep)
    return keep_total


# ---------------------------------------------------------------------------
# The per-goal jitted step
# ---------------------------------------------------------------------------

def _goal_step(model: TensorClusterModel, options: OptimizationOptions,
               spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
               constraint: BalancingConstraint,
               num_sources: int, num_dests: int, mesh=None):
    """One optimization step for ``spec``: returns (new_model, num_applied).

    Static args (spec, prev_specs, constraint, widths, mesh) select the
    compiled graph; model/options are traced.  With ``mesh`` set, the
    candidate batch is sharding-constrained along its K axis so GSPMD
    partitions the scoring/masking math across the mesh devices (see
    parallel/mesh.py).
    """
    arrays = BrokerArrays.from_model(model)

    batches = []
    if spec.uses_moves:
        batches.append(cgen.move_candidates(spec, model, arrays, constraint, options,
                                            num_sources, num_dests))
    if spec.uses_leadership:
        batches.append(cgen.leadership_candidates(spec, model, arrays, constraint,
                                                  options, num_sources))
    if spec.uses_intra_moves:
        batches.append(cgen.intra_disk_candidates(spec, model, arrays, constraint,
                                                  options, num_sources))
    cand = batches[0]
    for extra in batches[1:]:
        cand = cgen.concat_candidates(cand, extra)
    if mesh is not None:
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
        cand = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), cand)

    feasible = kernels.self_feasible(spec, model, arrays, cand, constraint)
    accepted = jnp.ones_like(feasible)
    for prev in prev_specs:
        accepted = accepted & kernels.accepts(prev, model, arrays, cand, constraint)
    score = kernels.score(spec, model, arrays, cand, constraint)

    eligible = cand.valid & feasible & accepted & (score > _MIN_SCORE)
    keep = select_nonconflicting(score, cand, eligible, model.num_brokers,
                                 model.num_partitions)
    new_model = apply_candidates(model, cand, keep)
    return new_model, keep.sum()


_step_cache: Dict[tuple, object] = {}


def _get_step_fn(spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                 constraint: BalancingConstraint, num_sources: int, num_dests: int,
                 mesh=None):
    key = (spec, prev_specs, constraint, num_sources, num_dests, mesh)
    fn = _step_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_goal_step, spec=spec, prev_specs=prev_specs,
                             constraint=constraint, num_sources=num_sources,
                             num_dests=num_dests, mesh=mesh))
        _step_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Goal orchestration (priority order)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GoalResult:
    name: str
    is_hard: bool
    satisfied_before: bool
    satisfied_after: bool
    steps: int
    actions_applied: int
    duration_s: float


@dataclasses.dataclass
class OptimizerRun:
    """Result bundle of one optimization pass (analyzer/OptimizerResult.java:34)."""

    model: TensorClusterModel
    goal_results: List[GoalResult]
    stats_before: ClusterModelStats
    stats_after: ClusterModelStats
    num_candidates_scored: int
    provision_response: object = None  # ProvisionResponse

    @property
    def violated_goals_before(self) -> List[str]:
        return [g.name for g in self.goal_results if not g.satisfied_before]

    @property
    def violated_goals_after(self) -> List[str]:
        return [g.name for g in self.goal_results if not g.satisfied_after]


def optimize_goal(model: TensorClusterModel, spec: GoalSpec,
                  prev_specs: Tuple[GoalSpec, ...], constraint: BalancingConstraint,
                  options: OptimizationOptions, max_steps: int = 256,
                  num_sources: Optional[int] = None, num_dests: Optional[int] = None
                  ) -> Tuple[TensorClusterModel, int, int]:
    """Run one goal to fixpoint. Returns (model, steps, actions)."""
    ns = num_sources or cgen.default_num_sources(model)
    nd = num_dests or cgen.default_num_dests(model)
    step = _get_step_fn(spec, prev_specs, constraint, ns, nd)
    total = 0
    for i in range(max_steps):
        model, n = step(model, options)
        n = int(n)
        total += n
        if n == 0:
            return model, i + 1, total
    return model, max_steps, total


_satisfied_cache: Dict[tuple, object] = {}


def _goal_satisfied(model: TensorClusterModel, spec: GoalSpec,
                    constraint: BalancingConstraint) -> bool:
    key = (spec, constraint)
    fn = _satisfied_cache.get(key)
    if fn is None:
        def _fn(m):
            arrays = BrokerArrays.from_model(m)
            return kernels.goal_satisfied(spec, m, arrays, constraint)
        fn = jax.jit(_fn)
        _satisfied_cache[key] = fn
    return bool(fn(model))


def optimize(model: TensorClusterModel, goal_names: Sequence[str],
             constraint: Optional[BalancingConstraint] = None,
             options: Optional[OptimizationOptions] = None,
             max_steps_per_goal: int = 256,
             num_sources: Optional[int] = None, num_dests: Optional[int] = None,
             raise_on_hard_failure: bool = True) -> OptimizerRun:
    """Run the goal stack in priority order (GoalOptimizer.optimizations).

    Each goal optimizes the model to its fixpoint, constrained by the
    acceptance masks of all previously-optimized goals; hard-goal failure
    raises unless ``raise_on_hard_failure`` is False (the reference throws
    OptimizationFailureException from hard goals' ``finish()``).
    """
    constraint = constraint or BalancingConstraint.default()
    options = options if options is not None else OptimizationOptions.none(model)
    specs = goals_by_priority(goal_names)

    stats_before = compute_stats(model)
    results: List[GoalResult] = []
    prev: Tuple[GoalSpec, ...] = ()
    ns = num_sources or cgen.default_num_sources(model)
    nd = num_dests or cgen.default_num_dests(model)
    scored = 0
    for spec in specs:
        t0 = time.monotonic()
        before = _goal_satisfied(model, spec, constraint)
        model, steps, actions = optimize_goal(model, spec, prev, constraint, options,
                                              max_steps_per_goal, ns, nd)
        after = _goal_satisfied(model, spec, constraint)
        k = ns * nd * (1 if spec.uses_moves else 0)
        if spec.uses_leadership:
            k += ns * model.max_rf
        if spec.uses_intra_moves:
            k += ns * model.broker_disks.shape[1]
        scored += steps * k
        results.append(GoalResult(name=spec.name, is_hard=spec.is_hard,
                                  satisfied_before=before, satisfied_after=after,
                                  steps=steps, actions_applied=actions,
                                  duration_s=time.monotonic() - t0))
        if spec.is_hard and not after and raise_on_hard_failure:
            raise OptimizationFailureException(
                f"hard goal {spec.name} not satisfied after optimization")
        prev = prev + (spec,)

    from cruise_control_tpu.analyzer.provisioning import (ProvisionResponse,
                                                          provision_verdict_for_goal)
    provision = ProvisionResponse()
    for spec, res in zip(specs, results):
        provision.aggregate(provision_verdict_for_goal(spec, model, constraint,
                                                       res.satisfied_after))

    return OptimizerRun(model=model, goal_results=results, stats_before=stats_before,
                        stats_after=compute_stats(model), num_candidates_scored=scored,
                        provision_response=provision)
