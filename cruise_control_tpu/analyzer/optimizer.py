"""The batched greedy goal optimizer.

TPU-native redesign of the reference's analyzer hot loop
(GoalOptimizer.optimizations, analyzer/GoalOptimizer.java:417-492 →
AbstractGoal.optimize, analyzer/goals/AbstractGoal.java:82-119 →
maybeApplyBalancingAction, AbstractGoal.java:224-266).  The reference walks
brokers and replicas one at a time, probing one action against every
previously-optimized goal before mutating the model.  Here each *step*:

1. generates a K-wide candidate batch for the current goal (top-S relevant
   replicas × top-D destination brokers, plus leadership pairs);
2. scores and masks all K candidates in one fused XLA graph —
   ``self_feasible`` for the current goal, ``accepts`` for every previously
   optimized goal (the cross-goal veto of AnalyzerUtils.java:117, evaluated
   as composable masks with zero Python round-trips);
3. selects a *conflict-free* accepted subset — at most one action per source
   broker, per destination broker, and per partition — via three segment-
   argmax passes, and applies them with one vectorized scatter.

Uniqueness of brokers across applied actions makes the per-candidate load
deltas exact (no two actions touch the same broker in the same role), so
every feasibility/acceptance decision holds after application; a broker that
is a source in one action and a destination in another only sees
conservative checks (source deltas are ≤ 0, destination deltas ≥ 0 on the
capped metrics).  Each applied action strictly decreases the goal's
potential (excess over cap, count of rack conflicts, or squared deviation
from the balance target), so the step loop terminates.

Steps repeat until a fixpoint (no candidate is both feasible and positively
scored).  Goals run in priority order exactly as the reference does; the
optimized set grows by one after each goal.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
import time
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from cruise_control_tpu.analyzer import candidates as cgen
from cruise_control_tpu.analyzer.actions import Candidates, apply_candidates
from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import kernels
from cruise_control_tpu.analyzer.goals.specs import GoalSpec, goals_by_priority
from cruise_control_tpu.analyzer.state import (FLIGHT_ACTIONS, FLIGHT_BISECT,
                                               FLIGHT_FRONTIER, FLIGHT_KIND,
                                               FLIGHT_LANES, FLIGHT_REPAIR,
                                               FLIGHT_SCORE_BITS, FLIGHT_WIDTH,
                                               PACKED_AFTER, PACKED_ANY_OFFLINE,
                                               PACKED_CAPPED, PACKED_CONFLICT,
                                               BrokerArrays,
                                               FrontierInvariants,
                                               OptimizationOptions,
                                               PipelineNextGoal,
                                               StepInvariants, WarmStart,
                                               pow2_bucket)
from cruise_control_tpu.common import compile_cache
from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.common.tracing import TRACE
from cruise_control_tpu.model.stats import ClusterModelStats, compute_stats_jit
from cruise_control_tpu.model.tensor_model import TensorClusterModel

_LOG = logging.getLogger(__name__)

_MIN_SCORE = 1e-9  # strictly-positive improvement required (greedy accept)

# Lanes per select_batched round; rounds = ceil(moves_per_broker_step / this).
# Serial rounds dominate per-step cost (each is a long chain of small ops at
# the op-launch floor), so prefer wide lanes over many rounds: measured at
# the 50-broker rung, 1 round of 48 lanes is 3.8x cheaper per step than 6
# rounds of 8 AND reaches the fixpoint in fewer steps (the budget-repair
# passes run once instead of six times).  128 lanes lets hot brokers drain
# at full band-budget speed.
SUBROUNDS = 128

# Perf-debug switches (tools/profiling only; never set in production paths).
_DBG_TRIVIAL_SELECT = False
_DBG_NO_ACCEPTS = False
_DBG_NO_BUDGETS = False


def _repair_oracle() -> bool:
    """CRUISE_REPAIR_ORACLE=1 selects the legacy data-dependent repair
    (cond-gated prefix passes + unbounded drop while_loop) for differential
    testing against the bounded-depth exact repair.  Read by every _get_*
    cache constructor so the flag is part of the python cache key — flipping
    the env var mid-process selects a different executable, never a stale
    one."""
    return os.environ.get("CRUISE_REPAIR_ORACLE", "").strip() == "1"


def _flight_recorder() -> bool:
    """CRUISE_FLIGHT_RECORDER=1 turns on the solve flight recorder: the
    budget fixpoint carries an i32[C, FLIGHT_WIDTH] per-step telemetry
    buffer that piggybacks on the existing single boundary fetch.  Like
    ``_repair_oracle`` the flag is read by every _get_* cache constructor
    so it is part of the python cache key — recorder-on and recorder-off
    are different executables and never contaminate each other (the off
    program is byte-for-byte the pre-recorder graph, keeping the
    step-graph equation ceilings and bit-identity trivially intact)."""
    return os.environ.get("CRUISE_FLIGHT_RECORDER", "").strip() == "1"


def _aot_prelower() -> bool:
    """CRUISE_AOT_PRELOWER=1 turns on ahead-of-time lowering of the bucket
    family: the chunk driver AOT-compiles each (goal, bucket, mesh) shape
    via ``fn.lower(...).compile()`` before dispatching it and ships the
    serialized executable through the persistent artifact store
    (``common/compile_cache.py``), so tunneled transport moves a cached
    artifact once instead of re-serializing every fresh build — the actual
    root cause of the 375k-candidate ceiling (PR 9 probe).  Like
    ``_repair_oracle`` the flag is read by every _get_* cache constructor
    so it is part of the python cache key — flipping it mid-process never
    reuses a stale executable."""
    return os.environ.get("CRUISE_AOT_PRELOWER", "").strip() == "1"


#: Canonical order of the candidate-kind segments ``_goal_step`` concatenates;
#: ``FLIGHT_KIND`` rows index into this tuple (-1 = no action kept).
FLIGHT_KINDS = ("move", "leadership", "intra_move", "swap", "intra_swap")


# Below this K the selection rounds always run on the full lane axis:
# compaction buys nothing at tier-1 batch sizes, and the dense path keeps
# "bit-identical proposals at tier-1 sizes" structural (mirrors
# _FRONTIER_DENSE_MIN for the broker axis).
_LANE_DENSE_MIN = 4096


def _lane_bucket(k: int, nb_sel: int, subrounds: int) -> Optional[int]:
    """Live-candidate compaction bucket for a K-lane batch, or None for
    dense.  The score/feasibility/acceptance masks kill most lanes before
    the conflict rounds, so the rounds gather the surviving lanes into a
    dense top-K prefix of this (power-of-two, shared pow2_bucket ladder)
    length.  Sized so a full round of lane winners always fits: each round
    keeps at most ``subrounds`` actions per broker, and 2× headroom keeps
    the conflict passes from starving on collision-heavy batches."""
    if k <= _LANE_DENSE_MIN:
        return None
    target = min(k, max(_LANE_DENSE_MIN, 2 * nb_sel * subrounds))
    kc = pow2_bucket(target, _LANE_DENSE_MIN)
    return kc if kc < k else None


class OptimizationFailureException(Exception):
    """A hard goal could not be satisfied (reference:
    analyzer/goals/AbstractGoal.java OptimizationFailureException)."""


# ---------------------------------------------------------------------------
# Conflict-free selection
# ---------------------------------------------------------------------------

def _prefix_admit_role(score: Array, seg: Array, deltas: Array, kept: Array,
                       cum_before: Array, lo: Array, hi: Array,
                       num_segments: int) -> Array:
    """bool[K] — per segment (a broker in one role), admit the score-DESC
    prefix of ``kept`` whose cumulative channel deltas stay inside
    [lo, hi] given ``cum_before`` already committed.  This is the repair
    granularity between "keep everything" and the old single-best
    fallback: a broker near its band edge keeps every action that still
    fits instead of exactly one (the 1-action/step convergence tails).
    Rejected candidates' deltas still occupy the prefix sums, so admission
    is conservative — the caller's exactness while_loop stays the final
    guarantee."""
    K = score.shape[0]
    # Group by segment with score descending inside: stable two-pass sort.
    o1 = jnp.argsort(-score, stable=True)
    o2 = jnp.argsort(seg[o1], stable=True)
    order = o1[o2]
    s_seg = seg[order]
    s_deltas = jnp.where(kept[order][:, None], deltas[order], 0.0)
    cs = jnp.cumsum(s_deltas, axis=0)                       # [K, C]
    # First sorted position per present segment (scatter-min; the equivalent
    # searchsorted lowers to ~21 ops — absent segments get K, never read).
    seg_start = jnp.full((num_segments,), K, jnp.int32).at[s_seg].min(
        jnp.arange(K, dtype=jnp.int32))
    base = jnp.where((seg_start > 0)[:, None],
                     cs[jnp.maximum(seg_start - 1, 0)], 0.0)  # [B, C]
    prefix = cum_before[s_seg] + cs - base[s_seg]           # incl. self
    hi_s = hi[s_seg]
    lo_s = lo[s_seg]
    # RELATIVE tolerance: the bounds span bytes-scale channels (1e9+) where
    # an absolute 1e-6 is far below float32 resolution and count channels
    # near 0 where it is the right size — scale by the bound magnitude,
    # floored at 1 so the absolute behavior survives for counts.
    scale = jnp.maximum(1.0, jnp.maximum(
        jnp.where(jnp.isfinite(hi_s), jnp.abs(hi_s), 0.0),
        jnp.where(jnp.isfinite(lo_s), jnp.abs(lo_s), 0.0)))
    eps = 1e-6 * scale
    ok = ((prefix <= hi_s + eps) & (prefix >= lo_s - eps)).all(axis=1)
    # A candidate is admitted only if itself and every better-scored
    # candidate of its segment fit (monotone prefix).
    bad = jnp.cumsum((~ok).astype(jnp.int32))
    bad_base = jnp.where(seg_start > 0, bad[jnp.maximum(seg_start - 1, 0)], 0)
    admit_sorted = ok & ((bad - bad_base[s_seg]) == 0)
    admit = jnp.zeros((K,), bool).at[order].set(admit_sorted)
    return kept & admit


def _best_per_segment(score: Array, seg: Array, num_segments: int, eligible: Array) -> Array:
    """bool[K] — keep each segment's single highest-scored eligible candidate
    (ties broken by lowest candidate index)."""
    k = score.shape[0]
    masked = jnp.where(eligible, score, -jnp.inf)
    seg_safe = jnp.where(eligible, seg, 0)
    best = jnp.full((num_segments,), -jnp.inf, masked.dtype).at[seg_safe].max(
        jnp.where(eligible, masked, -jnp.inf))
    is_best = eligible & (masked >= best[seg_safe]) & jnp.isfinite(masked)
    idx = jnp.arange(k, dtype=jnp.int32)
    winner = jnp.full((num_segments,), k, jnp.int32).at[seg_safe].min(
        jnp.where(is_best, idx, k))
    return is_best & (idx == winner[seg_safe])


# The 8 budget channels: every band-style goal metric is one of these, so a
# per-broker (channel → remaining room/slack) budget captures the cumulative
# effect of MANY actions touching one broker in a single step.
# 0-3: resource load (CPU, NW_IN, NW_OUT, DISK); 4: replica count;
# 5: leader count; 6: potential NW_OUT; 7: leader bytes-in.
NUM_CHANNELS = 8

_CHANNEL_OF_KIND = {
    "replica_capacity": 4, "replica_distribution": 4,
    "leader_replica_distribution": 5,
    "potential_nw_out": 6,
    "leader_bytes_in": 7,
}
# Kinds whose accepts() only bounds the destination (cap-style).
_CAP_ONLY_KINDS = ("capacity", "replica_capacity", "potential_nw_out",
                   "leader_bytes_in")


def _spec_channel(spec: GoalSpec):
    if spec.kind in ("capacity", "resource_distribution"):
        return spec.resource
    return _CHANNEL_OF_KIND.get(spec.kind)


def _channel_metrics(model: TensorClusterModel, arrays: BrokerArrays) -> Array:
    """f32[B, 8] — current value of every budget channel per broker."""
    return jnp.concatenate([
        arrays.load,
        arrays.replica_count.astype(jnp.float32)[:, None],
        arrays.leader_count.astype(jnp.float32)[:, None],
        arrays.potential_nw_out[:, None],
        arrays.leader_bytes_in[:, None],
    ], axis=1)


def _channel_deltas(cand: Candidates):
    """(d_src f32[K, 8], d_dest f32[K, 8]) — per-candidate channel changes."""
    dc = cand.d_replica_count.astype(jnp.float32)[:, None]
    dl = cand.d_leader_count.astype(jnp.float32)[:, None]
    dp = cand.d_potential_nw_out[:, None]
    d_src = jnp.concatenate([cand.delta_src, -dc, -dl, -dp,
                             -cand.d_leader_bytes_in_src[:, None]], axis=1)
    d_dest = jnp.concatenate([cand.delta_dest, dc, dl, dp,
                              cand.d_leader_bytes_in_dest[:, None]], axis=1)
    return d_src, d_dest


def _band_sides(specs: Tuple[GoalSpec, ...], model: TensorClusterModel,
                arrays: BrokerArrays, constraint: BalancingConstraint):
    """(upper_min f32[B, 8], lower_max f32[B, 8]) — the folded band SIDES of
    every band goal in ``specs``.  Step-invariant: static capacities ×
    thresholds, plus averages over alive-broker totals that replica moves /
    swaps / leadership transfers between alive brokers conserve — so the
    fixpoint computes this once (compute_step_invariants) and only the
    metrics side of the budgets is rebuilt per step."""
    B = model.num_brokers
    upper_min = jnp.full((B, NUM_CHANNELS), jnp.inf, jnp.float32)
    lower_max = jnp.full((B, NUM_CHANNELS), -jnp.inf, jnp.float32)
    # The resource-axis kinds are computed VECTORIZED over all four
    # resources in one pass each (a per-spec limits() loop emitted ~6 small
    # ops × up to 13 specs per step — pure serial op-chain cost on TPU);
    # presence masks then select which channels actually constrain.
    cap_channels = [s.resource for s in specs if s.kind == "capacity"]
    if cap_channels:
        thresh = jnp.asarray(constraint.capacity_threshold, jnp.float32)
        upper_cap = arrays.capacity * thresh[None, :]              # [B, 4]
        sel = np.zeros((NUM_CHANNELS,), bool)
        sel[np.asarray(cap_channels)] = True
        pad = jnp.full((B, 4), jnp.inf)
        upper_min = jnp.minimum(
            upper_min,
            jnp.where(jnp.asarray(sel)[None, :],
                      jnp.concatenate([upper_cap, pad], axis=1), jnp.inf))
    dist_channels = [s.resource for s in specs
                     if s.kind == "resource_distribution"]
    # accepts_band_batch treats HARD-configured distribution goals as
    # cap-style (upper bound only, both brokers) — the lower side must not
    # fold into the budgets either, or the budgets would enforce a lower
    # band the acceptance oracle never checks.
    soft_dist_channels = [s.resource for s in specs
                          if s.kind == "resource_distribution"
                          and not s.is_hard]
    if dist_channels:
        bp = jnp.asarray([constraint.balance_percentage(r) for r in range(4)],
                         jnp.float32)
        alive_col = arrays.alive[:, None]
        total_util = jnp.where(alive_col, arrays.load, 0.0).sum(axis=0)
        total_cap = jnp.maximum(
            jnp.where(alive_col, arrays.capacity, 0.0).sum(axis=0), 1e-9)
        avg_pct = total_util / total_cap                            # [4]
        low = jnp.asarray(constraint.low_utilization_threshold, jnp.float32)
        gated = avg_pct <= low
        # Mirrors kernels.limits' resource_distribution branch exactly
        # (the _BIG sentinel under low-utilization gating included).
        up_d = jnp.where(gated[None, :], kernels._BIG,
                         avg_pct[None, :] * bp[None, :] * arrays.capacity)
        sel = np.zeros((NUM_CHANNELS,), bool)
        sel[np.asarray(dist_channels)] = True
        pad = jnp.full((B, 4), jnp.inf)
        upper_min = jnp.minimum(
            upper_min, jnp.where(jnp.asarray(sel)[None, :],
                                 jnp.concatenate([up_d, pad], axis=1),
                                 jnp.inf))
    if soft_dist_channels:
        lo_d = jnp.where(gated[None, :], 0.0,
                         jnp.maximum(avg_pct[None, :] * (2.0 - bp)[None, :]
                                     * arrays.capacity, 0.0))
        sel = np.zeros((NUM_CHANNELS,), bool)
        sel[np.asarray(soft_dist_channels)] = True
        lower_max = jnp.maximum(
            lower_max, jnp.where(jnp.asarray(sel)[None, :],
                                 jnp.concatenate([lo_d, -pad], axis=1),
                                 -jnp.inf))
    # The remaining channel kinds, vectorized the same way: ONE masked-sum
    # pass produces the count/bytes averages every count-style band is
    # built from (mirroring kernels.limits' per-kind branches exactly).
    rem = [s for s in specs
           if s.kind not in ("capacity", "resource_distribution")
           and _spec_channel(s) is not None]
    if rem:
        kinds = {s.kind for s in rem}
        if {"replica_distribution", "leader_replica_distribution"} & kinds:
            cnt2 = jnp.where(arrays.alive[:, None],
                             jnp.stack([arrays.replica_count,
                                        arrays.leader_count], axis=1), 0)
            avg_cnt = cnt2.sum(axis=0) / arrays.num_alive           # f32[2]
        ups, los = [], []
        if "replica_capacity" in kinds:
            ups.append((4, jnp.full(
                (B,), float(constraint.max_replicas_per_broker), jnp.float32)))
        if "potential_nw_out" in kinds:
            nw_out = kernels.Resource.NW_OUT
            ups.append((6, arrays.capacity[:, nw_out]
                        * constraint.capacity_threshold[nw_out]))
        if "replica_distribution" in kinds:
            bp_r = kernels._margin_pct(constraint.replica_count_balance_threshold)
            ups.append((4, jnp.broadcast_to(jnp.ceil(avg_cnt[0] * bp_r), (B,))))
            if any(s.kind == "replica_distribution" and not s.is_hard
                   for s in rem):
                los.append((4, jnp.broadcast_to(
                    jnp.floor(avg_cnt[0] * (2.0 - bp_r)), (B,))))
        if "leader_replica_distribution" in kinds:
            bp_l = kernels._margin_pct(
                constraint.leader_replica_count_balance_threshold)
            ups.append((5, jnp.broadcast_to(jnp.ceil(avg_cnt[1] * bp_l), (B,))))
            if any(s.kind == "leader_replica_distribution" and not s.is_hard
                   for s in rem):
                los.append((5, jnp.broadcast_to(
                    jnp.floor(avg_cnt[1] * (2.0 - bp_l)), (B,))))
        if "leader_bytes_in" in kinds:
            nw_in = kernels.Resource.NW_IN
            bp_b = kernels._margin_pct(constraint.resource_balance_threshold[nw_in])
            avg_b = jnp.where(arrays.alive, arrays.leader_bytes_in, 0.0).sum() \
                / arrays.num_alive
            ups.append((7, jnp.broadcast_to(avg_b * bp_b, (B,))))
        for ch, up in ups:
            upper_min = upper_min.at[:, ch].min(up)
        for ch, lo in los:
            lower_max = lower_max.at[:, ch].max(lo)
    return upper_min, lower_max


def _channel_budgets(specs: Tuple[GoalSpec, ...], model: TensorClusterModel,
                     arrays: BrokerArrays, constraint: BalancingConstraint,
                     sides=None):
    """(room_dest f32[B, 8], slack_src f32[B, 8]) — how much each broker may
    cumulatively gain / shed per channel this step without violating ANY
    band goal in ``specs`` (the current goal + every previously optimized
    one).  This is what makes multi-accept exact: per-candidate acceptance
    checks hold against the pre-step state, and these budgets bound the
    *sum* of accepted deltas per broker so the post-step state still
    respects every band.  ``sides`` optionally supplies the precomputed
    (upper_min, lower_max) band sides; only the current metrics and the
    room/slack application are per-step work."""
    metrics = _channel_metrics(model, arrays)
    upper_min, lower_max = sides if sides is not None else \
        _band_sides(specs, model, arrays, constraint)
    room_dest = jnp.maximum(upper_min - metrics, 0.0)
    slack_src = jnp.maximum(metrics - lower_max, 0.0)
    # Dead/invalid brokers: unlimited shed (healing drains them regardless of
    # bands — mirrors accepts()' ``~alive[src]`` exemption).
    slack_src = jnp.where(arrays.alive[:, None], slack_src, jnp.inf)
    return room_dest, slack_src


def select_batched(score: Array, cand: Candidates, eligible: Array,
                   model: TensorClusterModel,
                   room_dest: Array, slack_src: Array,
                   topic_budgets, disk_guard: bool,
                   rounds: int = 6, subrounds: int = 4,
                   has_swaps: bool = True,
                   frontier: Optional[FrontierInvariants] = None,
                   compact_k: Optional[int] = None,
                   repair_oracle: bool = False):
    """(keep bool[K], stats (repair_fired, lanes_live, bisect_depth) i32
    scalars) — greedy multi-accept subset.

    Round-1's selection kept at most ONE action per source broker, per
    destination broker and per partition per step, capping throughput at
    ~B actions/step and pushing distribution goals into a 256-step
    convergence tail (round-1 verdict item 4).  Here each round keeps up to
    ``subrounds`` actions per src / dest broker (candidates are hashed into
    subround lanes and a segment-argmax runs per (broker, lane)), but across
    rounds a broker participates only while the *cumulative* channel deltas
    stay inside every optimized goal's band (``room_dest`` / ``slack_src``).
    A round's multi-landings are made exact by a violation pass: per-broker
    sums of the round's kept deltas are checked against the remaining
    budgets, and a broker whose sum overshoots falls back to its single
    best action (which passed the per-candidate check by construction).
    Partition uniqueness stays absolute across the whole step — that keeps
    rack / sibling-table checks exact.

    Without lanes, a step's throughput was rounds-per-broker (8): the
    round-2 verdict's 216-step ReplicaDistribution tail at the mid rung was
    one hot broker shedding 8 replicas per step.

    Goals whose metric is finer than a broker channel get their own
    budgets: ``topic_budgets`` = (gain_rep, shed_rep, shed_lead), each
    f32[T*B], bounds the cumulative per-(topic, broker) replica-count and
    leader-count deltas of a step inside the optimized topic bands
    (TopicReplicaDistribution / MinTopicLeaders).  Round 3 capped a step to
    ONE action per (topic, broker) pair instead, which made the topic
    goal's fixpoint as long as its worst pair's overage (90 of the mid
    rung's 154 steps).  ``disk_guard`` still admits one landing per
    destination disk per step (intra-disk bands).

    ``frontier`` compacts every broker-indexed segment space and budget
    tensor onto the active set's power-of-two bucket (FrontierInvariants):
    the scatter/gather/sort chains above run over Bc ≪ B brokers while the
    candidates keep their FULL broker ids (apply_candidates scatters into
    the full model unchanged).  Ineligible candidates may alias compact
    slot 0; every keep/scatter below is masked by eligibility, so the alias
    never contributes.  Budget rows gathered for pad slots (full_of_compact
    = -1 → broker 0) are harmless for the same reason: no eligible
    candidate maps to a pad slot.

    ``compact_k`` gathers the lanes surviving the eligibility masks into a
    dense top-``compact_k``-by-score prefix BEFORE the rounds (live-candidate
    compaction): the sort/scan/scatter chains of the conflict and repair
    rounds then run over Kc ≪ K live lanes instead of the full S×D batch.
    The gathered candidates keep full ids, so the returned keep mask is
    scattered back to length K for the apply.  When more than ``compact_k``
    lanes are live the lowest-scored surplus is dropped — semantically a
    narrower greedy batch, never a band-exactness risk.

    ``repair_oracle`` selects the legacy data-dependent repair (cond-gated
    passes + unbounded drop loop) for differential testing; the default is
    the bounded-depth exact repair (kernels.prefix_cut_admit /
    prefix_admit_safe): fixed alternating src/dest bisection passes plus a
    terminal subset-closed admit — constant op count per step regardless of
    how close the model sits to the band edges.
    """
    num_brokers, num_partitions = model.num_brokers, model.num_partitions
    k_full = score.shape[0]
    lanes_live = jnp.int32(0)
    rep_fired = jnp.int32(0)
    sel_idx = None
    eps = 1e-6
    # Decorrelating tie-break: _best_per_segment resolves equal scores by
    # lowest candidate index, and the K batch is replica-major / dest-minor
    # with destinations in one global top-D order — so for tie-heavy goals
    # (rack conflicts, count distributions: scores are small integers) every
    # source broker's winner picked the SAME destination, the per-dest pass
    # then kept ONE action, and steps landed ~1 action per round regardless
    # of batch width.  A tiny multiplicative hash-jitter (≤1e-4 relative)
    # spreads near-tied winners across destinations without reordering
    # meaningfully different scores.
    # The hash bits depend only on the (static) batch width — numpy math
    # folds them into jaxpr literals (zero equations in the loop body)
    # instead of an 8-op uint32 chain retraced into every step.
    # Both hashes key off the candidate's FULL-batch position and are
    # computed BEFORE live-lane compaction, then gathered through sel_idx:
    # a compacted step sees the same jittered scores and subround lanes as
    # the dense step it stands in for.
    idx_k = np.arange(k_full, dtype=np.uint32)
    jitter = ((idx_k * np.uint32(2654435761)) >> np.uint32(12)).astype(
        np.float32) / np.float32(1 << 20)
    score = score * jnp.asarray(1.0 + 1e-4 * jitter)
    # Subround lane per candidate (decorrelated from the jitter bits).
    lane_np = (((idx_k * np.uint32(0x9E3779B9)) >> np.uint32(4)) %
               np.uint32(subrounds)).astype(np.int32)
    lane = jnp.asarray(lane_np)
    if repair_oracle:
        compact_k = None  # the oracle reproduces the pre-compaction path
    if compact_k is not None and compact_k < k_full:
        live = eligible
        lanes_live = live.sum().astype(jnp.int32)
        _, sel_idx = jax.lax.top_k(jnp.where(live, score, -jnp.inf),
                                   compact_k)
        cand = cgen.take_candidates(cand, sel_idx)
        score = score[sel_idx]
        eligible = live[sel_idx]
        lane = lane[sel_idx]
    if frontier is not None:
        nb_sel = frontier.full_of_compact.shape[0]
        c_of_f = jnp.maximum(frontier.compact_of_full, 0)
        src_b = c_of_f[cand.src]
        dest_b = c_of_f[cand.dest]
        gather = jnp.maximum(frontier.full_of_compact, 0)
        room_dest = room_dest[gather]
        slack_src = slack_src[gather]
        if topic_budgets is not None:
            topic_budgets = tuple(
                b.reshape(model.num_topics, num_brokers)[:, gather].reshape(-1)
                for b in topic_budgets)
    else:
        nb_sel = num_brokers
        src_b, dest_b = cand.src, cand.dest
    src_lane = src_b * subrounds + lane
    dest_lane = dest_b * subrounds + lane
    # Cross-round accumulators materialize lazily: round 1 knows they are
    # all-zero (specialized below), and a single-round step — the default
    # config — never allocates them at all.
    keep_total = used_part = cum_src = cum_dest = None
    d_src, d_dest = _channel_deltas(cand)
    topic_on = topic_budgets is not None
    if topic_on:
        gain_rep, shed_rep, shed_lead = topic_budgets
        n_tb = model.num_topics * nb_sel
        t1 = model.replica_topic[cand.replica]
        safe_r2 = jnp.where(cand.dest_replica >= 0, cand.dest_replica, 0)
        t2 = model.replica_topic[safe_r2]
        # Four (key, delta) legs per candidate on the (topic, broker) grid:
        # the moved replica leaves (t1, src) and lands on (t1, dest); a
        # swap's partner makes the reverse trip on its own topic.
        moves_tb = cand.is_move() | cand.is_swap()
        swap = cand.is_swap()
        lead1 = (cand.is_leadership() |
                 (moves_tb & model.replica_is_leader[cand.replica])
                 ).astype(jnp.float32)
        # Legs 3/4 exist only for swap batches (the partner's reverse trip
        # on its own topic) — a statically swap-free goal keeps 2 legs.
        if has_swaps:
            # A same-topic swap nets to ZERO on the topic grid (the two legs
            # of each key cancel); evaluating its legs independently would
            # falsely reject it at band-edge pairs, so net the legs up
            # front.  The leader channel nets likewise (lead1 vs lead2).
            same_t = swap & (t1 == t2)
            rep1 = jnp.where(same_t, 0.0, moves_tb.astype(jnp.float32))
            rep2 = jnp.where(same_t, 0.0, swap.astype(jnp.float32))
            leg_keys = jnp.stack([t1 * nb_sel + src_b,
                                  t1 * nb_sel + dest_b,
                                  t2 * nb_sel + dest_b,
                                  t2 * nb_sel + src_b])           # i32[L, K]
            d_rep = jnp.stack([-rep1, rep1, -rep2, rep2])         # f32[L, K]
            lead2 = (swap & model.replica_is_leader[safe_r2]).astype(jnp.float32)
            l1 = jnp.where(same_t, lead1 - lead2, lead1)
            l2 = jnp.where(same_t, 0.0, lead2)
            d_lead = jnp.stack([-l1, l1, -l2, l2])                # f32[L, K]
        else:
            leg_keys = jnp.stack([t1 * nb_sel + src_b,
                                  t1 * nb_sel + dest_b])
            d_rep = jnp.stack([-moves_tb.astype(jnp.float32),
                               moves_tb.astype(jnp.float32)])
            d_lead = jnp.stack([-lead1, lead1])
        num_legs = leg_keys.shape[0]
        cum_rep = cum_lead = None
        eps_tb = 1e-6

        def tb_ok(cum, d, gain, shed):
            total = d if cum is None else cum[leg_keys] + d
            return ((total <= gain[leg_keys] + eps_tb) &
                    (total >= -shed[leg_keys] - eps_tb)).all(axis=0)
    if disk_guard:
        safe_sd = jnp.maximum(cand.src_disk, 0)
        safe_dd = jnp.maximum(cand.dest_disk, 0)
        used_sdisk = used_ddisk = None
    for r in range(rounds):
        first, last = r == 0, r == rounds - 1
        # Round 1 is specialized on its accumulators being all-zero: the
        # budget checks compare raw deltas against the budgets directly —
        # no cumulative gathers/adds, no used-partition masks.  With the
        # default config (one round of 128 lanes) that's the WHOLE loop;
        # multi-round steps pay the general form from round 2 on.
        if first:
            elig = eligible
            cum_net = jnp.zeros((nb_sel, NUM_CHANNELS), jnp.float32)
            budget_ok = (
                (d_dest <= room_dest[dest_b] + eps) &
                (d_dest >= -slack_src[dest_b] - eps) &
                (d_src >= -slack_src[src_b] - eps) &
                (d_src <= room_dest[src_b] + eps)
            ).all(axis=1)
        else:
            elig = eligible & ~keep_total & ~used_part[cand.partition] & \
                ~used_part[cand.partition2]
            # Each broker's cumulative NET delta (src-role + dest-role — a
            # broker can shed via one action and gain via another in the
            # same step) stays inside [-shed slack, gain room].  Swaps make
            # d_src positive (source gains) / d_dest negative (dest sheds),
            # so BOTH bounds apply to both roles — one-sided per-role
            # checks let a swap push its source broker over an optimized
            # cap undetected, and separate per-role accumulators allowed
            # up to 2× room in one step.
            cum_net = cum_src + cum_dest
            budget_ok = (
                (cum_net[dest_b] + d_dest <= room_dest[dest_b] + eps) &
                (cum_net[dest_b] + d_dest >= -slack_src[dest_b] - eps) &
                (cum_net[src_b] + d_src >= -slack_src[src_b] - eps) &
                (cum_net[src_b] + d_src <= room_dest[src_b] + eps)
            ).all(axis=1)
        elig = elig & budget_ok
        if topic_on:
            if first:
                cum_rep = jnp.zeros((n_tb,), jnp.float32)
                cum_lead = jnp.zeros((n_tb,), jnp.float32)
            elig = elig & \
                tb_ok(None if first else cum_rep, d_rep, gain_rep, shed_rep) & \
                tb_ok(None if first else cum_lead, d_lead,
                      jnp.inf * jnp.ones_like(gain_rep), shed_lead)
        if disk_guard and not first:
            touches_disk = cand.dest_disk >= 0
            elig = elig & ~(touches_disk & (used_sdisk[safe_sd] | used_ddisk[safe_dd]))
        keep = _best_per_segment(score, src_lane, nb_sel * subrounds, elig)
        keep = _best_per_segment(score, dest_lane, nb_sel * subrounds, keep)
        keep = _best_per_segment(score, cand.partition, num_partitions, keep)
        if has_swaps:
            # Swaps involve a second partition — its uniqueness is absolute
            # too.
            keep = _best_per_segment(score, cand.partition2, num_partitions, keep)
            # Cross-field collision: the two passes above are per-field, so
            # one kept candidate's partition2 can still equal ANOTHER's
            # partition (the same replica would be relocated twice in one
            # round).  Drop the partition2-claimant of any such pair.
            claim1 = jnp.zeros((num_partitions,), bool).at[
                jnp.where(keep, cand.partition, 0)].max(keep)
            keep = keep & ~((cand.partition2 != cand.partition) &
                            claim1[cand.partition2])
        if disk_guard:
            touches = cand.dest_disk >= 0
            kd = _best_per_segment(score, safe_sd, model.num_disks,
                                   keep & touches)
            kd = _best_per_segment(score, safe_dd, model.num_disks, kd)
            keep = (keep & ~touches) | kd

        # Budget-exactness for multi-landings: per-broker sums of this
        # round's kept deltas vs the REMAINING budgets; a violating broker
        # falls back to its single best kept action.
        def round_net(k):
            km = k[:, None]
            s = jnp.zeros_like(cum_net).at[jnp.where(k, dest_b, 0)].add(
                jnp.where(km, d_dest, 0.0))
            s = s.at[jnp.where(k, src_b, 0)].add(jnp.where(km, d_src, 0.0))
            return s

        if topic_on:
            def round_tb(k, d):
                keys = jnp.where(k[None, :], leg_keys, 0)
                return jnp.zeros((n_tb,), jnp.float32).at[keys.reshape(-1)].add(
                    jnp.where(k[None, :], d, 0.0).reshape(-1))

            def tb_viol(k):
                rep = cum_rep + round_tb(k, d_rep)
                lead = cum_lead + round_tb(k, d_lead)
                return ((rep > gain_rep + eps_tb) |
                        (rep < -shed_rep - eps_tb) |
                        (lead < -shed_lead - eps_tb))

            def leg_contrib(i, k):
                return k & ((d_rep[i] != 0.0) | (d_lead[i] != 0.0))

            # Per-key lanes + key-exact repair: the elig budget check bounds
            # ONE candidate at a time, so many lane winners can pile onto a
            # key with less room.  Admit up to nl per key (lanes — wide
            # enough that a hot pair drains at budget speed), then drop a
            # violating key's extras down to its best-fitting prefix —
            # without nuking the whole broker (the broker-stage fallback
            # below stays the last resort for cross-key flips).
            nl = 16
            lane_tb = (lane % nl).astype(jnp.int32)
            for i in range(num_legs):
                contrib = leg_contrib(i, keep)
                sel = _best_per_segment(score, leg_keys[i] * nl + lane_tb,
                                        n_tb * nl, contrib)
                keep = keep & (~contrib | sel)

            hi_tb = jnp.stack([gain_rep, jnp.full_like(gain_rep, jnp.inf)], 1)
            lo_tb = jnp.stack([-shed_rep, -shed_lead], 1)
            cum_tb = jnp.stack([cum_rep, cum_lead], 1)

            if repair_oracle:
                def _tb_repair(k):
                    # Score-ranked prefix per violating key (same granularity
                    # fix as the broker-channel repair: single-best fallbacks
                    # made hot (topic, broker) pairs drain 1 action/step).
                    vt = tb_viol(k)
                    for i in range(num_legs):
                        contrib = leg_contrib(i, k)
                        admit = _prefix_admit_role(
                            score, leg_keys[i],
                            jnp.stack([d_rep[i], d_lead[i]], 1),
                            contrib, cum_tb, lo_tb, hi_tb, n_tb)
                        k = k & (~(contrib & vt[leg_keys[i]]) | admit)
                    return k

                # The legacy path gates the passes behind a cond — branch
                # divergence traded away per-step flatness for skipping the
                # common in-room case.
                keep = jax.lax.cond(tb_viol(keep).any(), _tb_repair,
                                    lambda k: k, keep)
            else:
                # Bounded repair: the per-key exact cuts ALWAYS run — they
                # are masked no-ops on rounds with no violating key, so the
                # per-step cost is constant instead of band-edge-dependent.
                vt = tb_viol(keep)
                rep_fired = rep_fired + vt.any().astype(jnp.int32)
                for i in range(num_legs):
                    contrib = leg_contrib(i, keep)
                    admit = kernels.prefix_cut_admit(
                        score, leg_keys[i],
                        jnp.stack([d_rep[i], d_lead[i]], 1),
                        contrib, cum_tb, lo_tb, hi_tb, n_tb)
                    keep = keep & (~(contrib & vt[leg_keys[i]]) | admit)

        def net_viol(k):
            total = cum_net + round_net(k)
            out = ((total > room_dest + eps) |
                   (total < -slack_src - eps)).any(axis=1)
            if topic_on:
                tb_bad = tb_viol(k)
                # Fold (topic, broker) violations onto the broker axis so the
                # per-broker fallback stages and the final drop loop repair
                # the rare cross-key flips too.
                bad_b = jnp.zeros((nb_sel,), bool).at[
                    jnp.arange(n_tb, dtype=jnp.int32) % nb_sel].max(tb_bad)
                out = out | bad_b
            return out

        # Exactness stages: a net-violating broker keeps the score-ranked
        # PREFIX of its actions that still fits the remaining budgets (per
        # role; the old single-best fallback produced 1-action/step
        # convergence tails at band edges — 16 such steps in the mid rung's
        # ReplicaDistribution fixpoint).
        if repair_oracle:
            # Legacy repair: a data-dependent drop loop sheds ALL actions of
            # any broker still violating — including brokers flipped into
            # violation by another broker's drops (removing one leg of a
            # compensating pair raises the partner's net) — until no
            # violation remains.  Monotone (a violating broker always has a
            # kept action to drop, since cum_net alone respects the bounds
            # by induction), so it terminates, but its trip count is
            # data-dependent: band-edge states pay extra sequential
            # iterations.  Kept verbatim behind CRUISE_REPAIR_ORACLE=1 as
            # the differential-test oracle.
            def _broker_repair(k):
                v = net_viol(k)
                admit_d = _prefix_admit_role(score, dest_b, d_dest, k, cum_net,
                                             -slack_src, room_dest, nb_sel)
                k = k & (~v[dest_b] | admit_d)
                v = net_viol(k)
                admit_s = _prefix_admit_role(score, src_b, d_src, k, cum_net,
                                             -slack_src, room_dest, nb_sel)
                k = k & (~v[src_b] | admit_s)

                def _drop_violators(kk):
                    vv = net_viol(kk)
                    return kk & ~vv[src_b] & ~vv[dest_b]

                return jax.lax.while_loop(lambda kk: net_viol(kk).any(),
                                          _drop_violators, k)

            keep = jax.lax.cond(net_viol(keep).any(), _broker_repair,
                                lambda k: k, keep)
        else:
            # Bounded-depth exact repair: a FIXED number of alternating
            # (dest, src) prefix-cut passes absorbs the direct violations
            # (each cut is the bisection over "zero bad prefix positions" —
            # identical to the legacy admit's cut), then ONE subset-closed
            # safe admit terminates the flip cascade without any loop: it
            # bounds each broker's admitted Σd⁺ ≤ hi−cum and Σd⁻ ≥ lo−cum
            # *separately* across BOTH roles (2K concatenated elements), so
            # any subset of the admitted set — in particular the one left
            # after intersecting the per-candidate role copies — still fits
            # every channel.  Every pass is masked to violating segments and
            # the terminal trim is gated on a residual violation, so
            # violation-free steps are bit-identical to the legacy path.
            v0 = net_viol(keep)
            rep_fired = rep_fired + v0.any().astype(jnp.int32)
            v = v0
            for _ in range(2):
                admit_d = kernels.prefix_cut_admit(
                    score, dest_b, d_dest, keep, cum_net,
                    -slack_src, room_dest, nb_sel)
                keep = keep & (~v[dest_b] | admit_d)
                v = net_viol(keep)
                admit_s = kernels.prefix_cut_admit(
                    score, src_b, d_src, keep, cum_net,
                    -slack_src, room_dest, nb_sel)
                keep = keep & (~v[src_b] | admit_s)
                v = net_viol(keep)
            any_left = v.any()
            kk = score.shape[0]
            safe2 = kernels.prefix_admit_safe(
                jnp.concatenate([score, score]),
                jnp.concatenate([src_b, dest_b]),
                jnp.concatenate([d_src, d_dest], axis=0),
                jnp.concatenate([keep, keep]),
                cum_net, -slack_src, room_dest, nb_sel)
            safe = safe2[:kk] & safe2[kk:]
            if topic_on:
                safe_t = kernels.prefix_admit_safe(
                    jnp.concatenate([score] * num_legs),
                    jnp.concatenate([leg_keys[i] for i in range(num_legs)]),
                    jnp.concatenate(
                        [jnp.stack([d_rep[i], d_lead[i]], 1)
                         for i in range(num_legs)], axis=0),
                    jnp.concatenate(
                        [leg_contrib(i, keep) for i in range(num_legs)]),
                    cum_tb, lo_tb, hi_tb, n_tb).reshape(num_legs, kk)
                for i in range(num_legs):
                    safe = safe & (~leg_contrib(i, keep) | safe_t[i])
            keep = jnp.where(any_left, keep & safe, keep)

        keep_total = keep if first else keep_total | keep
        if last:
            # The final round's bookkeeping has no reader — skip the
            # scatter/add chain entirely (the default single-round config
            # never executes it at all).
            continue
        if first:
            used_part = jnp.zeros((num_partitions,), bool)
            cum_src = jnp.zeros((nb_sel, NUM_CHANNELS), jnp.float32)
            cum_dest = jnp.zeros((nb_sel, NUM_CHANNELS), jnp.float32)
            if disk_guard:
                used_sdisk = jnp.zeros((model.num_disks,), bool)
                used_ddisk = jnp.zeros((model.num_disks,), bool)
        used_part = used_part.at[jnp.where(keep, cand.partition, 0)].max(keep)
        used_part = used_part.at[jnp.where(keep, cand.partition2, 0)].max(keep)
        km = keep[:, None]
        cum_src = cum_src.at[jnp.where(keep, src_b, 0)].add(
            jnp.where(km, d_src, 0.0))
        cum_dest = cum_dest.at[jnp.where(keep, dest_b, 0)].add(
            jnp.where(km, d_dest, 0.0))
        if topic_on:
            cum_rep = cum_rep + round_tb(keep, d_rep)
            cum_lead = cum_lead + round_tb(keep, d_lead)
        if disk_guard:
            touches = keep & (cand.dest_disk >= 0)
            used_sdisk = used_sdisk.at[jnp.where(touches, safe_sd, 0)].max(touches)
            used_ddisk = used_ddisk.at[jnp.where(touches, safe_dd, 0)].max(touches)
    if sel_idx is not None:
        # Scatter the compacted keep decisions back onto the full candidate
        # axis (dead lanes were never winners, so plain scatter suffices).
        keep_total = jnp.zeros((k_full,), bool).at[sel_idx].set(keep_total)
    stats = (rep_fired, lanes_live,
             jnp.int32(kernels.bisect_depth(score.shape[0])))
    return keep_total, stats


# ---------------------------------------------------------------------------
# The per-goal jitted step
# ---------------------------------------------------------------------------

def _topic_budgets(all_specs: Tuple[GoalSpec, ...], model: TensorClusterModel,
                   arrays: BrokerArrays, constraint: BalancingConstraint,
                   inv: Optional[StepInvariants] = None):
    """(gain_rep, shed_rep, shed_lead), each f32[T*B] — how much each
    (topic, broker) pair may cumulatively gain / shed in replica count and
    shed in leader count this step without leaving any optimized topic
    band.  None when no topic-metric goal is in play.  ``inv`` optionally
    supplies the step-invariant topic band sides / designated mask."""
    has_topic = any(s.kind == "topic_replica_distribution" for s in all_specs)
    has_min_leaders = any(s.kind == "min_topic_leaders" for s in all_specs)
    if not has_topic and not has_min_leaders:
        return None
    n_tb = model.num_topics * model.num_brokers
    inf = jnp.full((n_tb,), jnp.inf, jnp.float32)
    gain_rep = shed_rep = shed_lead = inf
    alive_row = arrays.alive[None, :]
    if has_topic:
        tbc = model.topic_broker_replica_counts().astype(jnp.float32)
        if inv is not None and inv.topic_lower is not None:
            lower_t, upper_t = inv.topic_lower, inv.topic_upper
        else:
            lower_t, upper_t = kernels._topic_limits(model, arrays, constraint)
        gain = jnp.maximum(upper_t[:, None] - tbc, 0.0)
        shed = jnp.maximum(tbc - lower_t[:, None], 0.0)
        # Dead brokers shed without band limits (healing; mirrors the broker
        # channel budgets' exemption).
        shed = jnp.where(alive_row, shed, jnp.inf)
        gain_rep, shed_rep = gain.reshape(-1), shed.reshape(-1)
    if has_min_leaders:
        tlc = model.topic_leader_counts().astype(jnp.float32)
        if inv is not None and inv.designated is not None:
            designated = inv.designated
        else:
            designated = kernels._designated_topic_mask(model, constraint)
        need = float(constraint.min_topic_leaders_per_broker)
        shed = jnp.where(designated[:, None], jnp.maximum(tlc - need, 0.0),
                         jnp.inf)
        shed = jnp.where(alive_row, shed, jnp.inf)
        shed_lead = shed.reshape(-1)
    return gain_rep, shed_rep, shed_lead


def compute_step_invariants(spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                            model: TensorClusterModel, arrays: BrokerArrays,
                            constraint: BalancingConstraint) -> StepInvariants:
    """All step-invariant tensors of one goal's fixpoint (see StepInvariants
    for the invariance argument).  _goal_fixpoint computes this ONCE outside
    its while_loop; the loop body closes over the result, so XLA hoists
    ~20% of the former per-step op chain into the once-per-fixpoint
    prologue."""
    all_specs = (spec,) + tuple(prev_specs)
    upper_min, lower_max = _band_sides(all_specs, model, arrays, constraint)
    spec_lower, spec_upper = kernels.limits(spec, model, arrays, constraint)
    topic_lower = topic_upper = designated = None
    if any(s.kind == "topic_replica_distribution" for s in all_specs):
        topic_lower, topic_upper = kernels._topic_limits(model, arrays,
                                                         constraint)
    if any(s.kind == "min_topic_leaders" for s in all_specs):
        designated = kernels._designated_topic_mask(model, constraint)
    return StepInvariants(upper_min=upper_min, lower_max=lower_max,
                          spec_lower=spec_lower, spec_upper=spec_upper,
                          topic_lower=topic_lower, topic_upper=topic_upper,
                          designated=designated)


# The tunneled TPU's remote-compile service hangs on S×D cross batches
# beyond roughly this many candidates (probed round 5: 256k-wide programs
# at 1000 brokers hung for two rounds; the same shapes compile and run
# once capped — BASELINE.md).  That is a deployment property of ONE
# backend transport, not of the analyzer, so the ceiling is opt-in
# (config/env), not inferred from backend detection: CPU / virtual-mesh
# runs compile 1M-shape programs in seconds and need the wide dest sets
# (nd=16 at 7k brokers starves the usage-distribution goals' exploration),
# and a local (untunneled) TPU does not share the remote-compile hang.
_COMPILE_CEILING_K = 32_768


def _cross_ceiling_k() -> Optional[int]:
    """The active candidate-batch compile ceiling, or None when unlimited.

    Gated by CRUISE_TPU_COMPILE_CEILING (env, or the
    analyzer.tpu.compile.ceiling config key propagated to it by app.py).
    Unset / "off" / "0" / "none" disables it everywhere — the DEFAULT:
    backend detection used to impose the ceiling on any tpu backend, which
    silently narrowed candidate batches on healthy local TPUs.  "auto"
    opts back into the historical behavior — the ceiling binds only when
    the tpu backend is active (deployments on the tunneled dev backend,
    whose remote-compile service is what hangs on wide programs, set this;
    bench.py does).  A positive integer imposes that ceiling on ANY
    backend (useful to reproduce TPU-shaped batches on CPU).  Every clamp
    the active ceiling causes is counted by the
    ``GoalOptimizer.compile-ceiling-clamps`` sensor and logged.
    """
    # _goal_step reads this at trace time via _goal_num_sources; every
    # program cache that can reach it keys on _cross_ceiling_k() (see
    # _get_step_fn), so a mid-process flip recompiles, never serves stale.
    # cruise-lint: disable=trace-purity (static config; keyed into every reachable jit cache)
    raw = os.environ.get("CRUISE_TPU_COMPILE_CEILING", "off").strip().lower()
    if raw in ("", "0", "off", "none", "false"):
        return None
    if raw != "auto":
        try:
            return max(1, int(raw))
        except ValueError:
            _LOG.warning("ignoring non-integer CRUISE_TPU_COMPILE_CEILING=%r",
                         raw)
            return None
    try:
        return _COMPILE_CEILING_K if jax.default_backend() == "tpu" else None
    except Exception:  # noqa: BLE001 — backend probing must never fail a run
        return None


def _goal_num_sources(spec: GoalSpec, model: TensorClusterModel,
                      num_sources: int, num_dests: int) -> int:
    """Per-goal source-width policy.  Rack healing is purely source-bound
    (every conflicted replica is one independent fix; the mid rung spent 5
    steps draining 699 conflicts 140-at-a-time through ns=200), so it gets
    a wide batch; band goals keep the configured width — their throughput
    is budget- and lane-bound, and wider cross batches measurably hurt
    (round-5 sweep: ns=512 at mid grew the stack 78 -> 95 steps).  The
    widened batch still respects the tunneled-TPU compile ceiling."""
    if spec.kind in ("rack", "rack_distribution"):
        ns = max(1, min(model.num_replicas_padded, max(4 * num_sources, 1024)))
        ceiling = _cross_ceiling_k()
        if ceiling is not None:
            ns = max(num_sources, min(ns, ceiling // max(num_dests, 1)))
        return ns
    return num_sources


def _goal_step(model: TensorClusterModel, options: OptimizationOptions,
               spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
               constraint: BalancingConstraint,
               num_sources: int, num_dests: int, mesh=None,
               invariants: Optional[StepInvariants] = None,
               frontier: Optional[FrontierInvariants] = None,
               repair_oracle: bool = False, flight: bool = False):
    """One optimization step for ``spec``: returns
    ``(new_model, num_applied, sel_stats)`` where ``sel_stats`` is the
    selection's ``(repair_fired, lanes_live, bisect_depth)`` i32 scalars
    (see select_batched).  ``repair_oracle`` selects the legacy
    data-dependent repair path (CRUISE_REPAIR_ORACLE=1).  ``flight``
    (static, CRUISE_FLIGHT_RECORDER=1) appends a fourth element — the
    flight-recorder extras ``(frontier_count, score_bits, kind)`` i32
    scalars — computed purely from already-materialized step values, so
    the selection itself is untouched and recorder-on proposals stay
    bit-identical to recorder-off.

    Static args (spec, prev_specs, constraint, widths, mesh) select the
    compiled graph; model/options are traced.  With ``mesh`` set, the
    candidate batch is sharding-constrained along its K axis so GSPMD
    partitions the scoring/masking math across the mesh devices (see
    parallel/mesh.py).  ``invariants`` carries the step-invariant band
    sides / topic sides precomputed by the fixpoint; a standalone step
    computes its own (identical math, just not hoisted).  ``frontier``
    restricts the step to the active broker set (see FrontierInvariants):
    sources and destinations come from active brokers only, and the
    selection's broker-segment spaces run over the compacted axis.
    """
    arrays = BrokerArrays.from_model(model)
    num_sources = _goal_num_sources(spec, model, num_sources, num_dests)
    inv = invariants
    if inv is None:
        inv = compute_step_invariants(spec, prev_specs, model, arrays,
                                      constraint)
    bands = (inv.spec_lower, inv.spec_upper)
    # ONE relevance ranking per step, shared by every candidate builder —
    # each builder used to recompute the ~150-op ranking itself.
    relevance = kernels.source_replica_relevance(spec, model, arrays,
                                                 constraint, bands=bands)
    active = None
    if frontier is not None:
        active = frontier.active
        # Source replicas only from active brokers.  The frontier engages
        # only for band kinds with no offline replicas (the driver falls
        # back to dense otherwise), so the -inf mask never clobbers the
        # offline-healing _BIG sentinel in practice.
        relevance = jnp.where(active[model.replica_broker], relevance,
                              -jnp.inf)

    batches = []
    kind_ids = []  # FLIGHT_KINDS index per batch, parallel to ``batches``
    if spec.uses_moves:
        # The 1:1 transport-matched batch drains count surpluses at batch
        # width (see matched_move_candidates); the cross batch stays as
        # the explorer for pairs the match rejects (sibling / rack
        # collisions) and shrinks to a quarter width when a matched batch
        # carries the bulk — at the large rung the full-width cross batch
        # was pure per-step compute with its winners mostly duplicating
        # the match.
        num_matched = 0
        if spec.kind == "replica_distribution":
            num_matched = cgen.default_num_matched(model, num_sources)
        elif spec.kind == "topic_replica_distribution":
            # The topic match needs the wider floor: its surplus spreads
            # over T·B pairs and narrowing the batch to the replica-goal
            # width grew the fixpoint 20 -> 27 steps at mid.
            num_matched = max(1, min(model.num_replicas_padded,
                                     max(16 * num_sources, 4096)))
        # Only the replica-count goal's cross batch shrinks: the topic
        # goal's matched batch covers band entry but its cross batch still
        # finds the key-budget-constrained shuffles (shrinking it grew the
        # fixpoint 18 -> 26 steps at mid).
        cross_ns = (min(num_sources, max(64, num_sources // 4))
                    if spec.kind == "replica_distribution" else num_sources)
        batches.append(cgen.combined_move_candidates(
            spec, model, arrays, constraint, options, cross_ns, num_dests,
            num_matched=num_matched, relevance=relevance, bands=bands,
            active=active, mesh=mesh))
        kind_ids.append(FLIGHT_KINDS.index("move"))
    if spec.uses_leadership:
        batches.append(cgen.leadership_candidates(spec, model, arrays, constraint,
                                                  options, num_sources,
                                                  relevance=relevance,
                                                  bands=bands))
        kind_ids.append(FLIGHT_KINDS.index("leadership"))
    if spec.uses_intra_moves:
        batches.append(cgen.intra_disk_candidates(spec, model, arrays, constraint,
                                                  options, num_sources,
                                                  relevance=relevance,
                                                  bands=bands))
        kind_ids.append(FLIGHT_KINDS.index("intra_move"))
    # Swap widths scale with the (possibly fast-mode / max-candidates
    # clamped) move widths so the latency/batch-size knobs bound them too.
    sw_s = min(cgen.default_num_swap_sources(model), num_sources)
    sw_p = min(cgen.default_num_swap_partners(model),
               max(2, num_dests), model.num_replicas_padded)
    if spec.uses_swaps:
        batches.append(cgen.swap_candidates(
            spec, model, arrays, constraint, options, sw_s, sw_p,
            relevance=relevance, bands=bands, active=active, mesh=mesh))
        kind_ids.append(FLIGHT_KINDS.index("swap"))
    if spec.uses_intra_swaps:
        batches.append(cgen.intra_swap_candidates(
            spec, model, arrays, constraint, options, sw_s, sw_p,
            relevance=relevance, bands=bands))
        kind_ids.append(FLIGHT_KINDS.index("intra_swap"))
    cand = batches[0]
    for extra in batches[1:]:
        cand = cgen.concat_candidates(cand, extra)
    if mesh is not None:
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
        cand = jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sharding), cand)

    feasible = kernels.self_feasible(spec, model, arrays, cand, constraint,
                                     bands=bands)
    # Band-kind prev goals' vetoes are fully subsumed by the channel
    # budgets below: room_dest/slack_src are built from the SAME
    # limits()/delta math over all_specs, and select_batched's per-candidate
    # eligibility check (cum = 0 in round 1) equals the per-candidate band
    # bounds.  Verified empirically: the full 15-goal mid stack produces
    # identical proposal sets with the per-spec band mask chain removed —
    # which deletes ~2 serial mask chains per optimized goal from the
    # per-step op chain (the late-stack goals carried 10+).  Structural
    # kinds (rack, topic counts, min-leaders, intra-disk) keep their
    # dedicated accepts.
    if _DBG_NO_BUDGETS:
        # The budget ablation must not silently drop band enforcement too:
        # with budgets off, the per-spec band mask chain is the band check
        # (and doubles as the production oracle for the equivalence —
        # tests/test_optimizer.py::test_band_budgets_subsume_band_accepts).
        accepted = kernels.accepts_band_batch(prev_specs, model, arrays, cand,
                                              constraint)
    else:
        accepted = None
    for prev in prev_specs:
        if not kernels.is_band_kind(prev):
            a = kernels.accepts(prev, model, arrays, cand, constraint)
            accepted = a if accepted is None else accepted & a
    if accepted is None or _DBG_NO_ACCEPTS:
        accepted = jnp.ones(cand.k, bool)
    score = kernels.score(spec, model, arrays, cand, constraint, bands=bands)

    eligible = cand.valid & feasible & accepted & (score > _MIN_SCORE)
    if active is not None:
        # Both endpoints inside the frontier: the compacted selection below
        # aliases inactive brokers onto compact slot 0, so they must never
        # be eligible (the candidate builders already bias against them;
        # this makes it absolute).
        eligible = eligible & active[cand.src] & active[cand.dest]
    if (mesh is not None and frontier is not None
            and frontier.shard_active is not None):
        # Per-shard frontier mask: each candidate endpoint's compact-slot
        # liveness ANDed into eligibility.  Semantically subsumed by the
        # active[] clause above (an inactive broker has no live compact
        # slot), so proposals stay bit-identical — but it hands GSPMD a
        # genuinely P(search)-partitioned compact-axis operand on the
        # eligibility path, anchoring the by-candidate partition of the
        # compacted selection instead of letting the bucket replicate.
        sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(mesh.axis_names[0]))
        shard_live = jax.lax.with_sharding_constraint(
            frontier.shard_active, sharding)
        bc = shard_live.shape[0]
        slot_src = jnp.clip(frontier.compact_of_full[cand.src], 0, bc - 1)
        slot_dest = jnp.clip(frontier.compact_of_full[cand.dest], 0, bc - 1)
        eligible = eligible & shard_live[slot_src] & shard_live[slot_dest]
    all_specs = (spec,) + prev_specs
    room_dest, slack_src = _channel_budgets(all_specs, model, arrays, constraint,
                                            sides=(inv.upper_min, inv.lower_max))
    topic_budgets = _topic_budgets(all_specs, model, arrays, constraint, inv=inv)
    if _DBG_NO_BUDGETS:
        room_dest = jnp.full_like(room_dest, jnp.inf)
        slack_src = jnp.full_like(slack_src, jnp.inf)
        topic_budgets = None
    disk_guard = any(s.kind in ("intra_disk_capacity", "intra_disk_distribution")
                     for s in all_specs)
    # moves.per.step: each round keeps up to `subrounds` actions per broker,
    # so rounds = ceil(moves_per_broker_step / subrounds).  Lanes are nearly
    # free (same op count, bigger segment space); serial rounds are not —
    # prefer wide lanes over many rounds.
    # moves.per.step remains the hard per-broker cap: lanes never exceed it
    # (128 lanes of one round for the default; a throttled config gets
    # exactly its configured width).
    subrounds = min(SUBROUNDS, max(1, int(constraint.moves_per_broker_step)))
    rounds = max(1, -(-int(constraint.moves_per_broker_step) // subrounds))
    if _DBG_TRIVIAL_SELECT:
        keep = _best_per_segment(score, jnp.zeros(cand.k, jnp.int32), 1, eligible)
        sel_stats = (jnp.int32(0), jnp.int32(0), jnp.int32(0))
    else:
        nb_sel_static = (frontier.full_of_compact.shape[0]
                         if frontier is not None else model.num_brokers)
        compact_k = (None if repair_oracle
                     else _lane_bucket(cand.k, nb_sel_static, subrounds))
        keep, sel_stats = select_batched(
            score, cand, eligible, model, room_dest, slack_src,
            topic_budgets, disk_guard, rounds=rounds,
            subrounds=subrounds,
            has_swaps=bool(spec.uses_swaps or spec.uses_intra_swaps),
            frontier=frontier, compact_k=compact_k,
            repair_oracle=repair_oracle)
    new_model = apply_candidates(model, cand, keep)
    if not flight:
        return new_model, keep.sum(), sel_stats
    # Flight-recorder extras: read-only derivations from values the step
    # already materialized (score/eligible/keep) plus one frontier_active
    # recomputation for band kinds — the per-step convergence view even in
    # dense mode.  None of this feeds back into selection.
    n_kept = keep.sum()
    off = 0
    seg_counts = []
    for b in batches:
        seg_counts.append(keep[off:off + b.k].sum())
        off += b.k
    best_kind = jnp.asarray(kind_ids, jnp.int32)[
        jnp.argmax(jnp.stack(seg_counts))]
    kind = jnp.where(n_kept > 0, best_kind, jnp.int32(-1)).astype(jnp.int32)
    best_score = jnp.max(jnp.where(eligible, score, -jnp.inf))
    score_bits = jax.lax.bitcast_convert_type(
        best_score.astype(jnp.float32), jnp.int32)
    if kernels.is_band_kind(spec):
        fcount = kernels.frontier_active(
            spec, model, arrays, constraint).sum().astype(jnp.int32)
    else:
        fcount = jnp.int32(-1)
    return new_model, n_kept, sel_stats, (fcount, score_bits, kind)


_step_cache: Dict[tuple, object] = {}


def donation_copy(model: TensorClusterModel) -> TensorClusterModel:
    """Buffer-level copy of every device leaf of ``model``.

    Callers that pass ``donate_model=True`` to :func:`optimize` surrender the
    input model's buffers (donation aliases them into the outputs and marks
    them deleted).  A caller that still needs the pre-optimization state —
    ``proposals.diff`` reads both sides — optimizes a copy and keeps the
    original: ``optimize(donation_copy(model), ..., donate_model=True)``.
    Host (numpy) leaves pass through untouched; they are never donated.
    """
    return jax.tree_util.tree_map(
        lambda leaf: jnp.array(leaf) if isinstance(leaf, jax.Array) else leaf,
        model)


def _persist_token(kind: str, key: tuple, *trees) -> Optional[str]:
    """Marker token for restart-aware ``fresh_compile`` reporting, or None
    when no persistent compile cache is active (env enables lazily here so
    ``CRUISE_COMPILE_CACHE_DIR`` works for bench/CLI runs without app.py).
    The traced-argument shape/dtype signature joins the python cache key
    because the jit fn re-compiles per input shape under the same key."""
    if compile_cache.maybe_enable_from_env() is None:
        return None
    sig = tuple((tuple(leaf.shape), str(leaf.dtype))
                for leaf in jax.tree_util.tree_leaves(trees)
                if hasattr(leaf, "shape"))
    return compile_cache.program_token(kind, key, sig)


def _get_step_fn(spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                 constraint: BalancingConstraint, num_sources: int, num_dests: int,
                 mesh=None, donate: bool = False):
    oracle = _repair_oracle()
    # The traced step derives rack-goal batch widths from the compile
    # ceiling (_goal_num_sources), so the ceiling is part of the program.
    ceiling = _cross_ceiling_k()
    aot = _aot_prelower()
    key = (spec, prev_specs, constraint, num_sources, num_dests, mesh, donate,
           oracle, ceiling, aot)
    fn = _step_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_goal_step, spec=spec, prev_specs=prev_specs,
                             constraint=constraint, num_sources=num_sources,
                             num_dests=num_dests, mesh=mesh,
                             repair_oracle=oracle),
                     donate_argnums=(0,) if donate else ())
        _step_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Device-resident fixpoint: the whole per-goal loop in ONE XLA dispatch
# ---------------------------------------------------------------------------

def _goal_fixpoint(model: TensorClusterModel, options: OptimizationOptions,
                   spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                   constraint: BalancingConstraint, num_sources: int,
                   num_dests: int, max_steps: int, mesh=None,
                   repair_oracle: bool = False):
    """Run ``spec`` to its fixpoint entirely on device.

    The reference's hot loop (GoalOptimizer.java:417-492 →
    AbstractGoal.optimize) re-enters the JVM between every applied action;
    round 1 of this build still re-entered *Python* between every step
    (one jitted step + a blocking host sync per step, up to 256 × goal).
    Here the whole candidate-gen / score / mask / select / apply /
    convergence-test cycle is a ``lax.while_loop`` body, so one goal costs
    one dispatch regardless of how many steps it takes.  Returns device
    scalars ``(model, steps, actions, satisfied_before, satisfied_after,
    capped)`` — ``capped`` distinguishes hitting ``max_steps`` from a true
    fixpoint (round-1 verdict: cap-out was silent).
    """
    arrays0 = BrokerArrays.from_model(model)
    before = kernels.goal_satisfied(spec, model, arrays0, constraint)
    # Already-satisfied goals skip the step graph entirely: a satisfied
    # goal's self_feasible mask is empty for every kind (violated_brokers
    # covers dead-broker leftovers for hard goals), so the first step would
    # generate/score/select a K batch just to apply nothing.  In a default
    # stack ~2/3 of the goals enter satisfied — at the small rung this is
    # most of the wall clock.  Offline replicas disable the shortcut (soft
    # goals' scoring carries the healing bonus and may act even in-band).
    any_offline = (model.replica_offline_now() & model.replica_valid).any()
    skip = before & ~any_offline
    # Step-invariant band/topic sides, computed ONCE here: the body closes
    # over them, so they become while_loop constvars — loop constants XLA
    # evaluates once per fixpoint dispatch instead of once per step (see
    # StepInvariants for why they are invariant and what freezing them at
    # fixpoint entry means for healing runs).
    inv = compute_step_invariants(spec, prev_specs, model, arrays0, constraint)

    def cond(state):
        _, steps, _, last_n = state
        return (last_n > 0) & (steps < max_steps)

    def body(state):
        m, steps, total, _ = state
        new_m, n, _sel = _goal_step(m, options, spec, prev_specs, constraint,
                                    num_sources, num_dests, mesh,
                                    invariants=inv,
                                    repair_oracle=repair_oracle)
        n = n.astype(jnp.int32)
        return (new_m, steps + 1, total + n, n)

    init = (model, jnp.int32(0), jnp.int32(0),
            jnp.where(skip, jnp.int32(0), jnp.int32(1)))
    model, steps, total, last_n = jax.lax.while_loop(cond, body, init)
    arrays1 = BrokerArrays.from_model(model)
    after = kernels.goal_satisfied(spec, model, arrays1, constraint)
    capped = (steps >= max_steps) & (last_n > 0)
    return model, steps, total, before, after, capped


_fixpoint_cache: Dict[tuple, object] = {}


def _get_fixpoint_fn(spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                     constraint: BalancingConstraint, num_sources: int,
                     num_dests: int, max_steps: int, mesh=None,
                     donate: bool = False):
    oracle = _repair_oracle()
    ceiling = _cross_ceiling_k()
    aot = _aot_prelower()
    key = (spec, prev_specs, constraint, num_sources, num_dests, max_steps,
           mesh, donate, oracle, ceiling, aot)
    fn = _fixpoint_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_goal_fixpoint, spec=spec, prev_specs=prev_specs,
                             constraint=constraint, num_sources=num_sources,
                             num_dests=num_dests, max_steps=max_steps, mesh=mesh,
                             repair_oracle=oracle),
                     donate_argnums=(0,) if donate else ())
        _fixpoint_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Shrinking-frontier stepping: per-step cost scales with remaining imbalance
# ---------------------------------------------------------------------------

# Below this broker count the frontier driver always runs dense: compaction
# buys nothing at tier-1 shapes (the whole cluster fits one bucket) and
# keeping the dense path makes "bit-identical proposals at tier-1 sizes" a
# structural property rather than a numerical accident.
_FRONTIER_DENSE_MIN = 64


def _frontier_bucket(num_active: int, num_brokers: int) -> Optional[int]:
    """The compacted broker-axis length for ``num_active`` active brokers,
    or None when the dense path should run.  Buckets double from 64, so at
    most ~log2(B) distinct compacted shapes (= executables) exist per goal;
    a bucket that would not be meaningfully smaller than B (or an active
    set over half the cluster) falls back to dense — the compacted program
    would do the same work with extra gathers."""
    if num_brokers <= _FRONTIER_DENSE_MIN:
        return None
    bucket = pow2_bucket(num_active, _FRONTIER_DENSE_MIN)
    if bucket >= num_brokers or 2 * num_active > num_brokers:
        return None
    return bucket


def _frontier_widths(bucket: int, ns: int, nd: int, lanes: int = 1):
    """(ns, nd) for a compacted chunk: candidate widths shrink with the
    frontier — the K = S·D batch is where per-step cost actually lives, and
    an active set of Bc brokers can neither source nor sink more than a few
    replicas per broker per step.  Floors keep exploration alive.

    ``lanes`` (mesh size under ``distributed_frontier_fixpoint``) rounds
    each width UP to a lane multiple so the compacted candidate batch
    shards evenly over the mesh axis — GSPMD handles ragged shardings by
    padding anyway; rounding on the host keeps every chip's slice identical
    and the compacted executables shape-stable across bucket transitions."""
    cns = max(1, min(ns, max(32, 4 * bucket)))
    cnd = max(1, min(nd, bucket))
    if lanes > 1:
        cns = -(-cns // lanes) * lanes
        cnd = -(-cnd // lanes) * lanes
    return cns, cnd


def _build_frontier(active_np: np.ndarray, bucket: int,
                    mesh=None) -> FrontierInvariants:
    """Host-side index maps from a fetched bool[B] mask (numpy: the mask was
    just device_get for the bucket decision; building the maps here costs
    nothing on device and keeps the compact ids dense and stable).

    Under a multi-device ``mesh`` the invariants additionally carry the
    per-shard frontier mask ``shard_active`` (bool[bucket] compact-slot
    liveness) device_put with ``P(search)`` — each device owns its slice of
    the bucket, giving every GSPMD chunk a genuinely partitioned
    compact-axis operand (see FrontierInvariants).  The pow2 bucket ladder
    starts at ``_FRONTIER_DENSE_MIN`` so the bucket always divides evenly
    over power-of-two meshes; a non-dividing mesh degrades to a replicated
    mask rather than ragged shards."""
    idx = np.flatnonzero(active_np).astype(np.int32)
    full_of_compact = np.full((bucket,), -1, np.int32)
    full_of_compact[:idx.size] = idx
    compact_of_full = np.full((active_np.shape[0],), -1, np.int32)
    compact_of_full[idx] = np.arange(idx.size, dtype=np.int32)
    shard_active = None
    if mesh is not None and mesh.devices.size > 1:
        spec = (jax.sharding.PartitionSpec(mesh.axis_names[0])
                if bucket % mesh.devices.size == 0
                else jax.sharding.PartitionSpec())
        shard_active = jax.device_put(
            full_of_compact >= 0, jax.sharding.NamedSharding(mesh, spec))
    return FrontierInvariants(active=jnp.asarray(active_np),
                              compact_of_full=jnp.asarray(compact_of_full),
                              full_of_compact=jnp.asarray(full_of_compact),
                              shard_active=shard_active)


# Dispatch/fetch accounting of the async chunk drivers (this module's
# frontier_fixpoint and the grouped-stack pipeline).  Process-global like
# SWEEP_COUNTERS; the fetch-count budget test and tools/dispatch_report.py
# read these, and every entry also lands in the per-goal sensor families
# (GoalOptimizer.device-fetches / chunks-speculative / chunks-wasted).
FETCH_COUNTERS = {"device_fetches": 0, "chunks_dispatched": 0,
                  "chunks_speculative": 0, "chunks_wasted": 0,
                  # Cross-goal pipeline: opening chunks of goal N+1 launched
                  # while goal N's tail drained, and the subset whose
                  # on-device conflict/convergence gate zeroed them.
                  "chunks_cross_goal": 0, "chunks_cross_wasted": 0,
                  # Bytes of flight-recorder buffers that rode the boundary
                  # fetches (0 with CRUISE_FLIGHT_RECORDER off) — lets the
                  # dispatch audit attribute recorder traffic separately
                  # while proving the fetch COUNT is unchanged.
                  "flight_bytes": 0,
                  # Total bytes every boundary fetch moved over the search
                  # axis (packed stats + active mask + flight buffer) —
                  # the per-shard dispatch-economy denominator.
                  "fetch_bytes": 0}

_gate_cache: Dict[tuple, object] = {}


def _replicated_on(mesh):
    """A closure pinning a scalar result to the mesh's replicated layout —
    this is what turns the tiny gate programs into GSPMD dispatches: with a
    mesh-layout operand/constraint XLA partitions the (trivial) computation
    over the same device set as the chunk programs instead of compiling a
    single-chip executable whose output would have to be re-laid-out before
    feeding the next sharded chunk's budget argument."""
    sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return lambda x: jax.lax.with_sharding_constraint(x, sharding)


def _get_gate_fn(mesh=None):
    """Jitted ``(packed, budget) -> packed[PACKED_CAPPED] * budget`` — the
    on-device budget gate of speculative dispatch.  The follow-up chunk's
    step budget is the predecessor's capped flag times the host's optimistic
    chunk length, computed WITHOUT fetching the flag: if the predecessor
    converged the product is 0 and the follow-up is a no-op by construction.
    One tiny executable per mesh shape shared by every goal (packed layout
    is uniform); under a mesh the gate compiles as a GSPMD program whose
    replicated output feeds the sharded chunk directly (no host round-trip,
    no cross-program relayout)."""
    aot = _aot_prelower()
    key = ("budget", mesh, aot)
    fn = _gate_cache.get(key)
    if fn is None:
        if mesh is not None and mesh.devices.size > 1:
            rep = _replicated_on(mesh)
            fn = jax.jit(
                lambda packed, budget: rep(packed[PACKED_CAPPED] * budget))
        else:
            fn = jax.jit(
                lambda packed, budget: packed[PACKED_CAPPED] * budget)
        _gate_cache[key] = fn
    return fn


def _get_cross_gate_fn(mesh=None):
    """Jitted cross-GOAL budget gate: the next goal's speculative opening
    chunk may only run when the current goal's chunk proved the goal DONE
    (satisfied, not capped, no offline replicas left — the same exit test
    the host makes after its fetch) AND no broker the stack has touched
    since the frontier sweep lies inside the next goal's predicted seed
    frontier (``PACKED_CONFLICT`` == 0).  Any other outcome collapses the
    opener to a zero-step no-op, bit-identical to never dispatching it —
    this is the PR-5 speculation gate extended across the goal boundary.
    Like ``_get_gate_fn`` the mesh variant dispatches under GSPMD with a
    replicated output layout."""
    aot = _aot_prelower()
    key = ("cross", mesh, aot)
    fn = _gate_cache.get(key)
    if fn is None:
        def gate(packed, budget):
            out = jnp.where(
                (packed[PACKED_AFTER] == 1)
                & (packed[PACKED_CAPPED] == 0)
                & (packed[PACKED_ANY_OFFLINE] == 0)
                & (packed[PACKED_CONFLICT] == 0),
                budget, 0)
            if mesh is not None and mesh.devices.size > 1:
                out = _replicated_on(mesh)(out)
            return out
        fn = jax.jit(gate)
        _gate_cache[key] = fn
    return fn


def _flight_step_dicts(rows, start_step: int, chunk_index: int) -> List[dict]:
    """Decode executed i32[FLIGHT_WIDTH] recorder rows into timeline dicts.

    ``rows`` must already be sliced to the executed step count (the packed
    PACKED_STEPS slot); ``start_step`` is the goal-global index of the first
    row and ``chunk_index`` points at the chunk annotation it belongs to.
    The best-score slot is bitcast back to f32 (None when no candidate was
    eligible — the on-device max over an empty set is -inf)."""
    out = []
    rows = np.asarray(rows, np.int32)
    for i, r in enumerate(rows):
        score = float(np.int32(r[FLIGHT_SCORE_BITS]).view(np.float32))
        kind = int(r[FLIGHT_KIND])
        out.append({
            "step": start_step + i,
            "chunk": chunk_index,
            "actions": int(r[FLIGHT_ACTIONS]),
            "frontier": int(r[FLIGHT_FRONTIER]),
            "repair": int(r[FLIGHT_REPAIR]),
            "bisect_depth": int(r[FLIGHT_BISECT]),
            "lanes_live": int(r[FLIGHT_LANES]),
            "best_score": score if math.isfinite(score) else None,
            "kind": FLIGHT_KINDS[kind] if 0 <= kind < len(FLIGHT_KINDS)
            else None,
        })
    return out


def _goal_fixpoint_budget(model: TensorClusterModel,
                          options: OptimizationOptions,
                          step_budget, frontier=None, touched=None,
                          next_mask=None, *, spec=None,
                          prev_specs=(), constraint=None, num_sources=None,
                          num_dests=None, mesh=None, repair_oracle=False,
                          flight_capacity: int = 0):
    """One CHUNK of a goal's fixpoint: identical math to _goal_fixpoint, but
    the step cap is a TRACED scalar and the packed stats come back as one
    i32[PACKED_WIDTH] vector (see state.py for the slot layout) — so every
    chunk length reuses ONE compiled executable per (goal, frontier bucket
    shape) and the driver's per-chunk fetch is a single transfer.

    Returns ``(model, packed, active)``.  The chunk carries EVERY
    chunk-boundary decision input in its own outputs — exit-state
    satisfaction and offline flags, convergence/capped state, and (band
    kinds) the post-chunk frontier mask with its population — so the driver
    never dispatches a separate boundary probe: one fetch of
    ``(packed, active)`` answers "exit? rebucket? keep going?".  For
    non-band specs ``active`` is a constant all-False mask and
    ``num_active`` is -1.

    ``frontier`` is a traced FrontierInvariants (or None for dense): its
    compacted-axis SHAPE specializes the trace, its values don't — all
    chunks of one bucket share an executable.  A ``step_budget`` of zero
    skips the loop entirely (the while condition is false before the first
    step), which is what makes speculative dispatch free to discard: a
    follow-up chunk whose on-device budget gate collapsed to 0 returns the
    model bit-unchanged.

    ``flight_capacity`` (static) > 0 turns on the flight recorder for this
    trace: the carry grows an i32[flight_capacity, FLIGHT_WIDTH] buffer
    (see state.py), the body writes one row per executed step, and the
    return becomes ``(model, packed, active, flight)`` — the buffer rides
    the same boundary fetch as the packed stats.  Capacity 0 compiles the
    exact pre-recorder graph and keeps the 3-tuple return.

    ``touched`` (traced bool[B], inter-goal pipeline accounting) is the
    broker-touched accumulator since the last frontier sweep: the chunk
    ORs in every broker whose replica set it changed (entry-vs-exit
    placement diff — exact, no step-loop plumbing) and appends
    ``touched_out`` to the return tuple.  With ``next_mask`` (traced
    bool[B], the next goal's PREDICTED seed frontier) the packed
    ``PACKED_CONFLICT`` slot carries ``|touched_out ∩ next_mask|`` so the
    cross-goal speculation gate can discard a prelaunched opener entirely
    on device.  Both default to None, which compiles the exact
    pre-pipeline graph (conflict slot constant 0, no extra output)."""
    flight = flight_capacity > 0
    rb0, rl0, rd0 = (model.replica_broker, model.replica_is_leader,
                     model.replica_disk)
    arrays0 = BrokerArrays.from_model(model)
    before = kernels.goal_satisfied(spec, model, arrays0, constraint)
    any_offline = (model.replica_offline_now() & model.replica_valid).any()
    skip = before & ~any_offline
    inv = compute_step_invariants(spec, prev_specs, model, arrays0, constraint)

    def cond(state):
        steps, last_n = state[1], state[3]
        return (last_n > 0) & (steps < step_budget)

    def body(state):
        m, steps, total, _, rep, dep, lan = state[:7]
        out = _goal_step(m, options, spec, prev_specs, constraint,
                         num_sources, num_dests, mesh,
                         invariants=inv, frontier=frontier,
                         repair_oracle=repair_oracle, flight=flight)
        if flight:
            new_m, n, sel, extra = out
        else:
            new_m, n, sel = out
        n = n.astype(jnp.int32)
        new_state = (new_m, steps + 1, total + n, n,
                     rep + sel[0], jnp.maximum(dep, sel[2]), lan + sel[1])
        if flight:
            row = jnp.stack([n, extra[0], sel[0], sel[2], sel[1],
                             extra[1], extra[2]])  # FLIGHT_* slot order
            buf = state[7].at[
                jnp.minimum(steps, flight_capacity - 1)].set(row)
            new_state = new_state + (buf,)
        return new_state

    init = (model, jnp.int32(0), jnp.int32(0),
            jnp.where(skip, jnp.int32(0), jnp.int32(1)),
            jnp.int32(0), jnp.int32(0), jnp.int32(0))
    if flight:
        init = init + (jnp.zeros((flight_capacity, FLIGHT_WIDTH),
                                 jnp.int32),)
    final = jax.lax.while_loop(cond, body, init)
    (model, steps, total, last_n, rep, dep, lan) = final[:7]
    arrays1 = BrokerArrays.from_model(model)
    after = kernels.goal_satisfied(spec, model, arrays1, constraint)
    off_after = (model.replica_offline_now() & model.replica_valid).any()
    capped = (steps >= step_budget) & (last_n > 0)
    if spec is not None and kernels.is_band_kind(spec):
        active = kernels.frontier_active(spec, model, arrays1, constraint)
        num_active = active.sum().astype(jnp.int32)
    else:
        active = jnp.zeros((model.num_brokers,), dtype=bool)
        num_active = jnp.int32(-1)
    conflict = jnp.int32(0)
    touched_out = None
    if touched is not None:
        # Exact touched-broker accounting from the entry-vs-exit placement
        # diff: any replica whose broker/disk/leadership changed marks BOTH
        # its entry and exit brokers (two B-sized scatter-adds — noise next
        # to the step loop).  Validity is move-invariant, so the entry mask
        # covers both sides.
        B = model.num_brokers
        moved = model.replica_valid & (
            (model.replica_broker != rb0) | (model.replica_is_leader != rl0)
            | (model.replica_disk != rd0))
        m_i = moved.astype(jnp.int32)
        hits = (jnp.zeros((B,), jnp.int32)
                .at[jnp.clip(rb0, 0, B - 1)].add(m_i)
                .at[jnp.clip(model.replica_broker, 0, B - 1)].add(m_i))
        touched_out = touched | (hits > 0)
        if next_mask is not None:
            conflict = (touched_out & next_mask).sum().astype(jnp.int32)
    packed = jnp.stack([steps, total, before.astype(jnp.int32),
                        after.astype(jnp.int32), capped.astype(jnp.int32),
                        rep, dep, lan, num_active,
                        off_after.astype(jnp.int32), conflict])
    out = (model, packed, active)
    if touched is not None:
        out = out + (touched_out,)
    if flight:
        out = out + (final[7],)
    return out


_budget_cache: Dict[tuple, object] = {}


def _get_budget_fixpoint_fn(spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                            constraint: BalancingConstraint, num_sources: int,
                            num_dests: int, mesh=None, donate: bool = False,
                            flight_capacity: int = 0):
    oracle = _repair_oracle()
    ceiling = _cross_ceiling_k()
    aot = _aot_prelower()
    key = (spec, prev_specs, constraint, num_sources, num_dests, mesh, donate,
           oracle, flight_capacity, ceiling, aot)
    fn = _budget_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_goal_fixpoint_budget, spec=spec,
                             prev_specs=prev_specs, constraint=constraint,
                             num_sources=num_sources, num_dests=num_dests,
                             mesh=mesh, repair_oracle=oracle,
                             flight_capacity=flight_capacity),
                     donate_argnums=(0,) if donate else ())
        _budget_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# AOT executable prelowering + shipping (CRUISE_AOT_PRELOWER)
# ---------------------------------------------------------------------------
# The 375k-candidate ceiling is transport-side (PR 9 probe): a tunneled
# runtime re-serializes every FRESHLY BUILT executable over the control
# channel, and the xl bucket family's executables are big enough that the
# per-compile serialization dominates — not the compile itself.  The fix is
# to lower and compile each (goal, bucket, mesh) shape AHEAD of dispatch
# (``jax.jit(...).lower(args).compile()`` — ``lower`` records the exact
# input shardings without executing) and persist the serialized artifact
# through ``common/compile_cache.py`` once, so transport ships a cached
# artifact instead of re-serializing per build.  The registries below are
# process-global like the jit caches; ``conftest.py`` clears them between
# test modules.

AOT_COUNTERS = {"prelowered": 0, "shipped_bytes": 0,
                "aot_dispatches": 0, "aot_fallbacks": 0}

#: (kind,) + builder key + arg-shape signature -> jax.stages.Compiled
_aot_registry: Dict[tuple, object] = {}
#: same key -> {"collectives": int} parsed from the compiled HLO
_aot_hlo: Dict[tuple, dict] = {}

#: HLO op substrings counted as cross-device collectives (the per-shard
#: dispatch-economy column in tools/dispatch_report.py).
_COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")


def _collective_count(hlo_text: str) -> int:
    return sum(hlo_text.count(op) for op in _COLLECTIVE_OPS)


def _aot_signature(args) -> tuple:
    return tuple((tuple(leaf.shape), str(leaf.dtype))
                 for leaf in jax.tree_util.tree_leaves(args)
                 if hasattr(leaf, "shape"))


def aot_prelower_fn(fn, kind: str, key: tuple, args):
    """AOT-compile ``fn`` at ``args``'s exact shapes/shardings and ship the
    serialized executable through the persistent artifact store.  Returns
    the ``jax.stages.Compiled`` (registry-cached per arg signature, so one
    executable per (goal, bucket, mesh) shape).  ``lower`` accepts the
    concrete args without executing them and records their committed
    shardings — prelowering with the live model gives a Compiled whose
    input layout matches every later dispatch of the same shape."""
    sig = _aot_signature(args)
    akey = (kind,) + tuple(key) + (sig,)
    compiled = _aot_registry.get(akey)
    if compiled is not None:
        return compiled, akey
    compiled = fn.lower(*args).compile()
    _aot_registry[akey] = compiled
    AOT_COUNTERS["prelowered"] += 1
    try:
        hlo = compiled.as_text()
    except Exception:  # backend without HLO text — stats stay unknown
        hlo = ""
    _aot_hlo[akey] = {"collectives": _collective_count(hlo)}
    token = compile_cache.program_token("aot-" + kind, tuple(key), sig)
    AOT_COUNTERS["shipped_bytes"] += compile_cache.ship_executable(
        token, compiled)
    return compiled, akey


def _call_chunk(fn, kind: str, key: tuple, args):
    """Dispatch one chunk program: through its AOT-prelowered executable
    when ``CRUISE_AOT_PRELOWER`` is on (Compiled objects skip the jit
    call-cache machinery entirely — no re-serialization on a tunneled
    runtime), else the jit fn.  A Compiled errors (rather than resharding)
    on a committed-array layout mismatch, so any dispatch the prelowered
    executable cannot serve falls back to the jit fn — correctness never
    depends on the AOT path.  Returns ``(outputs, akey)``; ``akey`` (None
    on the jit path) indexes ``_aot_hlo`` for per-shard report columns."""
    if not _aot_prelower():
        return fn(*args), None
    try:
        compiled, akey = aot_prelower_fn(fn, kind, key, args)
        out = compiled(*args)
        AOT_COUNTERS["aot_dispatches"] += 1
        return out, akey
    except Exception:
        AOT_COUNTERS["aot_fallbacks"] += 1
        return fn(*args), None


def prelower_bucket_family(model, options, spec: GoalSpec,
                           prev_specs: Tuple[GoalSpec, ...],
                           constraint: BalancingConstraint, ns: int, nd: int,
                           buckets=(None,), mesh=None, donate: bool = False,
                           flight_capacity: int = 0,
                           pipelined: bool = False):
    """AOT-lower and ship ``spec``'s chunk-program family AHEAD of a solve:
    one executable per frontier bucket shape (``None`` = dense) at the
    given candidate widths and mesh.  The registry keys match what the
    chunk driver's dispatches produce, so a later ``frontier_fixpoint`` run
    over the same shapes dispatches straight into the prelowered
    executables — no build, no per-compile transport serialization mid
    solve.  Frontier values don't matter to the trace (only shapes do), so
    an all-inactive mask stands in for every future frontier of the same
    bucket.  No-op (empty list) unless ``CRUISE_AOT_PRELOWER=1``; returns
    one record per bucket: {bucket, ns, nd, collectives}."""
    if not _aot_prelower():
        return []
    B = model.num_brokers
    lanes = int(mesh.devices.size) if mesh is not None else 1
    bud = jnp.int32(0)
    out = []
    for bucket in buckets:
        cns, cnd = (ns, nd) if bucket is None else _frontier_widths(
            bucket, ns, nd, lanes)
        fn = _get_budget_fixpoint_fn(spec, prev_specs, constraint, cns, cnd,
                                     mesh=mesh, donate=donate,
                                     flight_capacity=flight_capacity)
        fr = (None if bucket is None
              else _build_frontier(np.zeros(B, bool), bucket, mesh))
        args = (model, options, bud, fr)
        if pipelined:
            args = args + (jnp.zeros((B,), bool), jnp.zeros((B,), bool))
        key = (spec, prev_specs, constraint, cns, cnd, mesh, donate,
               flight_capacity)
        _, akey = aot_prelower_fn(fn, "budget", key, args)
        out.append({"bucket": bucket, "ns": cns, "nd": cnd,
                    "collectives": _aot_hlo.get(akey, {}).get("collectives")})
    return out


def frontier_fixpoint(model: TensorClusterModel, options: OptimizationOptions,
                      spec: GoalSpec, prev_specs: Tuple[GoalSpec, ...],
                      constraint: BalancingConstraint,
                      num_sources: Optional[int] = None,
                      num_dests: Optional[int] = None,
                      max_steps: int = 256, chunk_steps: int = 32,
                      mesh=None, donate: bool = False, frontier: bool = True,
                      tail_threshold: float = 0.1, min_chunk: int = 4,
                      on_chunk=None, speculate: Optional[bool] = None,
                      seed_active=None,
                      next_goal: Optional[PipelineNextGoal] = None,
                      prelaunch: Optional[dict] = None):
    """Async chunked driver for one goal's fixpoint.  Returns
    ``(model, info)`` where info = {chunks, buckets, fresh_compile, steps,
    actions, satisfied_before, satisfied_after, capped, repair_steps,
    bisect_depth, lanes_live, fetches, fetch_wait_s, chunks_speculative,
    chunks_wasted}.

    The chunk boundary is round-trip-free by construction:

    1. **Piggyback, don't probe.**  Every chunk program returns the
       boundary-decision inputs in its own outputs — the packed
       i32[PACKED_WIDTH] stats (satisfied/offline/capped/frontier
       population) plus the post-chunk active mask — so the driver issues
       at most ONE ``jax.device_get`` per boundary and never dispatches a
       separate mask probe.  The first chunk runs dense (no mask exists
       yet) and short, so quiet goals exit in a single small dispatch.
    2. **Double-buffered speculative dispatch** (``speculate``, default on
       when no ``on_chunk`` callback needs the intermediate models):
       immediately after dispatching chunk *k* the driver launches chunk
       *k+1* with the SAME bucket/shape and an on-device step budget of
       ``packed_k[PACKED_CAPPED] * len`` — then fetches chunk *k*'s stats
       while both run.  If chunk *k* converged the gate collapsed the
       follow-up to zero steps (a bit-exact no-op, counted in
       ``chunks_wasted``); if it capped, the follow-up was exactly the
       chunk a synchronous driver would have dispatched, minus the idle
       boundary.  Bucket changes and convergence decisions still block —
       a speculative chunk runs on the predecessor's (stale) frontier,
       which is sound because the mask is a performance hint, not a
       correctness gate.
    3. **Adaptive chunk growth**: when ``chunk_steps < max_steps`` chunks
       start at ``min_chunk`` and double toward ``chunk_steps`` while the
       accepted-actions-per-step rate stays above ``tail_threshold`` × the
       peak rate, then halve in the tail — fast convergence detection
       early, amortized boundaries while hot, short chunks in the tail.

    The population fetched with the mask picks a power-of-two bucket (or
    dense when the frontier covers most of the cluster / offline replicas
    need the full healing path); candidate widths shrink with the bucket.
    A compacted chunk that reaches its fixpoint is CONFIRMED by a dense
    chunk before the goal is declared converged; a dense chunk converging
    is authoritative.  A goal satisfied with no offline replicas at a
    boundary exits immediately.

    ``on_chunk(model, chunk_record)`` runs after every fetched chunk — the
    sharded driver uses it for checkpointing.  It disables speculation:
    under donation a speculative dispatch consumes the predecessor model's
    buffers before the callback could read them.

    With ``CRUISE_FLIGHT_RECORDER=1`` every chunk additionally returns an
    i32[capacity, FLIGHT_WIDTH] per-step buffer that joins the SAME
    boundary ``device_get`` (the fetch stays ≤1 per boundary; bytes are
    attributed in ``FETCH_COUNTERS["flight_bytes"]``).  The driver
    stitches the chunk buffers into ``info["flight"]`` — a per-goal step
    timeline whose entries point at their chunk record (wall, bucket,
    length, fresh_compile).  Discarded speculative chunks recorded into
    their own buffer, which is simply never fetched.

    ``seed_active`` (bool[B] host numpy, warm-start seeding) pre-builds the
    FIRST dispatch's frontier from the given mask instead of starting
    dense: when the mask buckets under the frontier policy, the opening
    chunk already runs compacted over the seed brokers.  Sound for the same
    reason as any compacted chunk — a compacted convergence is confirmed by
    a dense chunk before the goal is declared done, so a mask that misses a
    needed broker costs one confirm chunk, never correctness.  ``None``
    leaves the driver's behavior bit-identical to the unseeded path.

    **Inter-goal pipelining** (``next_goal`` / ``prelaunch``): with a
    ``PipelineNextGoal`` descriptor the driver speculatively dispatches the
    NEXT goal's first chunk off every authoritative chunk of its own goal,
    budget-gated ON DEVICE by this chunk's packed stats — the opener only
    runs when the current goal is provably DONE (satisfied, uncapped,
    nothing offline) and no move since the frontier sweep landed inside the
    next goal's predicted seed frontier (``PACKED_CONFLICT``); otherwise it
    traces as a bit-exact zero-step passthrough and is discarded.  An
    adopted opener is returned in ``info["handoff"]`` so the next driver
    invocation can resume from it via ``prelaunch`` without a fresh
    dispatch.  Pipelined drivers thread a ``touched`` broker mask through
    EVERY dispatch (the 6-arg trace) so all chunks of one (goal, bucket,
    flight_cap) still share ONE executable; non-pipelined callers keep the
    4-arg form and their pre-pipeline graphs byte-identical.
    """
    ns = num_sources or cgen.default_num_sources(model)
    nd = num_dests or cgen.default_num_dests(model)
    B = model.num_brokers
    # Static per driver call: every chunk length ≤ capacity, so all chunks
    # of one bucket shape still share ONE executable with the recorder on.
    flight_cap = min(chunk_steps, max_steps) if _flight_recorder() else 0
    flight_steps: List[dict] = []
    flight_chunks: List[dict] = []
    use_frontier = bool(frontier) and kernels.is_band_kind(spec)
    if speculate is None:
        speculate = True
    speculate = bool(speculate) and on_chunk is None
    chunks: List[dict] = []
    buckets: set = set()
    fresh = False
    steps_done = 0
    actions_total = 0
    repair_total = 0
    bisect_depth = 0
    lanes_total = 0
    fetches = 0
    fetch_wait = 0.0
    speculated = 0
    wasted = 0
    before0: Optional[bool] = None
    after = False
    capped = False
    grow = chunk_steps < max_steps
    chunk = max(1, min(min_chunk if grow else chunk_steps,
                       chunk_steps, max_steps))
    peak_aps = 0.0
    force_dense = not use_frontier
    bucket: Optional[int] = None  # config of the next host-decided dispatch
    fr: Optional[FrontierInvariants] = None
    seeded = 0
    if use_frontier and seed_active is not None:
        seed_np = np.asarray(seed_active, dtype=bool)
        nb = _frontier_bucket(int(seed_np.sum()), B)
        if nb is not None:
            bucket = nb
            fr = _build_frontier(seed_np, nb, mesh)
            seeded = int(seed_np.sum())
    # Inter-goal pipelining state.  ``pipelined`` switches every dispatch
    # to the 6-arg trace (touched mask + next-goal seed mask ride through
    # the program) so the conflict slot is live; the opener config mirrors
    # the first-chunk policy the next driver invocation would use itself.
    pipelined = next_goal is not None or prelaunch is not None
    touched_d = None
    next_mask_d = None
    opener_bucket: Optional[int] = None
    opener_fr: Optional[FrontierInvariants] = None
    opener_blen = 0
    opener_fcap = 0
    opener_seeded = 0
    cross_dispatched = 0
    cross_wasted = 0
    handoff: Optional[dict] = None
    if pipelined:
        touched_d = (prelaunch["touched"] if prelaunch is not None
                     else jnp.zeros((B,), bool))
        next_mask_d = jnp.zeros((B,), bool)
        if next_goal is not None:
            opener_fcap = (min(next_goal.chunk_len, next_goal.max_steps)
                           if _flight_recorder() else 0)
            grow_n = next_goal.chunk_len < next_goal.max_steps
            opener_blen = max(1, min(
                next_goal.min_chunk if grow_n else next_goal.chunk_len,
                next_goal.chunk_len, next_goal.max_steps))
            if bool(frontier) and kernels.is_band_kind(next_goal.spec) \
                    and next_goal.seed_active is not None:
                nseed = np.asarray(next_goal.seed_active, dtype=bool)
                nb = _frontier_bucket(int(nseed.sum()), B)
                if nb is not None:
                    opener_bucket = nb
                    opener_fr = _build_frontier(nseed, nb, mesh)
                    opener_seeded = int(nseed.sum())
                    # Conflict accounting only protects COMPACTED openers;
                    # a dense opener sees every broker and is always valid,
                    # so it keeps the all-zeros mask (never discarded for
                    # frontier staleness).
                    next_mask_d = jnp.asarray(nseed)
    pending: Optional[dict] = None  # the one in-flight speculative chunk
    t_first_dispatch: Optional[float] = None
    if prelaunch is not None:
        # Adopt the opener the PREVIOUS driver dispatched for this goal:
        # it becomes the first in-flight chunk and the existing pop/fetch
        # machinery treats it exactly like a chunk this driver launched.
        pending = prelaunch
        t_first_dispatch = prelaunch.get("t_dispatch")
        seeded = prelaunch.get("seeded", 0) or seeded
    t_prev = time.monotonic()

    def _dispatch(bucket, fr, budget, blen, speculative, confirm=False,
                  spec_d=None, prev_d=None, fcap=None, cross=False):
        """Launch one chunk (async) and return its in-flight record.
        ``budget`` is a host int for decided chunks or a device scalar for
        gated speculative ones; both trace as strong i32 so every chunk of
        one bucket shape shares ONE executable.  ``spec_d``/``prev_d``/
        ``fcap`` override the goal context for CROSS-GOAL openers (the next
        goal's first chunk launched while this goal's tail drains);
        defaults dispatch the driver's own goal."""
        nonlocal model, fresh, speculated, touched_d, t_first_dispatch
        sp = spec_d if spec_d is not None else spec
        pv = prev_d if prev_d is not None else prev_specs
        fc = flight_cap if fcap is None else fcap
        # Under a mesh the compacted candidate batch shards over the search
        # axis like the dense batch does — widths round up to lane
        # multiples so every device gets an equal slice of the bucket.
        lanes = int(mesh.devices.size) if mesh is not None else 1
        cns, cnd = (ns, nd) if bucket is None else _frontier_widths(
            bucket, ns, nd, lanes)
        fn = _get_budget_fixpoint_fn(sp, pv, constraint, cns, cnd,
                                     mesh=mesh, donate=donate,
                                     flight_capacity=fc)
        size0 = fn._cache_size() if hasattr(fn, "_cache_size") else None
        aot0 = len(_aot_registry)
        bud = jnp.int32(budget) if isinstance(budget, int) else budget
        fn_key = (sp, pv, constraint, cns, cnd, mesh, donate, fc)
        if pipelined:
            # 6-arg trace: the opener's conflict slot is meaningless for
            # the NEXT driver's own next goal, so cross dispatches carry an
            # all-zeros mask (their conflict slot is never consulted).
            mask = next_mask_d if spec_d is None else jnp.zeros((B,), bool)
            outs, akey = _call_chunk(
                fn, "budget", fn_key, (model, options, bud, fr, touched_d,
                                       mask))
            if fc:
                model, packed_d, active_d, touched_d, flight_d = outs
            else:
                model, packed_d, active_d, touched_d = outs
                flight_d = None
        else:
            outs, akey = _call_chunk(fn, "budget", fn_key,
                                     (model, options, bud, fr))
            if fc:
                model, packed_d, active_d, flight_d = outs
            else:
                model, packed_d, active_d = outs
                flight_d = None
        # A chunk that built (or deserialized) its executable this process
        # carries that one-off wall in wall_s — flag it so the wall-slope
        # flatness metric can exclude it (tools/tail_report.py).  An AOT
        # prelower this dispatch counts the same way (the build just moved
        # ahead of the call).
        chunk_fresh = ((size0 is not None and fn._cache_size() > size0)
                       or len(_aot_registry) > aot0)
        if chunk_fresh:
            # New trace for this (goal, bucket shape) — refine "fresh" the
            # same way the stack path does: a persistent-cache marker means
            # some process already built this executable (warm disk cache).
            token = _persist_token(
                "budget", (sp, pv, constraint, cns, cnd, mesh,
                           donate, bucket)
                + ((fc,) if fc else ()), model, options)
            if not (token and compile_cache.seen(token)):
                fresh = True
            if token:
                compile_cache.mark(token)
        FETCH_COUNTERS["chunks_dispatched"] += 1
        if cross:
            FETCH_COUNTERS["chunks_cross_goal"] += 1
        if speculative:
            FETCH_COUNTERS["chunks_speculative"] += 1
            speculated += 1
        now = time.monotonic()
        if t_first_dispatch is None:
            t_first_dispatch = now
        return {"packed": packed_d, "active": active_d, "flight": flight_d,
                "bucket": bucket, "fr": fr, "ns": cns, "nd": cnd,
                "blen": blen, "fresh": chunk_fresh,
                "speculative": speculative, "confirm": confirm,
                "cross": cross, "t_dispatch": now,
                "collectives": (_aot_hlo.get(akey, {}).get("collectives")
                                if akey is not None else None)}

    while steps_done < max_steps:
        if pending is not None:
            cur, pending = pending, None
        else:
            blen = min(chunk, max_steps - steps_done)
            cur = _dispatch(bucket, fr, blen, blen, False,
                            confirm=force_dense and use_frontier)
        if speculate and not cur["confirm"] and (cur["bucket"] is not None
                                                 or not use_frontier):
            # Double buffer: gate the follow-up's budget on-device by cur's
            # capped flag and launch it before the blocking fetch below, so
            # the device never idles across the boundary.  The length is
            # the optimistic (non-tail) growth-policy guess; cur's budget
            # is charged in full — exact when cur caps (a capped chunk
            # uses every step), and irrelevant when it converges (the gate
            # zeroes the follow-up).  Confirm chunks are excluded (they
            # exist to validate convergence and almost always no-op), as
            # are dense chunks under the frontier policy — their follow-up
            # usually switches to a compacted bucket, a different
            # executable the host must pick after the fetch.
            nxt = min(chunk * 2, chunk_steps) if grow else chunk
            nxt = min(nxt, max_steps - steps_done - cur["blen"])
            if nxt > 0:
                gated = _get_gate_fn(mesh)(cur["packed"], jnp.int32(nxt))
                pending = _dispatch(cur["bucket"], cur["fr"], gated, nxt,
                                    True)
        cross_rec: Optional[dict] = None
        if (next_goal is not None and speculate and cur["fr"] is None
                and not cur["speculative"] and not cur.get("cross")):
            # Speculatively open the NEXT goal's first chunk while this
            # goal's authoritative chunk drains.  The on-device gate
            # releases the opener's budget only when this chunk proves the
            # goal DONE (satisfied, uncapped, nothing offline) AND no move
            # since the frontier sweep landed inside the next goal's
            # predicted seed frontier; otherwise the opener is a bit-exact
            # zero-step passthrough, discarded at the fetch below.  Openers
            # hang ONLY off authoritative chunks (``fr is None``) — a
            # compacted convergence still needs its dense confirm — and
            # never off an adopted prelaunch, whose conflict slot was
            # computed against the PREVIOUS driver's mask.
            gated = _get_cross_gate_fn(mesh)(cur["packed"],
                                             jnp.int32(opener_blen))
            cross_rec = _dispatch(opener_bucket, opener_fr, gated,
                                  opener_blen, False,
                                  spec_d=next_goal.spec,
                                  prev_d=next_goal.prev_specs,
                                  fcap=opener_fcap, cross=True)
            cross_dispatched += 1
        t_f = time.monotonic()
        # ONE blocking transfer per boundary, recorder or not: the flight
        # buffer (when present) joins the same device_get tuple.
        targets = [cur["packed"]]
        if use_frontier:
            targets.append(cur["active"])
        if cur["flight"] is not None:
            targets.append(cur["flight"])
        fetched = list(jax.device_get(tuple(targets)))
        # Bytes moved over the boundary (per-shard dispatch economy): the
        # exact host-side size of everything this single fetch transferred.
        fetch_bytes = sum(int(np.asarray(x).nbytes) for x in fetched)
        FETCH_COUNTERS["fetch_bytes"] += fetch_bytes
        packed_np = fetched.pop(0)
        active_np = fetched.pop(0) if use_frontier else None
        flight_np = fetched.pop(0) if cur["flight"] is not None else None
        if flight_np is not None:
            FETCH_COUNTERS["flight_bytes"] += int(
                np.asarray(flight_np).nbytes)
        FETCH_COUNTERS["device_fetches"] += 1
        fetches += 1
        now = time.monotonic()
        wait = now - t_f
        fetch_wait += wait
        # Boundary-to-boundary walls: fetches complete in dispatch order,
        # so the delta between consecutive fetch completions is the real
        # incremental wall of this chunk even when the next chunk was
        # already running (per-dispatch stopwatches would double-count the
        # overlap).
        wall = now - t_prev
        t_prev = now
        (s, a, b4, aft, cap, rep, dep, lan, na, off, conf) = (
            int(x) for x in np.asarray(packed_np))
        if before0 is None:
            before0 = bool(b4)
        after = bool(aft)
        capped = bool(cap)
        steps_done += s
        actions_total += a
        repair_total += rep
        bisect_depth = max(bisect_depth, dep)
        lanes_total += lan
        if cur["bucket"] is not None:
            buckets.add(cur["bucket"])
        rec = {"steps": s, "actions": a, "wall_s": wall,
               "fetch_wait_s": wait, "bucket": cur["bucket"],
               "ns": cur["ns"], "nd": cur["nd"], "repair_steps": rep,
               "bisect_depth": dep, "lanes_live": lan,
               "fresh_compile": cur["fresh"],
               "speculative": cur["speculative"],
               "fetch_bytes": fetch_bytes,
               "collectives": cur.get("collectives")}
        chunks.append(rec)
        if flight_np is not None:
            ci = len(flight_chunks)
            flight_steps.extend(_flight_step_dicts(
                np.asarray(flight_np)[:s], len(flight_steps), ci))
            flight_chunks.append({"wall_s": wall, "bucket": cur["bucket"],
                                  "len": s, "fresh_compile": cur["fresh"],
                                  "speculative": cur["speculative"]})
        if on_chunk is not None:
            on_chunk(model, rec)
        # Adaptive chunk length: grow while hot, halve in the tail.
        aps = a / max(s, 1)
        peak_aps = max(peak_aps, aps)
        tail = peak_aps > 0 and aps < tail_threshold * peak_aps
        if tail:
            chunk = max(min_chunk, chunk // 2)
        elif grow:
            chunk = min(chunk * 2, chunk_steps)
        if not capped and cur["fr"] is not None:
            # Compacted convergence — even a satisfied one — is confirmed
            # with one dense chunk before the goal is declared done (the
            # frontier may have hidden a legal move between two "inactive"
            # brokers; in practice the mask is a superset of the kernels'
            # source/sink sets, so the confirm is a no-op chunk).  Any
            # in-flight follow-up's budget gate collapsed to zero steps.
            if pending is not None:
                wasted += 1
                FETCH_COUNTERS["chunks_wasted"] += 1
                pending = None
            force_dense = True
            bucket, fr = None, None
            continue
        if after and not off:
            # Satisfied with nothing offline left: exit now.  An in-flight
            # follow-up is a no-op either way — its own skip shortcut sees
            # the satisfied state — so adopt its (bit-identical) model.
            if pending is not None:
                wasted += 1
                FETCH_COUNTERS["chunks_wasted"] += 1
                pending = None
            if cross_rec is not None:
                # Host decision mirrors the on-device gate exactly (same
                # packed values, same predicate): adopt the opener as the
                # next goal's first in-flight chunk, or discard the
                # passthrough.
                if conf == 0 and not cap:
                    handoff = dict(cross_rec, touched=touched_d,
                                   seeded=opener_seeded)
                else:
                    cross_wasted += 1
                    FETCH_COUNTERS["chunks_cross_wasted"] += 1
            capped = False
            break
        if not capped:
            if pending is not None:
                # The follow-up's budget gate collapsed to zero steps.
                wasted += 1
                FETCH_COUNTERS["chunks_wasted"] += 1
                pending = None
            if cross_rec is not None:
                # Converged but unsatisfied (or offline left) — the gate
                # required ``after``, so the opener was a passthrough.
                cross_wasted += 1
                FETCH_COUNTERS["chunks_cross_wasted"] += 1
            break  # dense convergence is authoritative
        if cross_rec is not None:
            # Capped — the gate required ``capped == 0``, so the opener
            # was a passthrough.
            cross_wasted += 1
            FETCH_COUNTERS["chunks_cross_wasted"] += 1
        # Capped: pick the next host-decided config from the mask that
        # rode along with the chunk.  With a follow-up already in flight
        # this takes effect one chunk late — the speculative chunk runs on
        # the predecessor's frontier by design.
        if use_frontier:
            force_dense = False
            bucket, fr = None, None
            if not off:
                nb = _frontier_bucket(na, B)
                if nb is not None:
                    fr = _build_frontier(np.asarray(active_np), nb, mesh)
                    bucket = nb
    info = {"chunks": chunks, "buckets": sorted(buckets),
            "fresh_compile": fresh, "steps": steps_done,
            "actions": actions_total,
            "satisfied_before": bool(before0) if before0 is not None else after,
            "satisfied_after": after, "capped": capped,
            "repair_steps": repair_total, "bisect_depth": bisect_depth,
            "lanes_live": lanes_total, "fetches": fetches,
            "fetch_wait_s": fetch_wait, "chunks_speculative": speculated,
            "chunks_wasted": wasted}
    if seeded:
        info["seed_frontier"] = seeded
    if pipelined:
        info["cross_dispatched"] = cross_dispatched
        info["cross_wasted"] = cross_wasted
        info["handoff"] = handoff
        info["t_first_dispatch"] = t_first_dispatch
        info["adopted_prelaunch"] = prelaunch is not None
    if flight_cap:
        info["flight"] = {"kinds": list(FLIGHT_KINDS),
                          "steps": flight_steps, "chunks": flight_chunks}
    return model, info


# Fused "already satisfied?" sweep: ONE jitted dispatch answers the question
# for the whole goal stack, so satisfied goals cost a vector read instead of
# a fixpoint-program entry each (8-17 s of dispatch per goal at the 1M rung).
SWEEP_COUNTERS = {"dispatches": 0, "skipped_goals": 0}


def _stack_satisfied(model: TensorClusterModel, *, specs=(), constraint=None):
    arrays = BrokerArrays.from_model(model)
    sat = jnp.stack([kernels.goal_satisfied(s, model, arrays, constraint)
                     for s in specs])
    any_offline = (model.replica_offline_now() & model.replica_valid).any()
    return sat, any_offline


def _stack_frontiers(model: TensorClusterModel, *, specs=(), constraint=None):
    """One fused sweep answering BOTH stack questions for pipelining:
    per-goal satisfaction (as ``_stack_satisfied``) plus every goal's
    predicted frontier — bool[G, B], all-False rows for non-band goals —
    in a single dispatch.  The frontiers seed next-goal openers and decide
    disjoint-frontier fusion; they are predictions (performance hints),
    never correctness gates, so staleness costs a discarded opener or a
    confirm chunk, not a wrong answer."""
    arrays = BrokerArrays.from_model(model)
    sat = jnp.stack([kernels.goal_satisfied(s, model, arrays, constraint)
                     for s in specs])
    any_offline = (model.replica_offline_now() & model.replica_valid).any()
    fronts = kernels.frontier_active_batch(specs, model, arrays, constraint)
    return sat, any_offline, fronts


_sweep_cache: Dict[tuple, object] = {}


def _get_sweep_fn(specs: Tuple[GoalSpec, ...],
                  constraint: BalancingConstraint):
    aot = _aot_prelower()
    key = (specs, constraint, aot)
    fn = _sweep_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_stack_satisfied, specs=specs,
                             constraint=constraint))
        _sweep_cache[key] = fn
    return fn


def _get_frontier_sweep_fn(specs: Tuple[GoalSpec, ...],
                           constraint: BalancingConstraint):
    aot = _aot_prelower()
    key = (specs, constraint, "fronts", aot)
    fn = _sweep_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_stack_frontiers, specs=specs,
                             constraint=constraint))
        _sweep_cache[key] = fn
    return fn


# Execution-time balancedness re-scoring: as movement batches land, the
# executor's ledger wants "how far from the optimized placement are we?" in
# the same units the optimizer reports (balancedness_before/after).  One
# compile-cached program evaluates the full goal-stack sweep over a BATCH of
# landed-partition masks — lax.map keeps the program a single sweep body, so
# a batch of checkpoints costs one dispatch, never one per poll.
_placement_score_cache: Dict[tuple, object] = {}


def _get_placement_score_fn(specs: Tuple[GoalSpec, ...],
                            constraint: BalancingConstraint, batch: int):
    aot = _aot_prelower()
    key = (specs, constraint, batch, aot)
    fn = _placement_score_cache.get(key)
    if fn is None:
        def run(before, after, masks):
            def one(mask):
                rmask = mask[before.replica_partition]
                blended = before.with_placement(
                    jnp.where(rmask, after.replica_broker,
                              before.replica_broker),
                    jnp.where(rmask, after.replica_is_leader,
                              before.replica_is_leader),
                    jnp.where(rmask, after.replica_disk,
                              before.replica_disk))
                sat, _ = _stack_satisfied(blended, specs=specs,
                                          constraint=constraint)
                return sat
            return jax.lax.map(one, masks)
        fn = jax.jit(run)
        _placement_score_cache[key] = fn
    return fn


class PlacementScorer:
    """Balancedness of execution checkpoints, batched and compile-cached.

    A checkpoint is a set of *landed* partitions (all tasks completed); the
    hypothetical cluster at that instant places landed partitions at the
    optimized (after) placement and the rest at the pre-execution (before)
    placement.  ``score`` runs the goal-stack satisfaction sweep over the
    whole batch of checkpoints in one jitted dispatch (batch padded to a
    power of two so the executable is reused across flushes) and converts
    violations to the optimizer's balancedness scale: 100 minus each
    violated goal's priority/strictness cost.
    """

    def __init__(self, model_before: TensorClusterModel,
                 model_after: TensorClusterModel,
                 goal_names: Sequence[str],
                 constraint: Optional[BalancingConstraint] = None,
                 priority_weight: float = 1.1,
                 strictness_weight: float = 1.5):
        from cruise_control_tpu.analyzer.balancedness import \
            balancedness_cost_by_goal
        # goals_by_priority returns a list; tuple() so cache keys hash.
        self._specs = tuple(goals_by_priority(list(goal_names)))
        self._constraint = constraint or BalancingConstraint.default()
        self._before = model_before
        self._after = model_after
        costs = balancedness_cost_by_goal(self._specs, priority_weight,
                                          strictness_weight)
        self._costs = np.array([costs[s.name] for s in self._specs],
                               np.float64)
        self.dispatches = 0

    @classmethod
    def for_run(cls, model_before: TensorClusterModel, run: "OptimizerRun",
                constraint: Optional[BalancingConstraint] = None,
                priority_weight: float = 1.1,
                strictness_weight: float = 1.5) -> "PlacementScorer":
        """Scorer from an optimization result: before = the model the run
        started from, after = the optimized placement, goals = the run's
        stack — the facade builds this for non-dryrun executions."""
        return cls(model_before, run.model,
                   [g.name for g in run.goal_results], constraint,
                   priority_weight, strictness_weight)

    @property
    def num_partitions(self) -> int:
        return int(self._before.partition_valid.shape[0])

    def score_landed(self, landed_sets: Sequence) -> np.ndarray:
        """Scores for a batch of landed-partition id sets (the ledger's
        checkpoint representation)."""
        masks = np.zeros((len(landed_sets), self.num_partitions), bool)
        for i, landed in enumerate(landed_sets):
            if landed:
                masks[i, np.fromiter(landed, int, len(landed))] = True
        return self.score(masks)

    def score(self, masks: np.ndarray) -> np.ndarray:
        """f64[C] balancedness for bool[C, P] landed masks (one dispatch)."""
        masks = np.asarray(masks, bool)
        c = masks.shape[0]
        if c == 0:
            return np.zeros((0,), np.float64)
        c_pad = 1 << (c - 1).bit_length()
        padded = np.zeros((c_pad, masks.shape[1]), bool)
        padded[:c] = masks
        fn = _get_placement_score_fn(self._specs, self._constraint, c_pad)
        sat = np.asarray(jax.device_get(
            fn(self._before, self._after, jnp.asarray(padded))))
        self.dispatches += 1
        violated = ~sat[:c]
        return 100.0 - violated.astype(np.float64) @ self._costs


def _stack_fixpoint(model: TensorClusterModel, options: OptimizationOptions,
                    specs: Tuple[GoalSpec, ...], constraint: BalancingConstraint,
                    num_sources: int, num_dests: int, max_steps: int, mesh=None,
                    prev_specs: Tuple[GoalSpec, ...] = (),
                    repair_oracle: bool = False, flight_capacity: int = 0):
    """A run of goals in one XLA program: each goal's while_loop runs
    in priority order, prev-goal acceptance masks accumulating exactly as in
    the unfused path.  One dispatch + one host transfer for the whole run —
    the per-goal dispatch/sync overhead matters on a tunneled TPU (15 goals
    × dispatch + 6 scalar fetches each).  ``prev_specs`` seeds the
    already-optimized set, so a long stack can be split into a few chunked
    programs (the 200-broker single-program compile kernel-faults the TPU
    worker; see optimize(fuse_group_size=...)).

    Each goal runs through _goal_fixpoint_budget so the packed result is
    one i32[PACKED_WIDTH, G] matrix (slot layout in state.py) — and the
    grouped path reports the bounded-repair counters just like the per-goal
    frontier driver does.

    ``flight_capacity`` > 0 (static) also stacks each goal's flight
    buffer into one i32[G, capacity, FLIGHT_WIDTH] block returned as a
    third output — per-goal step timelines for the whole run in the same
    single host fetch."""
    packed_l = []
    flight_l = []
    prev: Tuple[GoalSpec, ...] = tuple(prev_specs)
    for spec in specs:
        out = _goal_fixpoint_budget(
            model, options, jnp.int32(max_steps), None, spec=spec,
            prev_specs=prev, constraint=constraint,
            num_sources=num_sources, num_dests=num_dests, mesh=mesh,
            repair_oracle=repair_oracle, flight_capacity=flight_capacity)
        if flight_capacity:
            model, packed, _, buf = out
            flight_l.append(buf)
        else:
            model, packed, _ = out
        packed_l.append(packed)
        prev = prev + (spec,)
    # One i32[PACKED_WIDTH, G] result matrix: a single host fetch covers the
    # whole run (each device_get round trip costs ~0.5-1 s over a tunneled
    # TPU; separate vectors were separate round trips).
    if flight_capacity:
        return (model, jnp.stack(packed_l, axis=1),
                jnp.stack(flight_l, axis=0))
    return model, jnp.stack(packed_l, axis=1)


def _push_repair_sensors(goal_name: str, repair_steps: int,
                         bisect_depth: int, lanes_live: int) -> None:
    """Bounded-repair counters into the sensor registry — both fused paths
    (per-goal frontier driver and grouped stack programs) report through
    here so /metrics carries the repair families regardless of grouping."""
    labels = {"goal": goal_name}
    SENSORS.counter(
        "GoalOptimizer.repair-steps", labels=labels,
        help="Steps whose bounded selection repair saw a violation",
    ).inc(repair_steps)
    SENSORS.counter(
        "GoalOptimizer.repair-lanes-live", labels=labels,
        help="Live candidate lanes at compaction, summed over steps",
    ).inc(lanes_live)
    SENSORS.gauge(
        "GoalOptimizer.repair-bisect-depth", labels=labels,
        help="Compiled repair bisection depth (log2 of lane count)",
    ).set(bisect_depth)


def _push_dispatch_sensors(goal_name: str, fetches: int,
                           chunks_speculative: int, chunks_wasted: int,
                           fetch_bytes: int = 0,
                           collectives: int = 0) -> None:
    """Async-orchestration counters into the sensor registry: how often the
    chunk driver blocked on the device, how much speculative dispatch
    bought (launched) and burned (gated to zero), and the per-shard
    dispatch economy — bytes each boundary fetch moved over the search
    axis and cross-device collectives in the dispatched programs' HLO
    (0 on a single chip or when no AOT-lowered text is available)."""
    labels = {"goal": goal_name}
    SENSORS.counter(
        "GoalOptimizer.device-fetches", labels=labels,
        help="Blocking host fetches at chunk boundaries",
    ).inc(fetches)
    SENSORS.counter(
        "GoalOptimizer.chunks-speculative", labels=labels,
        help="Chunks dispatched before the predecessor's stats were fetched",
    ).inc(chunks_speculative)
    SENSORS.counter(
        "GoalOptimizer.chunks-wasted", labels=labels,
        help="Speculative chunks whose on-device budget gate zeroed them",
    ).inc(chunks_wasted)
    SENSORS.counter(
        "GoalOptimizer.boundary-fetch-bytes", labels=labels,
        help="Bytes moved hostward by chunk-boundary fetches",
    ).inc(fetch_bytes)
    SENSORS.counter(
        "GoalOptimizer.mesh-collective-ops", labels=labels,
        help="Cross-device collectives in dispatched chunk HLO (AOT runs)",
    ).inc(collectives)


def _push_aot_sensors() -> None:
    """AOT prelower/shipping accounting (CRUISE_AOT_PRELOWER=1 runs):
    process totals from ``AOT_COUNTERS`` — how many (goal, bucket, mesh)
    shapes were lowered ahead of dispatch, and how many serialized
    executable bytes the persistent store shipped (the transport-side
    traffic the 375k ceiling was made of)."""
    SENSORS.gauge(
        "GoalOptimizer.aot-prelowered",
        help="Chunk executables AOT-lowered ahead of dispatch",
    ).set(AOT_COUNTERS["prelowered"])
    SENSORS.gauge(
        "GoalOptimizer.executables-shipped-bytes",
        help="Serialized executable bytes shipped to the artifact store",
    ).set(AOT_COUNTERS["shipped_bytes"])
    SENSORS.gauge(
        "GoalOptimizer.aot-dispatches",
        help="Chunk dispatches served by a prelowered executable",
    ).set(AOT_COUNTERS["aot_dispatches"])


def _push_flight_sensors(goal_name: str, flight: dict) -> None:
    """Flight-recorder convergence-shape sensors (recorder-on runs only):
    the per-step action distribution and how front-loaded the goal's
    progress was.  Both fused paths report through here."""
    steps = flight.get("steps") or []
    labels = {"goal": goal_name}
    hist = SENSORS.histogram(
        "GoalOptimizer.actions-per-step",
        buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
        labels=labels,
        help="Accepted actions per fixpoint step (flight recorder)")
    total = 0
    for s in steps:
        hist.observe(s["actions"])
        total += s["actions"]
    to90 = 0
    if total > 0:
        cum = 0
        for i, s in enumerate(steps):
            cum += s["actions"]
            if cum >= 0.9 * total:
                to90 = i + 1
                break
    SENSORS.gauge(
        "GoalOptimizer.steps-to-90pct-actions", labels=labels,
        help="Steps to reach 90% of the goal's accepted actions "
             "(flight recorder)",
    ).set(to90)


def _push_warm_sensors(seed_frontier_size: int, goals_skipped: int) -> None:
    """Warm-start counters into the sensor registry — one report per warm
    ``_optimize`` pass (cruise mode / warm facade requests)."""
    SENSORS.counter(
        "GoalOptimizer.warm-start-solves",
        help="Optimization passes seeded from a previously-converged "
             "placement",
    ).inc(1)
    SENSORS.counter(
        "GoalOptimizer.warm-start-goals-skipped",
        help="Goals skipped outright because the seeded placement still "
             "passed their fused satisfaction sweep",
    ).inc(goals_skipped)
    SENSORS.histogram(
        "GoalOptimizer.warm-start-seed-frontier-size",
        buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        help="Brokers in the warm seed frontier mask (changed union "
             "previously-active); 0 when the solve ran dense",
    ).observe(seed_frontier_size)


def _push_pipeline_sensors(goals_overlapped: int, cross_wasted: int,
                           fill_ratio: float, goals_fused: int) -> None:
    """Inter-goal pipelining counters into the sensor registry — one
    report per pipelined ``_optimize`` pass."""
    SENSORS.counter(
        "GoalOptimizer.goals-overlapped",
        help="Goal transitions whose first chunk was already in flight "
             "when the previous goal finished (adopted cross-goal openers)",
    ).inc(goals_overlapped)
    SENSORS.counter(
        "GoalOptimizer.speculative-goal-chunks-wasted",
        help="Cross-goal opener chunks discarded because the gating goal "
             "capped, left offline replicas, or touched the next goal's "
             "predicted seed frontier",
    ).inc(cross_wasted)
    SENSORS.gauge(
        "GoalOptimizer.pipeline-fill-ratio",
        help="Adopted cross-goal openers over goal transitions in the "
             "last pipelined optimization pass",
    ).set(fill_ratio)
    SENSORS.counter(
        "GoalOptimizer.goals-fused",
        help="Goals that ran inside an auto-fused disjoint-frontier stack "
             "program instead of their own per-goal driver",
    ).inc(goals_fused)


# Size cap for auto-fused disjoint-frontier groups: chaining more goals in
# one program stops paying off once the program's step budget dwarfs the
# per-goal dispatch overhead, and big multi-goal programs are exactly what
# the tunneled-TPU guard below exists to avoid.
_FUSE_MAX = 4


_stack_cache: Dict[tuple, object] = {}


def _get_stack_fn(specs: Tuple[GoalSpec, ...], constraint: BalancingConstraint,
                  num_sources: int, num_dests: int, max_steps: int, mesh=None,
                  prev_specs: Tuple[GoalSpec, ...] = (), donate: bool = False,
                  flight_capacity: int = 0):
    oracle = _repair_oracle()
    ceiling = _cross_ceiling_k()
    aot = _aot_prelower()
    key = (specs, constraint, num_sources, num_dests, max_steps, mesh,
           prev_specs, donate, oracle, flight_capacity, ceiling, aot)
    fn = _stack_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(_stack_fixpoint, specs=specs, constraint=constraint,
                             num_sources=num_sources, num_dests=num_dests,
                             max_steps=max_steps, mesh=mesh,
                             prev_specs=prev_specs, repair_oracle=oracle,
                             flight_capacity=flight_capacity),
                     donate_argnums=(0,) if donate else ())
        _stack_cache[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Goal orchestration (priority order)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GoalResult:
    name: str
    is_hard: bool
    satisfied_before: bool
    satisfied_after: bool
    steps: int
    actions_applied: int
    duration_s: float
    # True when the step loop hit its max_steps budget while still applying
    # actions — the run may not be a true fixpoint (round-1 verdict item:
    # cap-out used to be indistinguishable from convergence).
    capped: bool = False
    # True when this goal's device program was built fresh for this run (a
    # python-cache miss → XLA compiles on first invocation), so duration_s
    # includes compile time.  In the fused path the flag is per chunk: every
    # goal in a freshly-built chunk program reports True.
    fresh_compile: bool = False
    # Per-chunk records from the frontier driver (steps, actions, wall_s,
    # bucket, ns, nd, repair_steps, bisect_depth, lanes_live) when the goal
    # ran through frontier_fixpoint; None on the legacy paths.
    # tools/tail_report.py summarizes these.
    chunks: Optional[list] = None
    # Bounded-repair observability (both fused paths — the per-goal
    # frontier driver and the grouped stack programs; zeros on the legacy
    # unfused path): how many steps fired a repair pass, the compiled
    # bisection depth, and the summed live-lane counts seen by the
    # candidate compaction.
    repair_steps: int = 0
    bisect_depth: int = 0
    lanes_live: int = 0
    # Dispatch/fetch accounting of the async chunk driver (zeros on paths
    # without per-goal chunking): blocking host fetches at chunk
    # boundaries, seconds spent blocked in them, follow-up chunks launched
    # before their predecessor's stats were fetched, and the subset whose
    # on-device budget gate collapsed to a zero-step no-op.
    fetches: int = 0
    fetch_wait_s: float = 0.0
    chunks_speculative: int = 0
    chunks_wasted: int = 0
    # Flight-recorder timeline ({"kinds", "steps", "chunks"} — see
    # _flight_step_dicts for the per-step schema) when the goal ran with
    # CRUISE_FLIGHT_RECORDER=1; None with the recorder off.
    flight: Optional[dict] = None
    # Inter-goal pipelining (pipelined per-goal path only): True when this
    # goal's first chunk was a cross-goal opener adopted from the previous
    # goal's driver; the signed gap between the previous goal's end and
    # this goal's first dispatch (negative = the dispatch preceded the
    # boundary, i.e. real overlap); and this goal's own opener
    # dispatch/discard counts toward its successor.
    pipelined: bool = False
    boundary_gap_s: float = 0.0
    chunks_cross_goal: int = 0
    chunks_cross_wasted: int = 0
    # Goals this result's program was auto-fused with under the
    # disjoint-frontier grouping; 1 = ran alone.
    fused_group: int = 1


@dataclasses.dataclass
class OptimizerRun:
    """Result bundle of one optimization pass (analyzer/OptimizerResult.java:34)."""

    model: TensorClusterModel
    goal_results: List[GoalResult]
    stats_before: ClusterModelStats
    stats_after: ClusterModelStats
    num_candidates_scored: int
    provision_response: object = None  # ProvisionResponse
    # On-demand balancedness (OptimizerResult.java:117-118): 100 = no goal
    # violated, each violated goal subtracts its priority/strictness cost.
    balancedness_before: float = 100.0
    balancedness_after: float = 100.0
    # Warm-start accounting (cruise mode): whether this pass was seeded
    # from a previously-converged placement, how many brokers the seed
    # frontier mask covered (0 = no mask / dense), and how many goals the
    # fused sweep skipped outright on the seeded placement.
    warm: bool = False
    seed_frontier_size: int = 0
    goals_skipped: int = 0
    # Inter-goal pipelining accounting: whether the pass ran the pipelined
    # per-goal path, how many goal transitions adopted an in-flight
    # cross-goal opener, and how many goals ran inside auto-fused
    # disjoint-frontier groups.
    pipelined: bool = False
    goals_overlapped: int = 0
    goals_fused: int = 0

    @property
    def violated_goals_before(self) -> List[str]:
        return [g.name for g in self.goal_results if not g.satisfied_before]

    @property
    def violated_goals_after(self) -> List[str]:
        return [g.name for g in self.goal_results if not g.satisfied_after]


def optimize_goal(model: TensorClusterModel, spec: GoalSpec,
                  prev_specs: Tuple[GoalSpec, ...], constraint: BalancingConstraint,
                  options: OptimizationOptions, max_steps: int = 256,
                  num_sources: Optional[int] = None, num_dests: Optional[int] = None
                  ) -> Tuple[TensorClusterModel, int, int]:
    """Run one goal to fixpoint (one device dispatch).
    Returns (model, steps, actions)."""
    ns = num_sources or cgen.default_num_sources(model)
    nd = num_dests or cgen.default_num_dests(model)
    fixpoint = _get_fixpoint_fn(spec, prev_specs, constraint, ns, nd, max_steps)
    model, steps, total, _, _, _ = fixpoint(model, options)
    return model, int(steps), int(total)


def optimize(model: TensorClusterModel, goal_names: Sequence[str],
             constraint: Optional[BalancingConstraint] = None,
             options: Optional[OptimizationOptions] = None,
             max_steps_per_goal: int = 256,
             num_sources: Optional[int] = None, num_dests: Optional[int] = None,
             raise_on_hard_failure: bool = True,
             fused: bool = False,
             fuse_group_size: Optional[int] = None,
             fast_mode: bool = False,
             max_candidates_per_step: Optional[int] = None,
             segment_steps: Optional[int] = None,
             balancedness_priority_weight: float = 1.1,
             balancedness_strictness_weight: float = 1.5,
             mesh=None, donate_model: bool = False,
             frontier: Optional[bool] = None,
             warm_start: Optional[WarmStart] = None,
             pipeline: Optional[bool] = None) -> OptimizerRun:
    """Traced entry point around ``_optimize`` (see its docstring for the
    optimization semantics): the whole pass runs inside an
    ``analyzer.optimize`` span, and each goal's fixpoint stats (steps,
    actions, wall seconds, fresh compile) land as an ``analyzer.goal``
    child span.  The children are recorded post-hoc because the fused path
    learns the per-goal numbers from ONE packed device fetch at the end."""
    with TRACE.span("analyzer.optimize", fused=fused,
                    goals=len(list(goal_names))) as sp:
        run = _optimize(model, goal_names, constraint=constraint,
                        options=options,
                        max_steps_per_goal=max_steps_per_goal,
                        num_sources=num_sources, num_dests=num_dests,
                        raise_on_hard_failure=raise_on_hard_failure,
                        fused=fused, fuse_group_size=fuse_group_size,
                        fast_mode=fast_mode,
                        max_candidates_per_step=max_candidates_per_step,
                        segment_steps=segment_steps,
                        balancedness_priority_weight=balancedness_priority_weight,
                        balancedness_strictness_weight=balancedness_strictness_weight,
                        mesh=mesh, donate_model=donate_model,
                        frontier=frontier, warm_start=warm_start,
                        pipeline=pipeline)
        warm_attrs = ({"warm": True,
                       "seed_frontier_size": run.seed_frontier_size,
                       "goals_skipped": run.goals_skipped}
                      if run.warm else {})
        for g in run.goal_results:
            pipe_attrs = ({"pipelined": g.pipelined,
                           "boundary_gap_s": g.boundary_gap_s,
                           "chunks_cross_goal": g.chunks_cross_goal,
                           "chunks_cross_wasted": g.chunks_cross_wasted,
                           "fused_group": g.fused_group}
                          if run.pipelined else {})
            TRACE.record("analyzer.goal", g.duration_s, goal=g.name,
                         steps=g.steps, actions=g.actions_applied,
                         satisfied_after=g.satisfied_after, capped=g.capped,
                         fresh_compile=g.fresh_compile,
                         repair_steps=g.repair_steps,
                         bisect_depth=g.bisect_depth,
                         lanes_live=g.lanes_live,
                         fetches=g.fetches,
                         chunks_speculative=g.chunks_speculative,
                         chunks_wasted=g.chunks_wasted,
                         **warm_attrs,
                         **pipe_attrs,
                         **({"flight": g.flight}
                            if g.flight is not None else {}))
        sp.annotate(actions=sum(g.actions_applied for g in run.goal_results),
                    steps=sum(g.steps for g in run.goal_results),
                    candidates_scored=run.num_candidates_scored,
                    pipelined=run.pipelined,
                    goals_overlapped=run.goals_overlapped,
                    goals_fused=run.goals_fused)
        return run


def _optimize(model: TensorClusterModel, goal_names: Sequence[str],
              constraint: Optional[BalancingConstraint] = None,
              options: Optional[OptimizationOptions] = None,
              max_steps_per_goal: int = 256,
              num_sources: Optional[int] = None,
              num_dests: Optional[int] = None,
              raise_on_hard_failure: bool = True,
              fused: bool = False,
              fuse_group_size: Optional[int] = None,
              fast_mode: bool = False,
              max_candidates_per_step: Optional[int] = None,
              segment_steps: Optional[int] = None,
              balancedness_priority_weight: float = 1.1,
              balancedness_strictness_weight: float = 1.5,
              mesh=None, donate_model: bool = False,
              frontier: Optional[bool] = None,
              warm_start: Optional[WarmStart] = None,
              pipeline: Optional[bool] = None) -> OptimizerRun:
    """Run the goal stack in priority order (GoalOptimizer.optimizations).

    Each goal optimizes the model to its fixpoint, constrained by the
    acceptance masks of all previously-optimized goals; hard-goal failure
    raises unless ``raise_on_hard_failure`` is False (the reference throws
    OptimizationFailureException from hard goals' ``finish()``).

    ``fused=True`` compiles the whole stack into ONE device program (one
    dispatch + one transfer per optimization, per-goal wall times folded
    into the total) — what the service and bench use; the unfused path
    keeps per-goal compile caching, better for many distinct small stacks.
    ``fuse_group_size`` splits the fused stack into chunks of that many
    goals (each its own program, acceptance context carried across): the
    single 15-goal program at 200-broker shapes kernel-faults the TPU
    worker, while the same goals compile and run fine as smaller programs.

    ``fast_mode`` trades proposal quality for latency (the request
    parameter of OptimizationOptions.java:16; the reference caps per-broker
    search time, BalancingConstraint.java:36 /
    ResourceDistributionGoal.java:475-479): narrower candidate batches and
    a quarter of the step budget per goal.

    ``mesh`` runs every goal program through the GSPMD sharded path
    (parallel/mesh.py): pass a model already sharded with
    ``shard_model_replica_axis`` and the same ``jax.sharding.Mesh`` — the
    orchestration (chunking, segmenting, acceptance context, results) is
    identical to the single-device path.

    ``donate_model=True`` donates the model's device buffers into every
    goal/stack dispatch (``jax.jit(..., donate_argnums=0)``): the chain of
    intermediate models reuses ONE set of buffers instead of allocating a
    fresh model per dispatch, halving peak HBM for the hot path.  The
    CALLER'S input model is consumed by the first dispatch — pass
    ``donation_copy(model)`` if the pre-optimization state is still needed
    (proposals.diff reads both sides).  Ignored under ``mesh`` (sharded
    buffers keep the conservative non-donating path).

    ``frontier`` controls shrinking-frontier stepping on the fused per-goal
    path (fuse_group_size=1): None (default) engages it automatically when
    the cluster exceeds ``_FRONTIER_DENSE_MIN`` brokers, False forces the
    dense path, True forces the frontier policy (still dense below the
    floor and for non-band goals).  The multi-goal-chunk and unfused paths
    always run dense.

    ``pipeline`` controls inter-goal pipelining on the fused per-goal path:
    one fused sweep predicts every goal's satisfaction AND frontier,
    adjacent unsatisfied band goals with pairwise-disjoint predicted
    frontiers auto-fuse into one stack program, and singleton goals
    speculatively open their successor's first chunk while their own tail
    drains (discarded by an on-device conflict gate whenever the running
    goal mutates a broker inside the successor's predicted seed frontier —
    results stay bit-identical to sequential stepping).  ``None`` (default)
    engages it automatically when the per-goal chunking default kicked in
    (no manual ``fuse_group_size``) and the cluster exceeds the frontier
    floor; ``True`` forces it (requires per-goal chunking); ``False`` — or
    ``CRUISE_PIPELINE=0`` in the environment — keeps the sequential loop.

    ``warm_start`` seeds the solve from a previously-converged placement
    (cruise mode): the fresh model's replica placement is re-based onto
    ``warm_start.prev_model``'s converged arrays (copied — the donation
    path would otherwise consume the caller's standing buffers), and
    ``warm_start.active_mask`` restricts each goal's INITIAL frontier to
    changed ∪ previously-active brokers.  Correctness does not rest on the
    mask: the frontier driver always confirms compacted convergence with a
    dense chunk.  Goals the seeded placement already satisfies fall out of
    the existing fused-sweep skip.  Incompatible warm starts (shape or
    membership drift) silently fall back to the cold path; ``None`` keeps
    every code path bit-identical to a cold solve.
    """
    constraint = constraint or BalancingConstraint.default()
    options = options if options is not None else OptimizationOptions.none(model)
    specs = goals_by_priority(goal_names)
    warm = False
    seed_mask: Optional[np.ndarray] = None
    if warm_start is not None and warm_start.compatible_with(model):
        prev_pl = warm_start.prev_model
        # jnp.array COPIES: the seeded dispatch may donate its input
        # buffers, and the standing model must survive for the next delta.
        model = model.replace(
            replica_broker=jnp.array(prev_pl.replica_broker),
            replica_is_leader=jnp.array(prev_pl.replica_is_leader),
            replica_disk=jnp.array(prev_pl.replica_disk))
        warm = True
        if warm_start.active_mask is not None:
            seed_mask = np.asarray(warm_start.active_mask, dtype=bool)
    dests_pinned = num_dests is not None
    if fast_mode:
        num_sources = min(max(32, (num_sources or cgen.default_num_sources(model)) // 2),
                          model.num_replicas_padded)
        num_dests = max(min(8, model.num_brokers),
                        min((num_dests or cgen.default_num_dests(model)) // 2,
                            model.num_brokers))
        max_steps_per_goal = max(max_steps_per_goal // 4, 16)

    # Jitted: ONE runtime dispatch instead of ~30 eager ops (each eager op
    # is an RPC to a tunneled TPU runtime; results stay on device, lazily
    # fetched by to_dict()).
    stats_before = compute_stats_jit(model)
    # compute_stats_jit has already enqueued its reads of the input buffers;
    # PJRT orders donation reuse after outstanding usages, so donating the
    # same buffers below is safe.
    donate = donate_model and mesh is None
    results: List[GoalResult] = []
    ns = num_sources or cgen.default_num_sources(model)
    nd = num_dests or cgen.default_num_dests(model)
    if max_candidates_per_step:
        ns = max(1, min(ns, max_candidates_per_step))
        nd = max(1, min(nd, max_candidates_per_step // ns))
    ceiling = _cross_ceiling_k()
    if ceiling is not None and not dests_pinned and ns * nd > ceiling:
        # Remote-compile ceiling (see _COMPILE_CEILING_K): applies on the
        # tunneled TPU backend whenever the caller didn't pin the dest
        # width explicitly — including fast_mode, whose halved widths at
        # 1000 brokers still exceeded the ceiling.  The transport-matched
        # batches carry dest assignment for the count goals, so narrow
        # cross dests no longer throttle them.  Shrink nd first, then ns,
        # so the invariant ns*nd <= ceiling holds even for wide explicit
        # num_sources.
        ns0, nd0 = ns, nd
        nd = max(8, ceiling // ns)
        if ns * nd > ceiling:
            ns = max(64, ceiling // nd)
        SENSORS.counter(
            "GoalOptimizer.compile-ceiling-clamps",
            labels={"ceiling": ceiling},
            help="Candidate-width clamps caused by the opt-in "
                 "remote-compile ceiling (CRUISE_TPU_COMPILE_CEILING)",
        ).inc(1)
        _LOG.info(
            "compile ceiling %d clamped candidate widths: num_sources "
            "%d -> %d, num_dests %d -> %d (set CRUISE_TPU_COMPILE_CEILING="
            "off to disable)", ceiling, ns0, ns, nd0, nd)
    scored = 0
    goals_skipped = 0
    pipelined_run = False
    goals_overlapped = 0
    goals_fused = 0
    if pipeline and not fused:
        raise ValueError("pipeline=True requires fused=True (the fused "
                         "per-goal path)")

    def k_of(spec: GoalSpec, ns_k: Optional[int] = None,
             nd_k: Optional[int] = None) -> int:
        ns_l = ns if ns_k is None else ns_k
        nd_l = nd if nd_k is None else nd_k
        k = ns_l * nd_l * (1 if spec.uses_moves else 0)
        if spec.uses_leadership:
            k += ns_l * model.max_rf
        if spec.uses_intra_moves:
            k += ns_l * model.broker_disks.shape[1]
        if spec.uses_swaps or spec.uses_intra_swaps:
            k += min(cgen.default_num_swap_sources(model), ns_l) * \
                min(cgen.default_num_swap_partners(model), max(2, nd_l),
                    model.num_replicas_padded)
        return k

    if fused:
        # Default chunking is adaptive: one program for small models,
        # per-goal programs at ≥100 brokers — multi-goal programs at
        # 200-broker shapes break the tunneled TPU's remote-compile RPC
        # ("response body closed") and can kernel-fault the worker, while
        # the same goals compile and run fine one program each.  Chunked
        # dispatches stay async (one host fetch at the end), so the
        # round-trip cost of chunking is one transfer regardless of chunk
        # count.  EVERY fused caller (service facade included) gets the
        # safe default, not just the bench.
        manual_group = fuse_group_size
        if fuse_group_size is None and model.num_brokers >= 100:
            fuse_group_size = 1
        group = fuse_group_size or len(specs) or 1
        if pipeline:
            if manual_group is not None and manual_group > 1:
                raise ValueError(
                    "pipeline=True requires per-goal chunking; pass "
                    "fuse_group_size=1 (or omit it) when pipelining")
        pipe = pipeline
        if pipe is None:
            # Auto policy: above the frontier threshold the per-goal
            # drivers already amortize their boundaries, so inter-goal
            # overlap is pure win; a manual fuse_group_size is a caller
            # opt-out.  Below the threshold the whole-stack program is one
            # dispatch — nothing to overlap.
            pipe = (manual_group is None
                    and model.num_brokers > _FRONTIER_DENSE_MIN)
        env_p = os.environ.get("CRUISE_PIPELINE", "").strip().lower()
        if env_p in ("0", "off", "false", "no"):
            pipe = False
        if pipe:
            # The pipeline IS the grouping policy: per-goal chunk drivers
            # with speculative next-goal openers, plus automatic
            # disjoint-frontier fusion replacing the manual whole-stack
            # grouping.
            group = 1
        # At ≥500-broker shapes a single goal's full fixpoint can run many
        # minutes inside ONE dispatch, and the tunneled TPU worker kills
        # long executions ("TPU worker process crashed").  Segment each
        # goal's fixpoint into bounded dispatches and continue while the
        # segment reports capped — identical math (the model state carries
        # over), a few extra host syncs.
        if segment_steps is None and group == 1 and model.num_brokers >= 500:
            segment_steps = 32
        if segment_steps is not None and group > 1:
            # The segmented loop reads ONE goal's packed stats per dispatch
            # (packed[:, 0]); a multi-goal chunk would silently drop every
            # other goal's stats and misindex the per-spec results below.
            if fuse_group_size is not None and fuse_group_size > 1:
                raise ValueError(
                    "segment_steps requires per-goal chunking; pass "
                    "fuse_group_size=1 (or omit it) when segmenting")
            group = 1
        if group == 1:
            # Per-goal path: fused satisfaction sweep + adaptive frontier
            # chunk driver.  Per-goal durations are REAL here — every chunk
            # ends in a blocking packed fetch, so the wall between goal
            # boundaries is device-synced (the old path divided ONE total
            # across all goals: bench showed 16 identical 0.057 s entries).
            use_frontier = (frontier if frontier is not None
                            else model.num_brokers > _FRONTIER_DENSE_MIN)
            pipelined_run = bool(pipe)
            if pipe:
                # Inter-goal pipelined path: ONE fused sweep predicts every
                # goal's satisfaction AND frontier; adjacent unsatisfied
                # band goals with pairwise-disjoint predicted frontiers
                # auto-fuse into one stack program; singleton goals run the
                # frontier driver, which speculatively opens the NEXT
                # goal's first chunk while its own tail drains
                # (conflict-gated on device — bit-identical to sequential
                # stepping).
                fr_sweep = _get_frontier_sweep_fn(tuple(specs), constraint)
                env_f = os.environ.get("CRUISE_PIPELINE_FUSE",
                                       "").strip().lower()
                if env_f in ("0", "off", "false", "no"):
                    allow_fuse = False
                elif env_f in ("1", "on", "force"):
                    allow_fuse = True
                else:
                    # Multi-goal programs at 200-broker shapes kernel-fault
                    # the tunneled TPU worker (see the chunking comment
                    # above): the auto-fusion default honors that guard.
                    allow_fuse = (jax.default_backend() != "tpu"
                                  or model.num_brokers < 200)
                # Fused groups run dense without the recorder/segment
                # plumbing; those modes keep the per-goal driver.
                allow_fuse = (allow_fuse and use_frontier
                              and not _flight_recorder()
                              and segment_steps is None)
                sat_v = None
                fronts_v = None
                sweep_off = False
                handoff: Optional[dict] = None
                cross_wasted_total = 0
                goals_attempted = 0
                t_goal_end: Optional[float] = None

                def chunk_len_of(sp: GoalSpec) -> int:
                    return segment_steps or (
                        32 if (use_frontier and kernels.is_band_kind(sp)
                               and model.num_brokers > _FRONTIER_DENSE_MIN)
                        else max(max_steps_per_goal, 1))

                def mk_next(m: int) -> Optional[PipelineNextGoal]:
                    # Descriptor of the IMMEDIATE successor only: skipping
                    # a stale-satisfied intermediate goal would need the
                    # sweep the pipeline is overlapping away, so the
                    # opener's in-program skip shortcut plays that role.
                    if m >= len(specs) or fronts_v is None:
                        return None
                    sp_n = specs[m]
                    seed = None
                    if kernels.is_band_kind(sp_n):
                        seed = fronts_v[m].copy()
                        if seed_mask is not None:
                            seed = seed | seed_mask
                        if not seed.any():
                            seed = None
                    return PipelineNextGoal(
                        spec=sp_n, prev_specs=tuple(specs[:m]),
                        seed_active=seed, chunk_len=chunk_len_of(sp_n),
                        max_steps=max(max_steps_per_goal, 1))

                idx = 0
                while idx < len(specs):
                    spec = specs[idx]
                    tg = time.monotonic()
                    prev = tuple(specs[:idx])
                    if handoff is None:
                        if sat_v is None:
                            SWEEP_COUNTERS["dispatches"] += 1
                            sat_np, off_np, fronts_np = jax.device_get(
                                fr_sweep(model))
                            sat_v = np.asarray(sat_np)
                            fronts_v = np.asarray(fronts_np)
                            sweep_off = bool(off_np)
                        if bool(sat_v[idx]) and not sweep_off:
                            SWEEP_COUNTERS["skipped_goals"] += 1
                            goals_skipped += 1
                            results.append(GoalResult(
                                name=spec.name, is_hard=spec.is_hard,
                                satisfied_before=True, satisfied_after=True,
                                steps=0, actions_applied=0,
                                duration_s=time.monotonic() - tg))
                            idx += 1
                            continue
                        # Auto disjoint-frontier fusion: adjacent
                        # unsatisfied band goals whose predicted frontiers
                        # share no broker run as ONE chained stack program
                        # — replacing the manual fuse_group_size knob for
                        # exactly the groups where in-program chaining
                        # can't thrash (no broker is revisited).
                        fuse_specs = (spec,)
                        if (allow_fuse and not sweep_off
                                and kernels.is_band_kind(spec)
                                and fronts_v[idx].any()):
                            acc = fronts_v[idx].copy()
                            j = idx + 1
                            while (len(fuse_specs) < _FUSE_MAX
                                   and j < len(specs)
                                   and kernels.is_band_kind(specs[j])
                                   and not bool(sat_v[j])
                                   and fronts_v[j].any()
                                   and not (acc & fronts_v[j]).any()):
                                acc = acc | fronts_v[j]
                                fuse_specs = fuse_specs + (specs[j],)
                                j += 1
                        if len(fuse_specs) > 1:
                            n_cached = len(_stack_cache)
                            stack_fn = _get_stack_fn(
                                fuse_specs, constraint, ns, nd,
                                max_steps_per_goal, mesh=mesh,
                                prev_specs=prev, donate=donate)
                            miss = len(_stack_cache) > n_cached
                            token = _persist_token(
                                "stack", (fuse_specs, constraint, ns, nd,
                                          max_steps_per_goal, mesh, prev,
                                          donate), model, options) \
                                if miss else None
                            g_fresh = miss and not (
                                token and compile_cache.seen(token))
                            model, packed = stack_fn(model, options)
                            if token:
                                compile_cache.mark(token)
                            FETCH_COUNTERS["chunks_dispatched"] += 1
                            packed_np = np.asarray(jax.device_get(packed))
                            FETCH_COUNTERS["device_fetches"] += 1
                            now = time.monotonic()
                            share = (now - tg) / len(fuse_specs)
                            for gi, sp_g in enumerate(fuse_specs):
                                row = packed_np[:, gi]
                                scored += int(row[0]) * k_of(sp_g)
                                results.append(GoalResult(
                                    name=sp_g.name, is_hard=sp_g.is_hard,
                                    satisfied_before=bool(row[2]),
                                    satisfied_after=bool(row[3]),
                                    steps=int(row[0]),
                                    actions_applied=int(row[1]),
                                    duration_s=share,
                                    capped=bool(row[4]),
                                    fresh_compile=g_fresh,
                                    repair_steps=int(row[5]),
                                    bisect_depth=int(row[6]),
                                    lanes_live=int(row[7]),
                                    fetches=1 if gi == 0 else 0,
                                    fused_group=len(fuse_specs)))
                                _push_repair_sensors(
                                    sp_g.name, int(row[5]), int(row[6]),
                                    int(row[7]))
                                if sp_g.is_hard and not bool(row[3]) \
                                        and raise_on_hard_failure:
                                    raise OptimizationFailureException(
                                        f"hard goal {sp_g.name} not "
                                        "satisfied after optimization")
                            goals_fused += len(fuse_specs)
                            goals_attempted += len(fuse_specs)
                            if packed_np[1].any():
                                sat_v = None
                            t_goal_end = now
                            idx += len(fuse_specs)
                            continue
                    # Singleton per-goal driver, pipelined into the
                    # immediate successor.  With a handoff in hand the
                    # first chunk is already in flight — no sweep, no
                    # dispatch, straight to its fetch.
                    goals_attempted += 1
                    model, info = frontier_fixpoint(
                        model, options, spec, prev, constraint,
                        num_sources=ns, num_dests=nd,
                        max_steps=max(max_steps_per_goal, 1),
                        chunk_steps=chunk_len_of(spec), mesh=mesh,
                        donate=donate, frontier=use_frontier,
                        seed_active=seed_mask if handoff is None else None,
                        next_goal=mk_next(idx + 1), prelaunch=handoff)
                    adopted = bool(info.get("adopted_prelaunch"))
                    handoff = info.get("handoff")
                    if handoff is not None:
                        goals_overlapped += 1
                    cross_wasted_total += info.get("cross_wasted", 0)
                    for ch in info["chunks"]:
                        scored += ch["steps"] * k_of(spec, ch["ns"],
                                                     ch["nd"])
                    if info["actions"]:
                        sat_v = None  # model changed — sweep re-dispatches
                    gap = 0.0
                    if t_goal_end is not None \
                            and info.get("t_first_dispatch"):
                        gap = info["t_first_dispatch"] - t_goal_end
                    results.append(GoalResult(
                        name=spec.name, is_hard=spec.is_hard,
                        satisfied_before=info["satisfied_before"],
                        satisfied_after=info["satisfied_after"],
                        steps=info["steps"],
                        actions_applied=info["actions"],
                        duration_s=time.monotonic() - tg,
                        capped=info["capped"],
                        fresh_compile=info["fresh_compile"],
                        chunks=info["chunks"],
                        repair_steps=info.get("repair_steps", 0),
                        bisect_depth=info.get("bisect_depth", 0),
                        lanes_live=info.get("lanes_live", 0),
                        fetches=info.get("fetches", 0),
                        fetch_wait_s=info.get("fetch_wait_s", 0.0),
                        chunks_speculative=info.get("chunks_speculative",
                                                    0),
                        chunks_wasted=info.get("chunks_wasted", 0),
                        flight=info.get("flight"),
                        pipelined=adopted,
                        boundary_gap_s=gap,
                        chunks_cross_goal=info.get("cross_dispatched", 0),
                        chunks_cross_wasted=info.get("cross_wasted", 0)))
                    t_goal_end = time.monotonic()
                    _push_repair_sensors(spec.name,
                                         info.get("repair_steps", 0),
                                         info.get("bisect_depth", 0),
                                         info.get("lanes_live", 0))
                    _push_dispatch_sensors(
                        spec.name,
                        info.get("fetches", 0),
                        info.get("chunks_speculative", 0),
                        info.get("chunks_wasted", 0),
                        fetch_bytes=sum(c.get("fetch_bytes", 0)
                                        for c in info["chunks"]),
                        collectives=sum(c.get("collectives") or 0
                                        for c in info["chunks"]))
                    if info.get("flight") is not None:
                        _push_flight_sensors(spec.name, info["flight"])
                    if spec.is_hard and not info["satisfied_after"] \
                            and raise_on_hard_failure:
                        raise OptimizationFailureException(
                            f"hard goal {spec.name} not satisfied after "
                            "optimization")
                    idx += 1
                fill = (goals_overlapped / (goals_attempted - 1)
                        if goals_attempted > 1 else 0.0)
                _push_pipeline_sensors(goals_overlapped,
                                       cross_wasted_total, fill,
                                       goals_fused)
            else:
                sweep_fn = _get_sweep_fn(tuple(specs), constraint)
                sat_v = None
                sweep_off = False
                prev: Tuple[GoalSpec, ...] = ()
                for spec in specs:
                    tg = time.monotonic()
                    i = len(results)
                    if sat_v is None:
                        # ONE jitted dispatch answers "already satisfied?"
                        # for the WHOLE stack; it stays valid until some
                        # goal mutates the model, then re-dispatches the
                        # same program (one compile total).
                        SWEEP_COUNTERS["dispatches"] += 1
                        sat_np, off_np = jax.device_get(sweep_fn(model))
                        sat_v = np.asarray(sat_np)
                        sweep_off = bool(off_np)
                    if bool(sat_v[i]) and not sweep_off:
                        # The same decision _goal_fixpoint's skip shortcut
                        # makes (satisfied + no offline replicas → zero
                        # steps, before == after), minus the
                        # fixpoint-program entry.
                        SWEEP_COUNTERS["skipped_goals"] += 1
                        goals_skipped += 1
                        results.append(GoalResult(
                            name=spec.name, is_hard=spec.is_hard,
                            satisfied_before=True, satisfied_after=True,
                            steps=0, actions_applied=0,
                            duration_s=time.monotonic() - tg))
                        prev = prev + (spec,)
                        continue
                    chunk_len = segment_steps or (
                        32 if (use_frontier and kernels.is_band_kind(spec)
                               and model.num_brokers > _FRONTIER_DENSE_MIN)
                        else max(max_steps_per_goal, 1))
                    model, info = frontier_fixpoint(
                        model, options, spec, prev, constraint,
                        num_sources=ns, num_dests=nd,
                        max_steps=max(max_steps_per_goal, 1),
                        chunk_steps=chunk_len, mesh=mesh, donate=donate,
                        frontier=use_frontier, seed_active=seed_mask)
                    for ch in info["chunks"]:
                        scored += ch["steps"] * k_of(spec, ch["ns"],
                                                     ch["nd"])
                    if info["actions"]:
                        sat_v = None  # model changed — sweep re-dispatches
                    results.append(GoalResult(
                        name=spec.name, is_hard=spec.is_hard,
                        satisfied_before=info["satisfied_before"],
                        satisfied_after=info["satisfied_after"],
                        steps=info["steps"],
                        actions_applied=info["actions"],
                        duration_s=time.monotonic() - tg,
                        capped=info["capped"],
                        fresh_compile=info["fresh_compile"],
                        chunks=info["chunks"],
                        repair_steps=info.get("repair_steps", 0),
                        bisect_depth=info.get("bisect_depth", 0),
                        lanes_live=info.get("lanes_live", 0),
                        fetches=info.get("fetches", 0),
                        fetch_wait_s=info.get("fetch_wait_s", 0.0),
                        chunks_speculative=info.get("chunks_speculative",
                                                    0),
                        chunks_wasted=info.get("chunks_wasted", 0),
                        flight=info.get("flight")))
                    _push_repair_sensors(spec.name,
                                         info.get("repair_steps", 0),
                                         info.get("bisect_depth", 0),
                                         info.get("lanes_live", 0))
                    _push_dispatch_sensors(
                        spec.name,
                        info.get("fetches", 0),
                        info.get("chunks_speculative", 0),
                        info.get("chunks_wasted", 0),
                        fetch_bytes=sum(c.get("fetch_bytes", 0)
                                        for c in info["chunks"]),
                        collectives=sum(c.get("collectives") or 0
                                        for c in info["chunks"]))
                    if info.get("flight") is not None:
                        _push_flight_sensors(spec.name, info["flight"])
                    if spec.is_hard and not info["satisfied_after"] \
                            and raise_on_hard_failure:
                        raise OptimizationFailureException(
                            f"hard goal {spec.name} not satisfied after "
                            "optimization")
                    prev = prev + (spec,)
        else:
            packed_rows = []
            # Per-goal flight buffers (i32[G, capacity, FLIGHT_WIDTH] per
            # group chunk) ride the same packed fetch when the recorder is
            # on; None entries keep the off path fetch-identical.
            flight_cap = (max(max_steps_per_goal, 1)
                          if _flight_recorder() else 0)
            flight_rows: List[np.ndarray] = []
            group_wall: List[float] = []  # one wall per group chunk
            group_of: List[int] = []      # goal index -> its chunk's wall
            # Per-goal fresh-compile flags: a _stack_cache miss means the
            # chunk's XLA program is built (compiled on first call) within
            # this run.
            fresh_v: List[bool] = []
            durations: List[float] = []
            prev: Tuple[GoalSpec, ...] = ()
            # One-ahead pipeline: dispatch chunk i+1 (tracing/compiling its
            # program on the host while the device runs chunk i) BEFORE
            # fetching chunk i's packed stats, so chunk boundaries cost no
            # device idle.  Fetches complete in dispatch order, so the
            # delta between consecutive fetch completions is each chunk's
            # real incremental wall — split evenly across its goals, as
            # before.  The default auto config uses one chunk for small
            # models, where the pipeline degenerates to dispatch + fetch.
            inflight: List[tuple] = []  # (goal_chunk, packed_d, flight_d, fresh)
            t_prev = time.monotonic()
            # One blocking fetch per group chunk; attributed to the chunk's
            # lead goal (a group shares its packed fetch, so per-goal split
            # would be fiction).  The one-ahead dispatch is unconditional,
            # not speculative — every chunk is needed — so the speculation
            # counters stay 0 on this path.
            fetch_of: Dict[str, int] = {}
            fetch_wait_of: Dict[str, float] = {}

            def _drain_one():
                nonlocal t_prev
                goal_chunk, packed_d, flight_d, chunk_fresh = inflight.pop(0)
                t_get = time.monotonic()
                # Still ONE blocking fetch per group chunk: the flight
                # block (when recording) joins the packed transfer.
                if flight_d is not None:
                    packed_np, flight_np = jax.device_get(
                        (packed_d, flight_d))
                    flight_np = np.asarray(flight_np)
                    FETCH_COUNTERS["flight_bytes"] += int(flight_np.nbytes)
                    flight_rows.append(flight_np)
                else:
                    packed_np = jax.device_get(packed_d)
                packed_rows.append(np.asarray(packed_np))
                FETCH_COUNTERS["device_fetches"] += 1
                now = time.monotonic()
                lead = goal_chunk[0].name
                fetch_of[lead] = fetch_of.get(lead, 0) + 1
                fetch_wait_of[lead] = fetch_wait_of.get(lead, 0.0) \
                    + (now - t_get)
                _push_dispatch_sensors(lead, 1, 0, 0)
                durations.extend([(now - t_prev) / len(goal_chunk)]
                                 * len(goal_chunk))
                fresh_v.extend([chunk_fresh] * len(goal_chunk))
                group_wall.append(now - t_prev)
                group_of.extend([len(group_wall) - 1] * len(goal_chunk))
                t_prev = now

            for start in range(0, len(specs), group):
                chunk = tuple(specs[start:start + group])
                n_cached = len(_stack_cache)
                stack_fn = _get_stack_fn(chunk, constraint, ns, nd,
                                         max_steps_per_goal, mesh=mesh,
                                         prev_specs=prev, donate=donate,
                                         flight_capacity=flight_cap)
                miss = len(_stack_cache) > n_cached
                # A python-dict miss alone can't tell a cold XLA build from
                # a warm persistent-cache load after a process restart; the
                # compile marker (written once the program exists) refines
                # fresh_compile to "no process has built this program yet".
                token = _persist_token(
                    "stack", (chunk, constraint, ns, nd, max_steps_per_goal,
                              mesh, prev, donate)
                    + ((flight_cap,) if flight_cap else ()), model,
                    options) if miss else None
                chunk_fresh = miss and not (token and compile_cache.seen(token))
                if flight_cap:
                    model, packed, flight_d = stack_fn(model, options)
                else:
                    model, packed = stack_fn(model, options)
                    flight_d = None
                if token:
                    compile_cache.mark(token)
                FETCH_COUNTERS["chunks_dispatched"] += 1
                inflight.append((chunk, packed, flight_d, chunk_fresh))
                if len(inflight) > 1:
                    _drain_one()
                prev = prev + chunk
            while inflight:
                _drain_one()
            # Async host copies of the result arrays the caller reads next
            # (props.diff): the immutable leaves are the same buffers in
            # the initial model, so prefetching covers both diff sides.
            for arr in (model.replica_broker, model.replica_disk,
                        model.replica_is_leader, model.partition_replicas,
                        model.replica_valid, model.replica_load_leader,
                        model.replica_load_follower, model.partition_topic,
                        model.partition_valid):
                if hasattr(arr, "copy_to_host_async"):
                    arr.copy_to_host_async()
            (steps_v, actions_v, before_v, after_v, capped_v,
             repair_v, depth_v, lanes_v) = (
                np.concatenate([row[i] for row in packed_rows])
                for i in range(8))
            flight_all = (np.concatenate(flight_rows, axis=0)
                          if flight_rows else None)
            for i, spec in enumerate(specs):
                scored += int(steps_v[i]) * k_of(spec)
                flight = None
                if flight_all is not None:
                    # Slice this goal's buffer to ITS executed step count
                    # (steps_v aligns with the concatenated goal axis) —
                    # grouped timelines attribute steps per goal, one
                    # synthetic "chunk" per group program.
                    flight = {
                        "kinds": list(FLIGHT_KINDS),
                        "steps": _flight_step_dicts(
                            flight_all[i][:int(steps_v[i])], 0, 0),
                        "chunks": [{"wall_s": group_wall[group_of[i]],
                                    "bucket": None,
                                    "len": int(steps_v[i]),
                                    "fresh_compile": fresh_v[i],
                                    "speculative": False}],
                    }
                results.append(GoalResult(
                    name=spec.name, is_hard=spec.is_hard,
                    satisfied_before=bool(before_v[i]),
                    satisfied_after=bool(after_v[i]),
                    steps=int(steps_v[i]), actions_applied=int(actions_v[i]),
                    duration_s=durations[i], capped=bool(capped_v[i]),
                    fresh_compile=fresh_v[i],
                    repair_steps=int(repair_v[i]),
                    bisect_depth=int(depth_v[i]),
                    lanes_live=int(lanes_v[i]),
                    fetches=fetch_of.get(spec.name, 0),
                    fetch_wait_s=fetch_wait_of.get(spec.name, 0.0),
                    flight=flight))
                _push_repair_sensors(spec.name, int(repair_v[i]),
                                     int(depth_v[i]), int(lanes_v[i]))
                if flight is not None:
                    _push_flight_sensors(spec.name, flight)
                if spec.is_hard and not bool(after_v[i]) \
                        and raise_on_hard_failure:
                    raise OptimizationFailureException(
                        f"hard goal {spec.name} not satisfied after "
                        "optimization")
    else:
        prev: Tuple[GoalSpec, ...] = ()
        for spec in specs:
            t0 = time.monotonic()
            n_cached = len(_fixpoint_cache)
            fixpoint = _get_fixpoint_fn(spec, prev, constraint, ns, nd,
                                        max_steps_per_goal, mesh=mesh,
                                        donate=donate)
            miss = len(_fixpoint_cache) > n_cached
            token = _persist_token(
                "fixpoint", (spec, prev, constraint, ns, nd,
                             max_steps_per_goal, mesh, donate),
                model, options) if miss else None
            fresh = miss and not (token and compile_cache.seen(token))
            model, steps_d, actions_d, before_d, after_d, capped_d = \
                fixpoint(model, options)
            if token:
                compile_cache.mark(token)
            steps, actions = int(steps_d), int(actions_d)
            before, after, capped = bool(before_d), bool(after_d), bool(capped_d)
            scored += steps * k_of(spec)
            results.append(GoalResult(name=spec.name, is_hard=spec.is_hard,
                                      satisfied_before=before, satisfied_after=after,
                                      steps=steps, actions_applied=actions,
                                      duration_s=time.monotonic() - t0, capped=capped,
                                      fresh_compile=fresh))
            if spec.is_hard and not after and raise_on_hard_failure:
                raise OptimizationFailureException(
                    f"hard goal {spec.name} not satisfied after optimization")
            prev = prev + (spec,)

    from cruise_control_tpu.analyzer.provisioning import (ProvisionResponse,
                                                          host_view,
                                                          provision_verdict_for_goal)
    provision = ProvisionResponse()
    view = host_view(model)
    for spec, res in zip(specs, results):
        provision.aggregate(provision_verdict_for_goal(spec, model, constraint,
                                                       res.satisfied_after, view))

    from cruise_control_tpu.analyzer.balancedness import (balancedness_cost_by_goal,
                                                          balancedness_score)
    costs = balancedness_cost_by_goal(specs, balancedness_priority_weight,
                                      balancedness_strictness_weight)
    seed_size = int(seed_mask.sum()) if (warm and seed_mask is not None) else 0
    if warm:
        _push_warm_sensors(seed_size, goals_skipped)
    if _aot_prelower():
        _push_aot_sensors()
    return OptimizerRun(model=model, goal_results=results, stats_before=stats_before,
                        stats_after=compute_stats_jit(model), num_candidates_scored=scored,
                        provision_response=provision,
                        balancedness_before=balancedness_score(
                            costs, [g.name for g in results if not g.satisfied_before]),
                        balancedness_after=balancedness_score(
                            costs, [g.name for g in results if not g.satisfied_after]),
                        warm=warm, seed_frontier_size=seed_size,
                        goals_skipped=goals_skipped,
                        pipelined=pipelined_run,
                        goals_overlapped=goals_overlapped,
                        goals_fused=goals_fused)
