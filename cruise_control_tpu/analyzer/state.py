"""Per-step broker aggregate bundle.

All goal kernels consume these aggregates instead of touching the replica
axis; they are recomputed once per optimizer step (one fused scatter pass
over R) and gathered per candidate.  This replaces the reference's
incrementally-maintained per-object accumulators (Broker/Host/Rack load
fields) with recompute-on-step — cheaper on TPU than fine-grained updates,
and trivially correct.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import Array

from cruise_control_tpu.model.tensor_model import TensorClusterModel


@struct.dataclass
class BrokerArrays:
    load: Array  # f32[B, 4]
    replica_count: Array  # i32[B]
    leader_count: Array  # i32[B]
    potential_nw_out: Array  # f32[B]
    leader_bytes_in: Array  # f32[B]
    alive: Array  # bool[B]
    capacity: Array  # f32[B, 4]
    valid: Array  # bool[B]
    num_alive: Array  # i32 scalar

    @classmethod
    def from_model(cls, model: TensorClusterModel) -> "BrokerArrays":
        alive = model.alive_broker_mask()
        return cls(
            load=model.broker_load(),
            replica_count=model.broker_replica_counts(),
            leader_count=model.broker_leader_counts(),
            potential_nw_out=model.potential_leadership_load(),
            leader_bytes_in=model.broker_leader_bytes_in(),
            alive=alive,
            capacity=model.broker_capacity,
            valid=model.broker_valid,
            num_alive=jnp.maximum(alive.sum(), 1),
        )


@struct.dataclass
class StepInvariants:
    """Step-invariant tensors of one goal fixpoint, computed ONCE before the
    ``lax.while_loop`` and closed over by the loop body (body constvars are
    loop constants — XLA evaluates them once per fixpoint dispatch, not once
    per step).  Everything here depends only on static capacities,
    thresholds, topology, and aliveness-conserved totals: replica moves,
    swaps, and leadership transfers between alive brokers conserve the
    alive-broker load/count sums the band averages are built from, so the
    band *sides* never change within a fixpoint.  (Healing moves off dead
    brokers do shift the alive totals; the sides are frozen at fixpoint
    entry — the final ``goal_satisfied`` check and the next goal's
    invariants always use fresh state.)  Built by
    ``optimizer.compute_step_invariants``."""

    upper_min: Array  # f32[B, 8] — min over all optimized goals' upper sides
    lower_max: Array  # f32[B, 8] — max over their lower sides
    spec_lower: Array  # f32[B] — the current goal's own band
    spec_upper: Array  # f32[B]
    topic_lower: Optional[Array] = None  # f32[T] when a topic goal is in play
    topic_upper: Optional[Array] = None  # f32[T]
    designated: Optional[Array] = None  # bool[T] when min-leaders is in play


def pow2_bucket(n: int, floor: int) -> int:
    """Smallest power-of-two-of-``floor`` bucket ≥ ``n`` (doubling ladder
    starting at ``floor``).  The shared bucketing rule of every compacted
    axis in the analyzer: the frontier's broker axis (FrontierInvariants)
    and the live-candidate lane axis (optimizer select_batched compaction)
    both quantize to this ladder, so at most ~log2(size/floor) distinct
    compiled shapes exist per goal for each axis."""
    bucket = max(1, int(floor))
    n = max(1, int(n))
    while bucket < n:
        bucket *= 2
    return bucket


@struct.dataclass
class FrontierInvariants:
    """The *active frontier* of one goal's chunked fixpoint: the brokers that
    can still matter to the goal's next steps (outside the band, donors of
    the pull phase, the receivers covering the remaining surplus, dead
    brokers still hosting replicas) plus the index maps between the full
    broker axis and a compacted axis bucketed to a power of two.  Computed
    at each chunk boundary by ``optimizer.frontier_fixpoint`` (the mask is
    ``kernels.frontier_active``; bucketing bounds recompiles to ~log2(B)
    shapes); the step then runs its candidate batches and selection segment
    spaces over the compacted axis and scatters accepted actions back into
    the full model through the candidates' full broker ids.  The compacted
    axis length (``full_of_compact.shape[0]``) is the bucket — shape, not a
    static field, so the jit trace specializes on it."""

    active: Array           # bool[B] — full-axis membership mask
    compact_of_full: Array  # i32[B] — compact id per broker, -1 when inactive
    full_of_compact: Array  # i32[Bc] — full broker id per compact slot, -1 pad
    # Per-shard frontier mask: slot liveness over the compacted axis
    # (``full_of_compact >= 0``).  Only materialized under a search mesh,
    # where it is device_put with ``P(SEARCH_AXIS)`` so every GSPMD program
    # consuming the frontier owns a genuinely partitioned compact-axis
    # operand (each shard holds its own slice of the bucket) instead of a
    # replicated one.  ``None`` on the single-device path keeps those
    # graphs byte-identical to the pre-mesh builds.
    shard_active: Optional[Array] = None  # bool[Bc] — compact slot liveness


# ---------------------------------------------------------------------------
# Packed per-chunk stats layout
# ---------------------------------------------------------------------------
# ``optimizer._goal_fixpoint_budget`` returns one i32[PACKED_WIDTH] vector per
# chunk so the whole chunk-boundary decision — did the goal converge, is it
# satisfied, are offline replicas left, how big is the next frontier — rides
# in ONE host transfer alongside the active mask.  The layout is shared by the
# per-goal chunk driver, the grouped-stack i32[PACKED_WIDTH, G] matrix, the
# sharded driver, and tools/dispatch_report.py; extend it by appending (the
# first 8 slots predate the orchestration fields and are pinned by recorded
# bench artifacts).

PACKED_STEPS = 0          # steps executed this chunk
PACKED_ACTIONS = 1        # actions accepted this chunk
PACKED_BEFORE = 2         # goal satisfied at chunk entry (0/1)
PACKED_AFTER = 3          # goal satisfied at chunk exit (0/1)
PACKED_CAPPED = 4         # hit the step budget while still applying (0/1)
PACKED_REPAIR_STEPS = 5   # steps whose selection repair saw a violation
PACKED_BISECT_DEPTH = 6   # max compiled repair bisection depth
PACKED_LANES_LIVE = 7     # live candidate lanes at compaction, summed
PACKED_NUM_ACTIVE = 8     # frontier population at chunk exit; -1 = non-band
PACKED_ANY_OFFLINE = 9    # offline replicas remain at chunk exit (0/1)
PACKED_CONFLICT = 10      # brokers touched since the frontier sweep that lie
                          # inside the NEXT goal's predicted seed frontier; 0
                          # when the chunk ran without pipeline accounting
PACKED_WIDTH = 11


# ---------------------------------------------------------------------------
# Inter-goal pipeline invariants
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PipelineNextGoal:
    """Host-side descriptor of the NEXT goal in a pipelined stack run.

    The chunk driver (``optimizer.frontier_fixpoint``) uses it to dispatch
    the next goal's opening chunk while the current goal's convergence tail
    drains: ``seed_active`` is the next goal's frontier as PREDICTED by the
    fused stack sweep (computed before the current goal mutated anything),
    and the on-device conflict slot (``PACKED_CONFLICT`` = |touched ∩
    seed_active|) invalidates the speculative opener whenever the current
    goal touched a broker inside that predicted frontier.  ``chunk_len`` /
    ``max_steps`` / ``min_chunk`` replicate the first-chunk length policy
    the next goal's own driver would use, so an adopted opener is
    bit-identical to the chunk a sequential driver would have dispatched.
    """

    spec: object                        # GoalSpec of the next goal
    prev_specs: tuple                   # acceptance context it will run under
    seed_active: Optional[np.ndarray]   # bool[B] predicted frontier (or None)
    chunk_len: int                      # the next goal's chunk_steps
    max_steps: int                      # the next goal's step budget
    min_chunk: int = 4                  # the next goal's min_chunk


# ---------------------------------------------------------------------------
# Flight-recorder per-step row layout
# ---------------------------------------------------------------------------
# With ``CRUISE_FLIGHT_RECORDER=1`` the budget fixpoint additionally carries a
# fixed-size i32[C, FLIGHT_WIDTH] telemetry buffer (C = chunk capacity): the
# loop body writes one row per executed step, and the buffer piggybacks on the
# same single boundary fetch as the packed stats — zero extra dispatches, zero
# extra ``device_get`` calls.  Speculative chunks record into their own buffer
# and are simply never fetched when the budget gate collapses them.  The
# f32 best-eligible score is bitcast into the i32 row (FLIGHT_SCORE_BITS);
# hosts decode it with ``np.int32(...).view(np.float32)``.

FLIGHT_ACTIONS = 0      # candidates accepted this step
FLIGHT_FRONTIER = 1     # frontier_active population at step entry; -1 non-band
FLIGHT_REPAIR = 2       # selection repair saw a violation this step (0/1)
FLIGHT_BISECT = 3       # compiled repair bisection depth this step
FLIGHT_LANES = 4        # live candidate lanes at compaction this step
FLIGHT_SCORE_BITS = 5   # best eligible candidate score, f32 bitcast to i32
FLIGHT_KIND = 6         # argmax action-kind index into FLIGHT_KINDS; -1 none
FLIGHT_WIDTH = 7


@struct.dataclass
class OptimizationOptions:
    """Traced per-request constraints (analyzer/OptimizationOptions.java:16).

    Arrays so that changing exclusions does not trigger recompilation.
    """

    topic_excluded: Array  # bool[T] excluded from partition movement
    broker_excluded_replica_move: Array  # bool[B] may not *receive* replicas
    broker_excluded_leadership: Array  # bool[B] may not *receive* leadership
    requested_dest_only: Array  # bool[B] — if any set, moves must land on these
    only_move_immigrants: Array  # bool scalar

    @classmethod
    def none(cls, model: TensorClusterModel) -> "OptimizationOptions":
        # Host (numpy) leaves: on a tunneled TPU each eager jnp.zeros is one
        # runtime RPC; jit arguments are shipped in a single batched
        # transfer instead.
        B = model.num_brokers
        return cls(
            topic_excluded=np.zeros((model.num_topics,), bool),
            broker_excluded_replica_move=np.zeros((B,), bool),
            broker_excluded_leadership=np.zeros((B,), bool),
            requested_dest_only=np.zeros((B,), bool),
            only_move_immigrants=np.zeros((), bool),
        )


# ---------------------------------------------------------------------------
# Warm-start seeding (cruise mode)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ModelDelta:
    """Host-side diff of a previous converged model against a fresh one.

    ``changed_mask`` flags every broker whose aggregate load moved by more
    than a relative epsilon OR whose replica set differs between the fresh
    (actual) placement and the previous converged (target) placement — the
    second clause is the "previously-active" component of the warm-start
    seed frontier: brokers the standing target still wants moves on.
    ``magnitude`` is the relative L1 load delta over the whole cluster, the
    number the warm/cold threshold compares against."""

    changed_mask: np.ndarray  # bool[B]
    magnitude: float
    num_changed: int

    @property
    def is_zero(self) -> bool:
        return self.num_changed == 0


@dataclasses.dataclass(frozen=True)
class WarmStart:
    """Seed for a delta-seeded warm-start optimization.

    ``prev_model`` is the previous CONVERGED model (an ``OptimizerRun``'s
    ``model``); the fixpoint starts from its placement re-based onto the
    fresh model's load state.  ``active_mask`` (bool[B], host numpy)
    restricts the initial frontier to changed ∪ previously-active brokers;
    the dense confirm chunk still validates convergence, so an undersized
    mask costs steps, never correctness.  ``per_goal_satisfied`` carries the
    previous run's per-goal verdicts for observability — the fused
    already-satisfied sweep remains the authority on skipping."""

    prev_model: TensorClusterModel
    active_mask: Optional[np.ndarray] = None
    per_goal_satisfied: Optional[Dict[str, bool]] = None

    def compatible_with(self, model: TensorClusterModel) -> bool:
        """Seeding is only sound when the replica axis is identical: same
        padded shapes and the same replica→partition/topic identity (moves
        change ``replica_broker``, never membership)."""
        p = self.prev_model
        if (p.num_brokers != model.num_brokers
                or p.num_replicas_padded != model.num_replicas_padded
                or p.num_partitions != model.num_partitions
                or p.max_rf != model.max_rf):
            return False
        return bool(
            np.array_equal(np.asarray(p.replica_partition),
                           np.asarray(model.replica_partition))
            and np.array_equal(np.asarray(p.replica_valid),
                               np.asarray(model.replica_valid)))


def model_delta(prev_model: TensorClusterModel,
                fresh_model: TensorClusterModel,
                rel_epsilon: float = 1e-3) -> Optional[ModelDelta]:
    """Host-side model-delta probe: diff the previous converged model against
    the fresh one into a changed-broker mask + relative delta magnitude.

    Returns None when the models are shape- or membership-incompatible
    (brokers added/removed, partitions created, padding changed) — the
    caller must fall back to a cold solve.  Pure numpy over host fetches of
    a handful of per-broker aggregates; no compiled program is involved, so
    the probe itself costs zero device dispatches beyond the two aggregate
    reads."""
    ws = WarmStart(prev_model=prev_model)
    if not ws.compatible_with(fresh_model):
        return None
    prev_load = np.asarray(prev_model.broker_load(), dtype=np.float64)
    new_load = np.asarray(fresh_model.broker_load(), dtype=np.float64)
    diff = np.abs(new_load - prev_load).sum(axis=1)
    total = max(float(np.abs(prev_load).sum()), 1e-9)
    load_changed = diff > rel_epsilon * max(total / max(prev_load.shape[0], 1),
                                            1e-9)
    magnitude = float(diff.sum() / total)
    # Placement component: brokers whose replica set differs between the
    # fresh actual placement and the previous converged target.
    prev_rb = np.asarray(prev_model.replica_broker)
    new_rb = np.asarray(fresh_model.replica_broker)
    valid = np.asarray(fresh_model.replica_valid)
    moved = (prev_rb != new_rb) & valid
    B = fresh_model.num_brokers
    placement_changed = np.zeros(B, bool)
    if moved.any():
        placement_changed[np.unique(prev_rb[moved])] = True
        placement_changed[np.unique(new_rb[moved])] = True
    lead_moved = (np.asarray(prev_model.replica_is_leader)
                  != np.asarray(fresh_model.replica_is_leader)) & valid
    if lead_moved.any():
        placement_changed[np.unique(new_rb[lead_moved])] = True
    # Dead/offline brokers always join the mask — healing moves must see
    # them even when their loads look unchanged.
    state_changed = (np.asarray(prev_model.broker_state)
                     != np.asarray(fresh_model.broker_state))
    changed = (load_changed | placement_changed | state_changed) \
        & np.asarray(fresh_model.broker_valid)
    return ModelDelta(changed_mask=changed, magnitude=magnitude,
                      num_changed=int(changed.sum()))
