"""Replica movement strategies.

Parity with the strategy SPI + implementations
(executor/strategy/ReplicaMovementStrategy.java and *.java): a strategy
orders a broker's pending inter-broker movement tasks; strategies compose
with ``chain`` (earlier strategies dominate, later ones break ties), and
the default chain ends with the by-execution-id base strategy so ordering
is always total and deterministic.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.executor.task import ExecutionTask


class ReplicaMovementStrategy:
    """SPI: smaller sort keys execute earlier."""

    name = "abstract"

    def sort_key(self, task: ExecutionTask, context: "StrategyContext"):
        raise NotImplementedError

    def chain(self, nxt: "ReplicaMovementStrategy") -> "ReplicaMovementStrategy":
        return _ChainedStrategy(self, nxt)

    def sorted_tasks(self, tasks: Sequence[ExecutionTask],
                     context: Optional["StrategyContext"] = None) -> List[ExecutionTask]:
        ctx = context or StrategyContext()
        final = self.chain(BaseReplicaMovementStrategy())
        return sorted(tasks, key=lambda t: final.sort_key(t, ctx))


class StrategyContext:
    """Cluster facts strategies consult (URP set, min-ISR info) — the
    reference passes a Cluster + StrategyOptions."""

    def __init__(self, under_replicated: Optional[Set[int]] = None,
                 under_min_isr: Optional[Set[int]] = None,
                 partitions_with_offline_replicas: Optional[Set[int]] = None):
        self.under_replicated = under_replicated or set()
        self.under_min_isr = under_min_isr or set()
        self.partitions_with_offline_replicas = partitions_with_offline_replicas or set()


class _ChainedStrategy(ReplicaMovementStrategy):
    def __init__(self, first: ReplicaMovementStrategy, second: ReplicaMovementStrategy):
        self._first = first
        self._second = second
        self.name = f"{first.name}+{second.name}"

    def sort_key(self, task, context):
        k1 = self._first.sort_key(task, context)
        k2 = self._second.sort_key(task, context)
        k1 = k1 if isinstance(k1, tuple) else (k1,)
        k2 = k2 if isinstance(k2, tuple) else (k2,)
        return k1 + k2


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """By execution id (BaseReplicaMovementStrategy.java) — the total-order
    fallback."""

    name = "base"

    def sort_key(self, task, context):
        return (task.execution_id,)


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Large partitions first (PrioritizeLargeReplicaMovementStrategy.java)."""

    name = "prioritize-large"

    def sort_key(self, task, context):
        return (-task.proposal.partition_size,)


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    """Small partitions first (PrioritizeSmallReplicaMovementStrategy.java)."""

    name = "prioritize-small"

    def sort_key(self, task, context):
        return (task.proposal.partition_size,)


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move partitions with no under-replicated state first
    (PostponeUrpReplicaMovementStrategy.java)."""

    name = "postpone-urp"

    def sort_key(self, task, context):
        return (1 if task.proposal.partition in context.under_replicated else 0,)


class PrioritizeMinIsrWithOfflineReplicasStrategy(ReplicaMovementStrategy):
    """(At/Under)MinISR partitions with offline replicas first
    (PrioritizeMinIsrWithOfflineReplicasStrategy.java)."""

    name = "prioritize-min-isr"

    def sort_key(self, task, context):
        p = task.proposal.partition
        urgent = (p in context.under_min_isr
                  and p in context.partitions_with_offline_replicas)
        return (0 if urgent else 1,)


STRATEGIES = {
    s.name: s for s in (
        BaseReplicaMovementStrategy(),
        PrioritizeLargeReplicaMovementStrategy(),
        PrioritizeSmallReplicaMovementStrategy(),
        PostponeUrpReplicaMovementStrategy(),
        PrioritizeMinIsrWithOfflineReplicasStrategy(),
    )
}


_BY_CLASS_NAME = {type(s).__name__: s for s in STRATEGIES.values()}


def resolve_strategy(names: Sequence[str]) -> ReplicaMovementStrategy:
    """Build a chained strategy from config names (ExecutorConfig
    default.replica.movement.strategies analogue).  Accepts short names
    ("prioritize-large"), class names, or fully-qualified class paths."""
    if not names:
        return BaseReplicaMovementStrategy()
    out: Optional[ReplicaMovementStrategy] = None
    for n in names:
        s = STRATEGIES.get(n) or _BY_CLASS_NAME.get(n.rsplit(".", 1)[-1])
        if s is None:
            raise ValueError(f"unknown replica movement strategy {n!r}")
        out = s if out is None else out.chain(s)
    return out
