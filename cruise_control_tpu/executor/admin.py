"""Cluster administration backend SPI + in-memory fake.

The reference mutates the cluster through Kafka AdminClient + ZooKeeper
(ExecutorUtils.scala:21 — /admin/reassign_partitions znode merges,
ExecutorAdminUtils.java — electLeaders/describeLogDirs,
ReplicationThrottleHelper.java — throttle configs).  Here every mutation
funnels through this ``ClusterAdmin`` SPI; production binds a Kafka admin
adapter at the edge, tests bind ``InMemoryClusterAdmin`` — the pure
in-memory fake cluster-state backend that replaces the reference's
embedded-Kafka harness (SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.monitor.metadata import (BrokerInfo, ClusterMetadata,
                                                 MetadataClient, PartitionInfo)

Tp = Tuple[str, int]


class TransientAdminError(RuntimeError):
    """A retryable admin-layer failure (network blip, controller handover,
    request timeout).  The executor's retry/backoff envelope retries these;
    anything else propagates."""


@dataclasses.dataclass
class ReassignmentRequest:
    tp: Tp
    new_replicas: Tuple[int, ...]  # preferred order, leader first


class ClusterAdmin:
    """SPI over the cluster's mutation + inspection surface."""

    def alter_partition_reassignments(self, requests: Sequence[ReassignmentRequest]) -> None:
        raise NotImplementedError

    def ongoing_reassignments(self) -> Set[Tp]:
        raise NotImplementedError

    def cancel_reassignments(self, tps: Optional[Sequence[Tp]] = None) -> None:
        """Cancel ongoing reassignments (force-stop path; the reference
        deletes the reassignment znode, Executor.java:1137-1139)."""
        raise NotImplementedError

    def elect_leaders(self, tps: Sequence[Tp]) -> None:
        """Preferred leader election (ExecutorUtils PLE path)."""
        raise NotImplementedError

    def alter_replica_logdirs(self, moves: Sequence[Tuple[Tp, int, str]]) -> None:
        """(tp, broker, target logdir) intra-broker moves."""
        raise NotImplementedError

    def set_replication_throttles(self, rate_bytes_per_sec: int,
                                  brokers: Sequence[int],
                                  throttled_replicas: Dict[str, List[str]]) -> None:
        raise NotImplementedError

    def clear_replication_throttles(self, brokers: Sequence[int],
                                    throttled_replicas: Dict[str, List[str]]) -> None:
        """Remove exactly the given throttle entries (and the rate on the
        given brokers when no entries remain), leaving operator-set throttle
        config untouched — ReplicationThrottleHelper's diff-based cleanup."""
        raise NotImplementedError

    def min_isr(self, topic: str) -> int:
        return 1

    def describe_logdirs(self) -> Dict[int, Dict[str, bool]]:
        """broker → {logdir → is_online} (ExecutorAdminUtils/DiskFailureDetector
        describeLogDirs path)."""
        return {}


class InMemoryClusterAdmin(ClusterAdmin):
    """Applies reassignments against a ``MetadataClient``-held metadata
    snapshot, completing each after ``latency_polls`` calls to
    ``ongoing_reassignments`` — modelling Kafka's asynchronous data movement
    so executor wait/poll loops and concurrency gates are actually
    exercised."""

    def __init__(self, metadata_client: MetadataClient, latency_polls: int = 1):
        self._md = metadata_client
        self._latency = max(int(latency_polls), 0)
        self._lock = threading.Lock()
        self._inflight: Dict[Tp, Tuple[ReassignmentRequest, int]] = {}
        self._logdir_moves: List[Tuple[Tp, int, str]] = []
        self.throttle_state: Dict[str, object] = {}
        self.throttle_history: List[Dict[str, object]] = []
        # broker → {logdir → online}; tests flip entries to simulate disk death.
        self.logdir_health: Dict[int, Dict[str, bool]] = {}

    @property
    def metadata_client(self) -> MetadataClient:
        """The metadata backend this admin mutates (resume harnesses build
        a fresh Executor against the same admin + metadata pair)."""
        return self._md

    # -- reassignment ------------------------------------------------------
    def alter_partition_reassignments(self, requests: Sequence[ReassignmentRequest]) -> None:
        with self._lock:
            cluster = self._md.cluster()
            known = {p.tp for p in cluster.partitions}
            for r in requests:
                if tuple(r.tp) in self._inflight:
                    raise RuntimeError(f"reassignment already in progress for {r.tp}")
                if tuple(r.tp) not in known:
                    raise ValueError(f"unknown partition {r.tp}")
                self._inflight[tuple(r.tp)] = (r, self._latency)

    def ongoing_reassignments(self) -> Set[Tp]:
        with self._lock:
            done: List[Tp] = []
            for tp, (req, remaining) in list(self._inflight.items()):
                if remaining <= 0:
                    self._apply(req)
                    done.append(tp)
                else:
                    self._inflight[tp] = (req, remaining - 1)
            for tp in done:
                del self._inflight[tp]
            return set(self._inflight)

    def _apply(self, req: ReassignmentRequest) -> None:
        cluster = self._md.cluster()
        parts = []
        for p in cluster.partitions:
            if p.tp == tuple(req.tp):
                leader = p.leader if p.leader in req.new_replicas else req.new_replicas[0]
                parts.append(dataclasses.replace(
                    p, replicas=tuple(req.new_replicas), leader=leader,
                    offline_replicas=tuple(b for b in p.offline_replicas
                                           if b in req.new_replicas)))
            else:
                parts.append(p)
        self._md.refresh(dataclasses.replace(cluster, partitions=tuple(parts)))

    def cancel_reassignments(self, tps: Optional[Sequence[Tp]] = None) -> None:
        with self._lock:
            if tps is None:
                self._inflight.clear()
            else:
                for tp in tps:
                    self._inflight.pop(tuple(tp), None)

    # -- leadership --------------------------------------------------------
    def elect_leaders(self, tps: Sequence[Tp]) -> None:
        cluster = self._md.cluster()
        want = {tuple(tp) for tp in tps}
        parts = []
        for p in cluster.partitions:
            if p.tp in want and p.replicas:
                parts.append(dataclasses.replace(p, leader=p.replicas[0]))
            else:
                parts.append(p)
        self._md.refresh(dataclasses.replace(cluster, partitions=tuple(parts)))

    # -- logdirs -----------------------------------------------------------
    def alter_replica_logdirs(self, moves: Sequence[Tuple[Tp, int, str]]) -> None:
        with self._lock:
            self._logdir_moves.extend(moves)

    @property
    def logdir_moves(self) -> List[Tuple[Tp, int, str]]:
        with self._lock:
            return list(self._logdir_moves)

    # -- throttles ---------------------------------------------------------
    def set_replication_throttles(self, rate_bytes_per_sec, brokers,
                                  throttled_replicas) -> None:
        state = self.throttle_state or {"rate": None, "brokers": set(),
                                        "replicas": {}}
        state["rate"] = rate_bytes_per_sec
        state["brokers"] = set(state["brokers"]) | set(brokers)
        for topic, entries in throttled_replicas.items():
            cur = set(state["replicas"].get(topic, ()))
            state["replicas"][topic] = cur | set(entries)
        self.throttle_state = state
        self.throttle_history.append({"rate": rate_bytes_per_sec,
                                      "brokers": sorted(brokers),
                                      "replicas": {t: sorted(e) for t, e in
                                                   throttled_replicas.items()}})

    def describe_logdirs(self) -> Dict[int, Dict[str, bool]]:
        if self.logdir_health:
            return {b: dict(d) for b, d in self.logdir_health.items()}
        return {b.broker_id: {ld: True for ld in b.logdirs}
                for b in self._md.cluster().brokers}

    def clear_replication_throttles(self, brokers, throttled_replicas) -> None:
        state = self.throttle_state
        if not state:
            return
        for topic, entries in throttled_replicas.items():
            cur = set(state["replicas"].get(topic, ()))
            cur -= set(entries)
            if cur:
                state["replicas"][topic] = cur
            else:
                state["replicas"].pop(topic, None)
        if not state["replicas"]:
            state["brokers"] = set(state["brokers"]) - set(brokers)
            if not state["brokers"]:
                self.throttle_state = {}


class SimulatedClusterAdmin(InMemoryClusterAdmin):
    """Byte-accurate fleet simulation under a virtual clock.

    ``InMemoryClusterAdmin`` completes every reassignment after a fixed
    number of polls — fine for exercising wait loops, useless for measuring
    time-to-balanced.  This subclass models the data plane: each
    reassignment must drain ``replica size × new destinations`` bytes at the
    replication-throttle rate, and a broker's rate is SHARED across its
    concurrent inbound transfers (the bottleneck broker paces each
    transfer), so concurrency limits and the adjuster visibly change the
    wall-to-balanced outcome.  The virtual clock advances ``tick_ms`` per
    ``ongoing_reassignments()`` poll; executors built with
    ``clock_ms=admin.now_ms`` record ledger time in fleet seconds.  Scales
    to the ROADMAP's 7k-broker fleet: state is one dict entry per in-flight
    transfer, not per broker.
    """

    def __init__(self, metadata_client: MetadataClient,
                 bytes_by_tp: Optional[Dict[Tp, int]] = None,
                 tick_ms: int = 1000,
                 rate_bytes_per_sec: float = 50_000_000.0):
        super().__init__(metadata_client, latency_polls=0)
        self._bytes_by_tp: Dict[Tp, int] = dict(bytes_by_tp or {})
        self._tick_ms = max(1, int(tick_ms))
        self._rate = float(rate_bytes_per_sec)
        self._now_ms = 0
        # tp → [remaining_bytes, destination brokers receiving data]
        self._transfers: Dict[Tp, list] = {}

    def now_ms(self) -> int:
        return self._now_ms

    @property
    def rate_bytes_per_sec(self) -> float:
        return self._rate

    # -- reassignment ------------------------------------------------------
    def alter_partition_reassignments(self, requests: Sequence[ReassignmentRequest]) -> None:
        with self._lock:
            cluster = self._md.cluster()
            current = {p.tp: set(p.replicas) for p in cluster.partitions}
            for r in requests:
                tp = tuple(r.tp)
                if tp in self._inflight:
                    raise RuntimeError(f"reassignment already in progress for {r.tp}")
                if tp not in current:
                    raise ValueError(f"unknown partition {r.tp}")
                dests = frozenset(b for b in r.new_replicas
                                  if b not in current[tp])
                size = self._bytes_by_tp.get(tp, 0) * len(dests)
                self._inflight[tp] = (r, 0)
                self._transfers[tp] = [float(size), dests]

    def ongoing_reassignments(self) -> Set[Tp]:
        with self._lock:
            self._now_ms += self._tick_ms
            # Per-destination-broker inbound transfer counts: a broker
            # receiving N partitions splits its throttle rate N ways.
            inbound: Dict[int, int] = {}
            for _remaining, dests in self._transfers.values():
                for b in dests:
                    inbound[b] = inbound.get(b, 0) + 1
            tick_s = self._tick_ms / 1000.0
            done: List[Tp] = []
            for tp, entry in self._transfers.items():
                remaining, dests = entry
                if dests:
                    bottleneck = max(inbound[b] for b in dests)
                    remaining -= self._rate / bottleneck * tick_s
                    entry[0] = remaining
                if not dests or remaining <= 0:
                    done.append(tp)
            for tp in done:
                req, _ = self._inflight.pop(tp)
                del self._transfers[tp]
                self._apply(req)
            return set(self._inflight)

    def cancel_reassignments(self, tps: Optional[Sequence[Tp]] = None) -> None:
        with self._lock:
            if tps is None:
                self._inflight.clear()
                self._transfers.clear()
            else:
                for tp in tps:
                    self._inflight.pop(tuple(tp), None)
                    self._transfers.pop(tuple(tp), None)

    # -- throttles ---------------------------------------------------------
    def set_replication_throttles(self, rate_bytes_per_sec, brokers,
                                  throttled_replicas) -> None:
        super().set_replication_throttles(rate_bytes_per_sec, brokers,
                                          throttled_replicas)
        # Adopt the executor's throttle as the simulation's transfer rate so
        # per-replica transfer times derive from size + throttle.
        self._rate = float(rate_bytes_per_sec)
