"""Simulated-fleet execution harness.

Bridges the tensor world to the executor's cluster protocol so a REAL
proposal plan (e.g. a mid-rung optimization's diff) can be executed against
``SimulatedClusterAdmin``'s byte-accurate virtual fleet — the measurement
rig behind ``bench.py --execute``, ``dump_sensors``'s executor exercise,
and the ledger tests.  Everything here is host-side glue: one device fetch
pulls the placement arrays, after which metadata synthesis is pure Python.

The seam invariants (matching ``api.facade``): brokers in the synthesized
metadata are the model's dense indices 0..B-1 (so proposals from
``proposals.diff`` need no renumbering), and ``partition_names[dense_pid]``
maps the proposal's dense partition id to its ``(topic, partition)``.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import numpy as np

from cruise_control_tpu.analyzer.proposals import (ExecutionProposal,
                                                   ReplicaPlacement)
from cruise_control_tpu.executor.admin import (ReassignmentRequest,
                                               SimulatedClusterAdmin,
                                               TransientAdminError, Tp)
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.executor.task_manager import ConcurrencyLimits
from cruise_control_tpu.monitor.metadata import (BrokerInfo, ClusterMetadata,
                                                 MetadataClient, PartitionInfo)


def metadata_from_model(model) -> Tuple[MetadataClient, List[Tp]]:
    """Synthesize cluster metadata from a tensor model's placement.

    Topics are named ``t<tid>``; partition numbers count up per topic in
    dense-partition-id order; replica lists are leader-first (the executor's
    completion check compares replica SETS, but leader-first keeps the
    synthesized metadata shaped like the reference's).
    Returns (metadata client, dense partition id → (topic, partition)).
    """
    (pr, rb, lead, ptopic, pvalid, bvalid, brack) = jax.device_get((
        model.partition_replicas, model.replica_broker,
        model.replica_is_leader, model.partition_topic,
        model.partition_valid, model.broker_valid, model.broker_rack))
    brokers = tuple(BrokerInfo(int(b), rack=f"rack{int(brack[b])}",
                               host=f"host{int(b)}")
                    for b in range(model.num_brokers) if bvalid[b])
    parts: List[PartitionInfo] = []
    partition_names: List[Tp] = []
    next_index: Dict[int, int] = {}
    for p in range(pr.shape[0]):
        tid = int(ptopic[p])
        topic = f"t{tid}"
        idx = next_index.get(tid, 0)
        next_index[tid] = idx + 1
        partition_names.append((topic, idx))
        if not pvalid[p]:
            continue
        slots = pr[p][pr[p] >= 0]
        if slots.size == 0:
            continue
        placements = [int(rb[r]) for r in slots]
        leader_pos = next((i for i, r in enumerate(slots) if lead[r]), 0)
        if leader_pos:
            placements = [placements[leader_pos]] + \
                placements[:leader_pos] + placements[leader_pos + 1:]
        parts.append(PartitionInfo(topic, idx, leader=placements[0],
                                   replicas=tuple(placements)))
    mc = MetadataClient(ClusterMetadata(brokers=brokers,
                                        partitions=tuple(parts)))
    return mc, partition_names


def proposal_bytes_by_tp(proposals: Sequence[ExecutionProposal],
                         partition_names: Sequence[Tp]) -> Dict[Tp, int]:
    """Per-partition transfer size for the simulated admin (bytes; the
    proposal's partition_size is MB)."""
    return {tuple(partition_names[p.partition]): int(p.partition_size * 1e6)
            for p in proposals}


def sample_move_proposals(model, moves: int = 2,
                          leadership: int = 1) -> List[ExecutionProposal]:
    """Small synthetic proposal set for exercising the executor without an
    optimizer run: ``moves`` replica relocations (last replica of the first
    eligible partitions moved to the lowest absent broker) plus
    ``leadership`` leader flips (replica order reversed) on the following
    partitions.  Placements reflect the model's current state, so they
    execute cleanly against ``metadata_from_model``'s metadata."""
    (pr, rb, rd, lead, ptopic, pvalid, bvalid) = jax.device_get((
        model.partition_replicas, model.replica_broker, model.replica_disk,
        model.replica_is_leader, model.partition_topic,
        model.partition_valid, model.broker_valid))
    alive = [b for b in range(model.num_brokers) if bvalid[b]]
    out: List[ExecutionProposal] = []
    want_moves, want_leads = moves, leadership
    for p in range(pr.shape[0]):
        if want_moves <= 0 and want_leads <= 0:
            break
        if not pvalid[p]:
            continue
        slots = pr[p][pr[p] >= 0]
        if slots.size == 0:
            continue
        placements = [ReplicaPlacement(int(rb[r]), int(rd[r])) for r in slots]
        leader_pos = next((i for i, r in enumerate(slots) if lead[r]), 0)
        if leader_pos:
            placements = [placements[leader_pos]] + \
                placements[:leader_pos] + placements[leader_pos + 1:]
        old = tuple(placements)
        size = 100.0
        if want_moves > 0:
            used = {pl.broker for pl in old}
            dest = next((b for b in alive if b not in used), None)
            if dest is None:
                continue
            new = old[:-1] + (ReplicaPlacement(dest, old[-1].disk),)
            want_moves -= 1
        elif len(old) > 1:
            new = tuple(reversed(old))
            want_leads -= 1
        else:
            continue
        out.append(ExecutionProposal(
            partition=p, topic=int(ptopic[p]), partition_size=size,
            old_leader=old[0], old_replicas=old, new_replicas=new))
    return out


def synthetic_health_metrics(stressed_polls=range(6, 12)):
    """Deterministic broker-health feed for the concurrency adjuster: deep
    request queues during ``stressed_polls`` (forcing halving), healthy
    otherwise (doubling back toward the cap) — so simulated executions
    exercise both adjuster directions reproducibly."""
    calls = {"n": 0}

    def fn() -> Dict[int, Dict[str, float]]:
        n = calls["n"]
        calls["n"] += 1
        stressed = n in stressed_polls
        return {0: {
            "BROKER_REQUEST_QUEUE_SIZE": 5000.0 if stressed else 10.0,
            "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT": 0.9,
        }}
    return fn


@dataclasses.dataclass
class FaultInjection:
    """Knobs for :class:`ChaosClusterAdmin`.  All randomness is seeded, so
    a given (faults, plan) pair replays identically — the chaos tests and
    the bench's kill/resume legs are deterministic.

    - ``transient_failure_rate``: probability that any admin mutation
      (reassign / elect / logdir move) raises :class:`TransientAdminError`.
    - ``failing_broker``: submissions whose destinations include this broker
      ALWAYS raise (models a persistently unreachable broker — drives the
      retry envelope to give-up and the circuit breaker to open).
    - ``latency_spike_rate`` / ``latency_spike_factor``: per-poll chance an
      in-flight transfer's remaining bytes inflate by the factor (a stuck
      or rate-starved task, visible to stuck-partition detection).
    - ``broker_death_ms`` + ``dead_broker``: at the given virtual time the
      broker drops from the alive set, so in-flight moves targeting it hit
      the executor's dead-broker path.
    """

    transient_failure_rate: float = 0.0
    failing_broker: Optional[int] = None
    latency_spike_rate: float = 0.0
    latency_spike_factor: float = 4.0
    broker_death_ms: Optional[int] = None
    dead_broker: Optional[int] = None
    seed: int = 0


class ChaosClusterAdmin(SimulatedClusterAdmin):
    """``SimulatedClusterAdmin`` with seeded fault injection.  ``injected``
    counts what actually fired, so tests can assert the fault surface was
    exercised rather than silently dormant."""

    def __init__(self, metadata_client: MetadataClient,
                 bytes_by_tp: Optional[Dict[Tp, int]] = None,
                 tick_ms: int = 1000,
                 rate_bytes_per_sec: float = 50_000_000.0,
                 faults: Optional[FaultInjection] = None):
        super().__init__(metadata_client, bytes_by_tp, tick_ms=tick_ms,
                         rate_bytes_per_sec=rate_bytes_per_sec)
        self._faults = faults or FaultInjection()
        self._rng = random.Random(self._faults.seed)
        self._broker_killed = False
        self._spiked: set = set()
        self.injected = {"transient": 0, "failing_broker": 0,
                         "latency_spikes": 0, "broker_deaths": 0}

    def _maybe_transient(self) -> None:
        f = self._faults
        if f.transient_failure_rate > 0 and \
                self._rng.random() < f.transient_failure_rate:
            self.injected["transient"] += 1
            raise TransientAdminError("injected transient admin failure")

    # -- mutation surface (fault-injected) ----------------------------------
    def alter_partition_reassignments(self, requests: Sequence[ReassignmentRequest]) -> None:
        f = self._faults
        if f.failing_broker is not None and any(
                f.failing_broker in r.new_replicas for r in requests):
            self.injected["failing_broker"] += 1
            raise TransientAdminError(
                f"injected failure: broker {f.failing_broker} unreachable")
        self._maybe_transient()
        super().alter_partition_reassignments(requests)

    def elect_leaders(self, tps: Sequence[Tp]) -> None:
        self._maybe_transient()
        super().elect_leaders(tps)

    def alter_replica_logdirs(self, moves: Sequence[Tuple[Tp, int, str]]) -> None:
        self._maybe_transient()
        super().alter_replica_logdirs(moves)

    # -- data plane (spikes + broker death ride the poll tick) ---------------
    def ongoing_reassignments(self) -> Set[Tp]:
        f = self._faults
        if f.latency_spike_rate > 0:
            with self._lock:
                for tp, entry in self._transfers.items():
                    # At most one spike per transfer: a spike models the
                    # task getting stuck ONCE, not compounding divergence.
                    if entry[0] > 0 and entry[1] and tp not in self._spiked \
                            and self._rng.random() < f.latency_spike_rate:
                        entry[0] *= f.latency_spike_factor
                        self._spiked.add(tp)
                        self.injected["latency_spikes"] += 1
        out = super().ongoing_reassignments()
        if f.broker_death_ms is not None and f.dead_broker is not None \
                and not self._broker_killed and self._now_ms >= f.broker_death_ms:
            self._kill_broker(f.dead_broker)
        return out

    def _kill_broker(self, broker: int) -> None:
        cluster = self._md.cluster()
        self._md.refresh(dataclasses.replace(cluster, brokers=tuple(
            dataclasses.replace(b, is_alive=False)
            if b.broker_id == broker else b for b in cluster.brokers)))
        self._broker_killed = True
        self.injected["broker_deaths"] += 1


def build_simulated_execution(model_before,
                              proposals: Sequence[ExecutionProposal],
                              *,
                              model_after=None,
                              goal_names: Optional[Sequence[str]] = None,
                              constraint=None,
                              balancedness_weights: Tuple[float, float] = (1.1, 1.5),
                              tick_ms: int = 1000,
                              rate_bytes_per_sec: float = 50_000_000.0,
                              limits: Optional[ConcurrencyLimits] = None,
                              ledger_enabled: bool = True,
                              faults: Optional[FaultInjection] = None):
    """Build the (executor, admin, partition_names, scorer) rig for a
    simulated execution without running it — crash/resume flows need the
    executor and admin to SURVIVE the (simulated) process death, so the
    harness hands them out before the run starts."""
    mc, partition_names = metadata_from_model(model_before)
    admin_cls = ChaosClusterAdmin if faults is not None else SimulatedClusterAdmin
    kwargs = dict(tick_ms=tick_ms, rate_bytes_per_sec=rate_bytes_per_sec)
    if faults is not None:
        kwargs["faults"] = faults
    admin = admin_cls(mc, proposal_bytes_by_tp(proposals, partition_names),
                      **kwargs)
    scorer = None
    if model_after is not None and goal_names:
        from cruise_control_tpu.analyzer.optimizer import PlacementScorer
        scorer = PlacementScorer(model_before, model_after, goal_names,
                                 constraint, *balancedness_weights)
    ex = Executor(admin, mc, limits=limits,
                  clock_ms=admin.now_ms,
                  ledger_enabled=ledger_enabled,
                  concurrency_adjuster_interval_ms=0,
                  admin_retry_backoff_s=0.0)
    return ex, admin, partition_names, scorer


def run_simulated_execution(model_before, proposals: Sequence[ExecutionProposal],
                            *,
                            model_after=None,
                            goal_names: Optional[Sequence[str]] = None,
                            constraint=None,
                            balancedness_weights: Tuple[float, float] = (1.1, 1.5),
                            tick_ms: int = 1000,
                            rate_bytes_per_sec: float = 50_000_000.0,
                            limits: Optional[ConcurrencyLimits] = None,
                            adjuster_churn: bool = True,
                            ledger_enabled: bool = True,
                            max_polls: int = 200_000,
                            faults: Optional[FaultInjection] = None,
                            journal_path: Optional[str] = None,
                            replanner=None,
                            replan_interval_polls: int = 0,
                            crash_after_polls: Optional[int] = None):
    """Execute ``proposals`` against a simulated fleet derived from
    ``model_before``.  With ``model_after`` + ``goal_names``, a
    ``PlacementScorer`` rides along so the ledger records the
    balancedness-over-time curve.  Returns ``(result, executor, admin)`` —
    the ledger is ``executor.progress(verbose=True)``; wall-to-balanced is
    fleet time (``admin.now_ms()``), not host time.

    ``faults`` swaps in :class:`ChaosClusterAdmin`; ``journal_path`` /
    ``replanner`` / ``replan_interval_polls`` / ``crash_after_polls`` pass
    through to :meth:`Executor.execute_proposals` (a ``crash_after_polls``
    run raises :class:`SimulatedCrash` — use
    :func:`build_simulated_execution` when you need the executor afterwards
    to ``resume()``)."""
    ex, admin, partition_names, scorer = build_simulated_execution(
        model_before, proposals, model_after=model_after,
        goal_names=goal_names, constraint=constraint,
        balancedness_weights=balancedness_weights, tick_ms=tick_ms,
        rate_bytes_per_sec=rate_bytes_per_sec, limits=limits,
        ledger_enabled=ledger_enabled, faults=faults)
    result = ex.execute_proposals(
        proposals, partition_names, max_polls=max_polls, poll_interval_s=0.0,
        replication_throttle=int(rate_bytes_per_sec),
        concurrency_adjust_metrics=(synthetic_health_metrics()
                                    if adjuster_churn else None),
        balancedness_scorer=scorer,
        replanner=replanner, replan_interval_polls=replan_interval_polls,
        journal_path=journal_path, crash_after_polls=crash_after_polls)
    return result, ex, admin
