"""Topic min.insync.replicas cache + (At/Under)MinISR pressure check.

Parity with ``TopicMinIsrCache`` (common/TopicMinIsrCache.java) and the
ConcurrencyAdjuster's MinISR gate (Executor.java:335-447 halves movement
concurrency while any partition sits at/under its topic's min ISR): topic
configs are fetched through the ClusterAdmin with a TTL so the wait loop
doesn't hammer DescribeConfigs every poll.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple

from cruise_control_tpu.monitor.metadata import ClusterMetadata


class TopicMinIsrCache:
    def __init__(self, admin, ttl_ms: int = 300_000):
        self._admin = admin
        self._ttl_s = ttl_ms / 1000.0
        self._lock = threading.Lock()
        self._cache: Dict[str, Tuple[int, float]] = {}  # topic → (min_isr, at)

    def min_isr(self, topic: str) -> int:
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(topic)
            if hit is not None and now - hit[1] < self._ttl_s:
                return hit[0]
        try:
            value = int(self._admin.min_isr(topic))
        except Exception:  # noqa: BLE001 — config fetch failure: assume 1
            value = 1
        with self._lock:
            self._cache[topic] = (value, now)
        return value


def min_isr_pressure(cluster: ClusterMetadata, cache: TopicMinIsrCache) -> bool:
    """True when any partition is under — or, for partitions whose RF leaves
    headroom, at — its topic's min ISR; the adjuster then halves concurrency
    instead of doubling it.  A partition whose RF equals min ISR (e.g. any
    RF=1 topic) is *always* at-min and must not count as standing pressure
    (the reference's AtMinIsr set excludes nothing less)."""
    alive = set(cluster.alive_broker_ids())
    for p in cluster.partitions:
        in_sync = sum(1 for b in p.replicas
                      if b in alive and b not in p.offline_replicas)
        min_isr = cache.min_isr(p.topic)
        if in_sync < min_isr:
            return True
        if len(p.replicas) > min_isr and in_sync <= min_isr:
            return True
    return False
