"""Execution tasks and their state machine.

Parity with ``ExecutionTask``/``ExecutionTaskState``
(executor/ExecutionTask.java:41, ExecutionTaskState.java): a task wraps one
``ExecutionProposal`` with an execution id and a type, and walks
PENDING → IN_PROGRESS → {COMPLETED | ABORTING → ABORTED | DEAD}.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Callable, Optional

from cruise_control_tpu.analyzer.proposals import ExecutionProposal

#: ``ExecutionProposal.partition_size`` is megabytes; ledger accounting is
#: bytes so throttle rates (bytes/sec) divide without unit juggling.
_MB = 1_000_000


class TaskType(enum.Enum):
    """executor/ExecutionTask.TaskType."""

    INTER_BROKER_REPLICA_ACTION = "inter_broker_replica_action"
    INTRA_BROKER_REPLICA_ACTION = "intra_broker_replica_action"
    LEADER_ACTION = "leader_action"


class TaskState(enum.Enum):
    """executor/ExecutionTaskState.java."""

    PENDING = "pending"
    IN_PROGRESS = "in_progress"
    ABORTING = "aborting"
    ABORTED = "aborted"
    DEAD = "dead"
    COMPLETED = "completed"


_VALID_TRANSITIONS = {
    # PENDING → ABORTED is the cancellation edge: a task dropped before
    # submission (replan cancel-what-changed, force-stop finalization, or a
    # circuit-broken destination).  It never carried in-flight bytes, which
    # the ledger's observe() distinguishes by the old state.
    TaskState.PENDING: {TaskState.IN_PROGRESS, TaskState.ABORTED},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD, TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.ABORTED: set(),
    TaskState.DEAD: set(),
    TaskState.COMPLETED: set(),
}


@dataclasses.dataclass
class ExecutionTask:
    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: int = -1
    end_time_ms: int = -1
    alert_time_ms: int = -1
    # Lifecycle observer (the execution ledger's hook): called after every
    # state transition as observer(task, old_state, new_state, now_ms).
    # Excluded from equality/repr — purely observational.
    observer: Optional[Callable[["ExecutionTask", TaskState, TaskState, int],
                                None]] = dataclasses.field(
        default=None, repr=False, compare=False)

    def _transition(self, to: TaskState, now_ms: Optional[int] = None) -> None:
        if to not in _VALID_TRANSITIONS[self.state]:
            raise ValueError(f"illegal task transition {self.state} -> {to} "
                             f"(task {self.execution_id})")
        old = self.state
        self.state = to
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        if to == TaskState.IN_PROGRESS:
            self.start_time_ms = now
        elif to in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD):
            self.end_time_ms = now
        if self.observer is not None:
            self.observer(self, old, to, now)

    def in_progress(self, now_ms: Optional[int] = None) -> None:
        self._transition(TaskState.IN_PROGRESS, now_ms)

    def completed(self, now_ms: Optional[int] = None) -> None:
        self._transition(TaskState.COMPLETED, now_ms)

    def aborting(self, now_ms: Optional[int] = None) -> None:
        self._transition(TaskState.ABORTING, now_ms)

    def aborted(self, now_ms: Optional[int] = None) -> None:
        self._transition(TaskState.ABORTED, now_ms)

    def cancel(self, now_ms: Optional[int] = None) -> None:
        """Abort a task that never started (PENDING → ABORTED)."""
        self._transition(TaskState.ABORTED, now_ms)

    def kill(self, now_ms: Optional[int] = None) -> None:
        self._transition(TaskState.DEAD, now_ms)

    @property
    def is_active(self) -> bool:
        return self.state in (TaskState.PENDING, TaskState.IN_PROGRESS,
                              TaskState.ABORTING)

    @property
    def bytes_to_move(self) -> int:
        """Data volume this task transfers, in bytes.

        Inter-broker: the partition's size lands once per NEW destination
        broker (existing replicas don't re-copy).  Intra-broker: once per
        disk move.  Leadership: metadata only, zero bytes.
        """
        p = self.proposal
        if self.task_type == TaskType.LEADER_ACTION:
            return 0
        if self.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION:
            n = len(p._intra_broker_moves())
        else:
            n = len(p.replicas_to_add)
        return int(p.partition_size * _MB) * n

    def brokers_involved(self):
        """Brokers this task touches (source + destination sets)."""
        p = self.proposal
        if self.task_type == TaskType.LEADER_ACTION:
            return {p.old_leader.broker, p.new_leader.broker}
        out = set(p.replicas_to_add) | set(p.replicas_to_remove)
        if self.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION:
            out |= {b for b, _, _ in p._intra_broker_moves()}
        return out

    def to_dict(self) -> dict:
        return {
            "executionId": self.execution_id,
            "type": self.task_type.value,
            "state": self.state.value,
            "proposal": self.proposal.to_dict(),
        }
