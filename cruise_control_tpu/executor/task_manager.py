"""In-flight task admission control.

Parity with ``ExecutionTaskManager`` (executor/ExecutionTaskManager.java:37)
+ ``ExecutionTaskTracker`` (ExecutionTaskTracker.java): tracks per-broker
in-flight movement counts against per-type concurrency limits and hands out
the next executable tasks; buckets tasks by state for gauges/state
reporting; enforces the cluster-wide movement cap
(MAX_NUM_CLUSTER_MOVEMENTS_CONFIG).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Set

from cruise_control_tpu.executor.planner import ExecutionPlan
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType


class ConcurrencyType(enum.Enum):
    """executor/ConcurrencyType.java."""

    INTER_BROKER_REPLICA = "inter_broker_replica"
    INTRA_BROKER_REPLICA = "intra_broker_replica"
    LEADERSHIP = "leadership"


@dataclasses.dataclass
class ConcurrencyLimits:
    """Per-broker movement caps (ExecutorConfig defaults:
    num.concurrent.partition.movements.per.broker=10 etc.)."""

    inter_broker_per_broker: int = 10
    intra_broker_per_broker: int = 2
    leadership_cluster: int = 1000
    max_cluster_movements: int = 1250
    # max.num.cluster.partition.movements: cluster-wide cap on in-flight
    # inter-broker partition movements specifically (max.num.cluster.movements
    # caps ALL in-flight work, leadership included).
    max_cluster_partition_movements: int = 1250

    def for_type(self, t: ConcurrencyType) -> int:
        if t == ConcurrencyType.INTER_BROKER_REPLICA:
            return self.inter_broker_per_broker
        if t == ConcurrencyType.INTRA_BROKER_REPLICA:
            return self.intra_broker_per_broker
        return self.leadership_cluster


class ExecutionTaskManager:
    def __init__(self, plan: ExecutionPlan, limits: Optional[ConcurrencyLimits] = None):
        self._plan = plan
        self._limits = limits or ConcurrencyLimits()
        self._inflight_by_broker: Dict[int, int] = {}
        self._inflight: Set[int] = set()

    @property
    def limits(self) -> ConcurrencyLimits:
        return self._limits

    def set_limits(self, limits: ConcurrencyLimits) -> None:
        """Dynamic concurrency adjustment (ConcurrencyAdjuster hook)."""
        self._limits = limits

    def inflight_by_broker(self) -> Dict[int, int]:
        """Snapshot of per-broker in-flight movement counts (ledger/gauge
        surface; brokers with zero in-flight are omitted)."""
        return {b: n for b, n in self._inflight_by_broker.items() if n > 0}

    # -- admission ---------------------------------------------------------
    def next_inter_broker_tasks(self) -> List[ExecutionTask]:
        """Next executable inter-broker movements: walk each broker's
        strategy-ordered list, admit a task when every involved broker has
        in-flight headroom (ExecutionTaskManager.
        getInterBrokerReplicaMovementTasks semantics)."""
        cap = self._limits.inter_broker_per_broker
        out: List[ExecutionTask] = []
        total_active = len(self._inflight)
        partition_cap = min(self._limits.max_cluster_movements,
                            self._limits.max_cluster_partition_movements)
        for task in self._plan.inter_broker_tasks:
            if total_active + len(out) >= partition_cap:
                break
            if task.state != TaskState.PENDING or task.execution_id in self._inflight:
                continue
            brokers = task.brokers_involved()
            if all(self._inflight_by_broker.get(b, 0) < cap for b in brokers):
                out.append(task)
                for b in brokers:
                    self._inflight_by_broker[b] = self._inflight_by_broker.get(b, 0) + 1
                self._inflight.add(task.execution_id)
        return out

    def next_intra_broker_tasks(self) -> List[ExecutionTask]:
        cap = self._limits.intra_broker_per_broker
        out: List[ExecutionTask] = []
        for task in self._plan.intra_broker_tasks:
            if task.state != TaskState.PENDING or task.execution_id in self._inflight:
                continue
            brokers = task.brokers_involved()
            if all(self._inflight_by_broker.get(b, 0) < cap for b in brokers):
                out.append(task)
                for b in brokers:
                    self._inflight_by_broker[b] = self._inflight_by_broker.get(b, 0) + 1
                self._inflight.add(task.execution_id)
        return out

    def next_leadership_tasks(self) -> List[ExecutionTask]:
        cap = min(self._limits.leadership_cluster,
                  max(0, self._limits.max_cluster_movements - len(self._inflight)))
        out: List[ExecutionTask] = []
        for task in self._plan.leadership_tasks:
            if len(out) >= cap:
                break
            if task.state == TaskState.PENDING and task.execution_id not in self._inflight:
                out.append(task)
                self._inflight.add(task.execution_id)
        return out

    def finished(self, task: ExecutionTask) -> None:
        """Release in-flight accounting once a task reaches a terminal state."""
        if task.execution_id in self._inflight:
            self._inflight.discard(task.execution_id)
            if task.task_type != TaskType.LEADER_ACTION:
                for b in task.brokers_involved():
                    n = self._inflight_by_broker.get(b, 0)
                    if n > 0:
                        self._inflight_by_broker[b] = n - 1

    # -- state reporting ---------------------------------------------------
    def tasks_by_state(self) -> Dict[TaskState, List[ExecutionTask]]:
        buckets: Dict[TaskState, List[ExecutionTask]] = {s: [] for s in TaskState}
        for t in (self._plan.inter_broker_tasks + self._plan.intra_broker_tasks
                  + self._plan.leadership_tasks):
            buckets[t.state].append(t)
        return buckets

    def counts(self) -> Dict[str, int]:
        return {s.value: len(ts) for s, ts in self.tasks_by_state().items()}

    @property
    def all_done(self) -> bool:
        return all(not t.is_active for t in
                   (self._plan.inter_broker_tasks + self._plan.intra_broker_tasks
                    + self._plan.leadership_tasks))
