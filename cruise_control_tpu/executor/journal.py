"""Execution journal: crash-exact resume for in-flight executions.

The `sharded_fixpoint` resume pattern applied to the executor: the live
execution appends its full mutable state to a sidecar JSONL file — the plan
(proposals + task order), every task transition, concurrency-limit changes,
replan patches, phase markers, and one line per ledger poll — and flushes
once per poll.  ``Executor.resume()`` replays the journal through a fresh
``ExecutionTaskManager`` + ``ExecutionLedger`` and continues mid-phase: the
replayed ledger is rebuilt by driving the *same* observer/poll code paths
with the recorded clock, so counts, bytes, landed sets, stride-sampled
checkpoints, and phase records come out bit-identical to the live run's at
the crash point.

Line kinds (one JSON object per line):

- ``header``  — version, partition names, limits, throttle, poll budget,
  start clock.  Always the first line.
- ``task``    — one per planned task, in plan (strategy) order:
  execution id, type, full proposal.
- ``event``   — a task transition (id, from, to, tMs).
- ``poll``    — one ledger poll (cumulative count + clock); the flush point.
- ``phase`` / ``phase_end`` — phase cursor.
- ``limits``  — a concurrency-adjuster change.
- ``replan``  — a live replan patch: tasks it ADDED (cancellations arrive
  as ordinary PENDING→ABORTED event lines) plus cancelled/kept counts.

Crash semantics: a torn final line is the normal signature of a kill and is
ignored; a corrupt header or mid-file garbage raises :class:`JournalError`
(the caller falls back to a clean abort).  Everything here is host-side
Python — journal writes never touch the device.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.analyzer.proposals import (ExecutionProposal,
                                                   ReplicaPlacement)
from cruise_control_tpu.executor.ledger import ExecutionLedger
from cruise_control_tpu.executor.planner import ExecutionPlan
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType
from cruise_control_tpu.executor.task_manager import (ConcurrencyLimits,
                                                      ExecutionTaskManager)

JOURNAL_VERSION = 1


class JournalError(RuntimeError):
    """Unrecoverable journal corruption (missing/garbled header or mid-file
    garbage) — resume must fall back to a clean abort."""


# -- proposal (de)serialization ----------------------------------------------

def _placement_to_json(p: ReplicaPlacement) -> List[int]:
    return [int(p.broker), int(p.disk)]


def proposal_to_json(p: ExecutionProposal) -> dict:
    return {
        "p": int(p.partition),
        "t": int(p.topic),
        "sz": float(p.partition_size),
        "ol": _placement_to_json(p.old_leader),
        "or": [_placement_to_json(x) for x in p.old_replicas],
        "nr": [_placement_to_json(x) for x in p.new_replicas],
    }


def proposal_from_json(d: dict) -> ExecutionProposal:
    return ExecutionProposal(
        partition=int(d["p"]), topic=int(d["t"]), partition_size=float(d["sz"]),
        old_leader=ReplicaPlacement(*d["ol"]),
        old_replicas=tuple(ReplicaPlacement(*x) for x in d["or"]),
        new_replicas=tuple(ReplicaPlacement(*x) for x in d["nr"]))


def _limits_to_json(limits: ConcurrencyLimits) -> dict:
    return dataclasses.asdict(limits)


def _limits_from_json(d: dict) -> ConcurrencyLimits:
    return ConcurrencyLimits(**d)


def _task_to_json(t: ExecutionTask) -> dict:
    return {"kind": "task", "id": t.execution_id, "type": t.task_type.value,
            "proposal": proposal_to_json(t.proposal)}


def _task_from_json(d: dict) -> ExecutionTask:
    return ExecutionTask(int(d["id"]), proposal_from_json(d["proposal"]),
                         TaskType(d["type"]))


# -- writer -------------------------------------------------------------------

class ExecutionJournal:
    """Append-only JSONL writer for one execution.  ``start()`` writes the
    header + plan; transition events buffer and hit the disk at the next
    ``poll()`` flush (so journal I/O amortizes to one small write + flush
    per executor wait-loop iteration)."""

    def __init__(self, path: str, append: bool = False):
        self.path = path
        self._f = open(path, "a" if append else "w", encoding="utf-8")

    def _line(self, obj: dict) -> None:
        self._f.write(json.dumps(obj, separators=(",", ":")) + "\n")

    def start(self, plan: ExecutionPlan, partition_names: Sequence[Tuple[str, int]],
              limits: ConcurrencyLimits, max_polls: int,
              replication_throttle: Optional[int], started_ms: int) -> None:
        self._line({"kind": "header", "version": JOURNAL_VERSION,
                    "partitionNames": [[t, i] for t, i in partition_names],
                    "limits": _limits_to_json(limits),
                    "maxPolls": int(max_polls),
                    "replicationThrottle": replication_throttle,
                    "startedMs": int(started_ms)})
        for t in (plan.inter_broker_tasks + plan.intra_broker_tasks
                  + plan.leadership_tasks):
            self._line(_task_to_json(t))
        self.flush()

    def event(self, task: ExecutionTask, old_state: TaskState,
              new_state: TaskState, now_ms: int) -> None:
        self._line({"kind": "event", "id": task.execution_id,
                    "from": old_state.value, "to": new_state.value,
                    "tMs": int(now_ms)})

    def poll(self, t_ms: int) -> None:
        self._line({"kind": "poll", "tMs": int(t_ms)})
        self.flush()

    def phase(self, name: str, t_ms: int) -> None:
        self._line({"kind": "phase", "phase": name, "tMs": int(t_ms)})
        self.flush()

    def phase_end(self, name: str, t_ms: int, polls: int, batches: int) -> None:
        self._line({"kind": "phase_end", "phase": name, "tMs": int(t_ms),
                    "polls": int(polls), "batches": int(batches)})
        self.flush()

    def limits(self, limits: ConcurrencyLimits) -> None:
        self._line({"kind": "limits", "limits": _limits_to_json(limits)})

    def replan(self, added: Sequence[ExecutionTask], cancelled: int,
               kept: int, t_ms: int) -> None:
        self._line({"kind": "replan", "tMs": int(t_ms),
                    "cancelled": int(cancelled), "kept": int(kept),
                    "added": [_task_to_json(t) for t in added]})
        self.flush()

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        try:
            self._f.flush()
            self._f.close()
        except ValueError:
            pass


# -- reader / replay ----------------------------------------------------------

class _ReplayClock:
    """Settable clock the replay drives so the rebuilt ledger records the
    journaled timestamps, not wall time."""

    def __init__(self, t_ms: int = 0):
        self.t_ms = int(t_ms)

    def __call__(self) -> int:
        return self.t_ms


@dataclasses.dataclass
class ResumeState:
    """Everything ``Executor.resume()`` needs to continue mid-phase."""

    plan: ExecutionPlan
    task_manager: ExecutionTaskManager
    ledger: ExecutionLedger
    partition_names: List[Tuple[str, int]]
    limits: ConcurrencyLimits
    max_polls: int
    replication_throttle: Optional[int]
    done_phases: Set[str]
    current_phase: Optional[str]
    in_flight: Dict[int, ExecutionTask]   # adopted (IN_PROGRESS at crash)
    polls: int
    clock: _ReplayClock


def _read_lines(path: str) -> List[dict]:
    """Parse the journal, tolerating exactly one torn line at the tail
    (the crash signature).  Garbage anywhere else is corruption."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read().split("\n")
    except OSError as e:
        raise JournalError(f"cannot read journal {path}: {e}")
    if raw and raw[-1] == "":
        raw.pop()
    lines: List[dict] = []
    for i, text in enumerate(raw):
        try:
            obj = json.loads(text)
            if not isinstance(obj, dict) or "kind" not in obj:
                raise ValueError("not a journal line")
        except ValueError:
            if i == len(raw) - 1:
                break  # torn tail: normal crash artifact
            raise JournalError(f"corrupt journal line {i + 1} in {path}")
        lines.append(obj)
    if not lines or lines[0].get("kind") != "header":
        raise JournalError(f"journal {path} has no header")
    if lines[0].get("version") != JOURNAL_VERSION:
        raise JournalError(
            f"journal {path} version {lines[0].get('version')} unsupported")
    return lines


_PHASE_OF_TYPE = {
    TaskType.INTER_BROKER_REPLICA_ACTION: "inter_broker",
    TaskType.INTRA_BROKER_REPLICA_ACTION: "intra_broker",
    TaskType.LEADER_ACTION: "leadership",
}


def _extend_plan(plan: ExecutionPlan, tasks: Sequence[ExecutionTask]) -> None:
    for t in tasks:
        if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
            plan.inter_broker_tasks.append(t)
            for b in t.brokers_involved():
                plan.tasks_by_broker.setdefault(b, []).append(t)
        elif t.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION:
            plan.intra_broker_tasks.append(t)
        else:
            plan.leadership_tasks.append(t)


def rebuild(path: str, scorer=None) -> ResumeState:
    """Replay the journal into a fresh plan/task-manager/ledger.

    The replay drives the real transition + poll code paths under the
    recorded clock, so every derived quantity (counts, bytes, landed set,
    checkpoint curve incl. stride thinning, phase records) is rebuilt by
    construction rather than deserialized — identical logic, identical
    state.  Raises :class:`JournalError` on corruption."""
    lines = _read_lines(path)
    header = lines[0]
    partition_names = [(t, int(i)) for t, i in header["partitionNames"]]
    limits = _limits_from_json(header["limits"])
    clock = _ReplayClock(header["startedMs"])

    plan = ExecutionPlan(inter_broker_tasks=[], intra_broker_tasks=[],
                         leadership_tasks=[], tasks_by_broker={})
    by_id: Dict[int, ExecutionTask] = {}
    idx = 1
    while idx < len(lines) and lines[idx]["kind"] == "task":
        t = _task_from_json(lines[idx])
        by_id[t.execution_id] = t
        _extend_plan(plan, [t])
        idx += 1

    ledger = ExecutionLedger(clock, throttle_rate_bytes_per_sec=header.get(
        "replicationThrottle"), scorer=scorer)
    ledger.attach(plan)
    tm = ExecutionTaskManager(plan, limits)
    done_phases: Set[str] = set()
    current_phase: Optional[str] = None

    try:
        for line in lines[idx:]:
            kind = line["kind"]
            if kind == "event":
                t = by_id[line["id"]]
                to = TaskState(line["to"])
                clock.t_ms = line["tMs"]
                t._transition(to, now_ms=line["tMs"])
                # Mirror the task manager's live admission bookkeeping.
                if to == TaskState.IN_PROGRESS:
                    tm._inflight.add(t.execution_id)
                    if t.task_type != TaskType.LEADER_ACTION:
                        for b in t.brokers_involved():
                            tm._inflight_by_broker[b] = \
                                tm._inflight_by_broker.get(b, 0) + 1
                elif to in (TaskState.COMPLETED, TaskState.ABORTED,
                            TaskState.DEAD):
                    tm.finished(t)
            elif kind == "poll":
                clock.t_ms = line["tMs"]
                ledger.poll(tm)
            elif kind == "phase":
                clock.t_ms = line["tMs"]
                ledger.phase_started(line["phase"])
                current_phase = line["phase"]
            elif kind == "phase_end":
                clock.t_ms = line["tMs"]
                ledger.phase_finished(polls=line["polls"],
                                      batches=line["batches"])
                done_phases.add(line["phase"])
                current_phase = None
            elif kind == "limits":
                limits = _limits_from_json(line["limits"])
                tm.set_limits(limits)
            elif kind == "replan":
                added = [_task_from_json(d) for d in line["added"]]
                for t in added:
                    by_id[t.execution_id] = t
                _extend_plan(plan, added)
                clock.t_ms = line["tMs"]
                ledger.replan_rebase(added, cancelled=line["cancelled"],
                                     kept=line["kept"])
            elif kind == "task":
                raise JournalError(f"stray task line after events in {path}")
    except (KeyError, ValueError, TypeError) as e:
        raise JournalError(f"journal {path} replay failed: {e}")

    in_flight = {t.execution_id: t for t in by_id.values()
                 if t.state == TaskState.IN_PROGRESS}
    return ResumeState(
        plan=plan, task_manager=tm, ledger=ledger,
        partition_names=partition_names, limits=limits,
        max_polls=int(header["maxPolls"]),
        replication_throttle=header.get("replicationThrottle"),
        done_phases=done_phases, current_phase=current_phase,
        in_flight=in_flight, polls=ledger.polls, clock=clock)


def remove_journal(path: str) -> None:
    """Best-effort cleanup once an execution fully completes."""
    try:
        os.remove(path)
    except OSError:
        pass
