"""Replication throttling around movement batches.

Parity with ``ReplicationThrottleHelper``
(executor/ReplicationThrottleHelper.java): before a batch of inter-broker
moves, set the leader/follower replication throttle rate on every involved
broker and mark the moving replicas as throttled (``"partition:broker"``
entries per topic); after the batch, remove exactly what was added, leaving
pre-existing operator-set throttles untouched.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from cruise_control_tpu.executor.admin import ClusterAdmin, Tp
from cruise_control_tpu.executor.task import ExecutionTask


class ReplicationThrottleHelper:
    def __init__(self, admin: ClusterAdmin, rate_bytes_per_sec: Optional[int] = None):
        self._admin = admin
        self._rate = rate_bytes_per_sec

    @property
    def rate_bytes_per_sec(self) -> Optional[int]:
        """The configured throttle rate (None = unthrottled) — the execution
        ledger reads this for its throttle-utilization accounting."""
        return self._rate

    def _throttled_replicas(self, tasks: Sequence[ExecutionTask],
                            partition_names: Sequence[Tp]) -> Dict[str, List[str]]:
        """topic → ["partition:broker", ...] covering old AND new replicas of
        every moving partition (both sides replicate during the move)."""
        out: Dict[str, List[str]] = {}
        for t in tasks:
            topic, part = partition_names[t.proposal.partition]
            brokers = {r.broker for r in t.proposal.old_replicas} | \
                      {r.broker for r in t.proposal.new_replicas}
            entries = out.setdefault(topic, [])
            for b in sorted(brokers):
                entries.append(f"{part}:{b}")
        return out

    def set_throttles(self, tasks: Sequence[ExecutionTask],
                      partition_names: Sequence[Tp]) -> None:
        if self._rate is None or not tasks:
            return
        brokers = sorted({b for t in tasks for b in t.brokers_involved()})
        self._admin.set_replication_throttles(
            self._rate, brokers, self._throttled_replicas(tasks, partition_names))

    def clear_throttles(self, tasks: Sequence[ExecutionTask],
                        partition_names: Sequence[Tp]) -> None:
        if self._rate is None or not tasks:
            return
        brokers = sorted({b for t in tasks for b in t.brokers_involved()})
        self._admin.clear_replication_throttles(
            brokers, self._throttled_replicas(tasks, partition_names))
