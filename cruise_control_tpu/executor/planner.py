"""Execution task planning.

Parity with ``ExecutionTaskPlanner`` (executor/ExecutionTaskPlanner.java:65,
class doc :46-64): converts proposals into (1) a leadership-movement task
list, (2) per-broker *sorted* inter-broker movement sets ordered by the
configured replica-movement strategy — each movement task appears in both
its source and destination brokers' plans — and (3) intra-broker movement
tasks for disk-only changes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.strategy import (BaseReplicaMovementStrategy,
                                                  ReplicaMovementStrategy,
                                                  StrategyContext)
from cruise_control_tpu.executor.task import ExecutionTask, TaskType


@dataclasses.dataclass
class ExecutionPlan:
    inter_broker_tasks: List[ExecutionTask]
    intra_broker_tasks: List[ExecutionTask]
    leadership_tasks: List[ExecutionTask]
    # broker id → its inter-broker tasks in strategy order (task present in
    # both source and destination brokers' lists).
    tasks_by_broker: Dict[int, List[ExecutionTask]]

    @property
    def total_tasks(self) -> int:
        return (len(self.inter_broker_tasks) + len(self.intra_broker_tasks)
                + len(self.leadership_tasks))

    @property
    def total_bytes(self) -> int:
        """Total data volume the plan will move (leadership moves none)."""
        return sum(t.bytes_to_move for t in
                   self.inter_broker_tasks + self.intra_broker_tasks)


class ExecutionTaskPlanner:
    def __init__(self, strategy: Optional[ReplicaMovementStrategy] = None,
                 first_execution_id: int = 0):
        # ``first_execution_id`` lets a mid-execution replan mint task ids
        # that continue after the live plan's current maximum.
        self._strategy = strategy or BaseReplicaMovementStrategy()
        self._next_execution_id = first_execution_id

    def _new_task(self, proposal: ExecutionProposal, task_type: TaskType) -> ExecutionTask:
        t = ExecutionTask(self._next_execution_id, proposal, task_type)
        self._next_execution_id += 1
        return t

    def plan(self, proposals: Sequence[ExecutionProposal],
             context: Optional[StrategyContext] = None) -> ExecutionPlan:
        inter: List[ExecutionTask] = []
        intra: List[ExecutionTask] = []
        leader: List[ExecutionTask] = []
        for p in proposals:
            if p.replicas_to_add or p.replicas_to_remove:
                inter.append(self._new_task(p, TaskType.INTER_BROKER_REPLICA_ACTION))
            # Not elif: a proposal can carry both an inter-broker change and a
            # same-broker disk move for a different replica of the partition.
            if p._intra_broker_moves():
                intra.append(self._new_task(p, TaskType.INTRA_BROKER_REPLICA_ACTION))
            if p.has_leader_action:
                leader.append(self._new_task(p, TaskType.LEADER_ACTION))

        ordered = self._strategy.sorted_tasks(inter, context)
        by_broker: Dict[int, List[ExecutionTask]] = {}
        for t in ordered:
            for b in t.brokers_involved():
                by_broker.setdefault(b, []).append(t)
        return ExecutionPlan(inter_broker_tasks=ordered, intra_broker_tasks=intra,
                             leadership_tasks=leader, tasks_by_broker=by_broker)
