"""The Executor: applies proposals to the cluster with admission control.

Parity with ``Executor`` (executor/Executor.java:76): owns the execution
lifecycle — phases inter-broker → intra-broker → leadership
(ProposalExecutionRunnable, Executor.java:1079-1148), per-batch reassignment
submission + wait loop (interBrokerMoveReplicas :1255-1318,
waitForExecutionTaskToFinish :1431), replication throttling around batches
(ReplicationThrottleHelper), dead-broker task handling (:1548), graceful
stop and force-stop (:91-96, znode deletion → ``cancel_reassignments``),
recently-removed/demoted broker history (:113-117), the
generating-proposals reservation handshake (:828), metric-sampling pause
during execution (adjustSamplingModeBeforeExecution :1051-1067), and the
concurrency auto-adjuster (:335-447).

The executor is deliberately synchronous and poll-driven ("keep it boring"),
driving any ``ClusterAdmin`` backend; the REST layer runs it on a worker
thread.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.common.tracing import TRACE
from cruise_control_tpu.executor.admin import (ClusterAdmin,
                                               ReassignmentRequest,
                                               TransientAdminError, Tp)
from cruise_control_tpu.executor.journal import (ExecutionJournal,
                                                 JournalError, ResumeState,
                                                 rebuild as rebuild_journal)
from cruise_control_tpu.executor.ledger import ExecutionLedger
from cruise_control_tpu.executor.planner import ExecutionPlan, ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import ReplicaMovementStrategy, StrategyContext
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType
from cruise_control_tpu.executor.task_manager import (ConcurrencyLimits,
                                                      ExecutionTaskManager)
from cruise_control_tpu.executor.throttle import ReplicationThrottleHelper


class ExecutorState(enum.Enum):
    """executor/ExecutorState.java state machine."""

    NO_TASK_IN_PROGRESS = "no_task_in_progress"
    STARTING_EXECUTION = "starting_execution"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = "inter_broker_replica_movement"
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = "intra_broker_replica_movement"
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "leader_movement"
    STOPPING_EXECUTION = "stopping_execution"
    GENERATING_PROPOSALS_FOR_EXECUTION = "generating_proposals_for_execution"


class OngoingExecutionError(RuntimeError):
    pass


class SimulatedCrash(RuntimeError):
    """Raised by the ``crash_after_polls`` fault hook: models a process
    death mid-execution.  The journal (if enabled) is left exactly as a
    real kill would leave it; ``Executor.resume()`` picks it up."""


def replan_enabled() -> bool:
    """CRUISE_REPLAN=0 kill-switch for replan-while-executing."""
    return os.environ.get("CRUISE_REPLAN", "1").lower() not in (
        "0", "false", "no", "off")


@dataclasses.dataclass
class ReplanDirective:
    """What a replanner callback hands back to the executor at a phase
    boundary: the re-solved proposal set for the partially-moved cluster
    (the executor patches the live queue against it: cancel-what-changed,
    keep-what-still-helps) plus an optional replacement ``PlacementScorer``
    whose before/after match the new plan."""

    proposals: List[ExecutionProposal]
    scorer: object = None
    info: Optional[Dict[str, object]] = None


#: Replanner signature: (landed_partitions, in_flight_partitions) →
#: ReplanDirective, or None to keep the current (static) plan.
Replanner = Callable[[frozenset, frozenset], Optional[ReplanDirective]]


@dataclasses.dataclass
class ExecutionResult:
    completed: int
    dead: int
    aborted: int
    polls: int
    stopped: bool

    @property
    def ok(self) -> bool:
        return not self.stopped and self.dead == 0 and self.aborted == 0


class ConcurrencyAdjuster:
    """Auto-scales movement concurrency from live broker metrics
    (Executor.java:335-447 + ExecutionUtils thresholds): halves concurrency
    when any broker looks stressed (deep request queue / low idle ratio or
    (At/Under)MinISR partitions), doubles it (up to the configured cap) when
    all brokers look healthy."""

    REQUEST_QUEUE_SIZE_CAP = 1000.0
    MIN_IDLE_RATIO = 0.3

    def __init__(self, base: ConcurrencyLimits,
                 min_per_broker: int = 1,
                 max_per_broker: Optional[int] = None,
                 interval_ms: int = 0):
        # concurrency.adjuster.{min,max}.partition.movements.per.broker +
        # .interval.ms (ExecutorConfig): the floor/ceiling of auto-scaling
        # and how often it re-evaluates (0 = every poll).
        self._base = base
        self._min = max(1, min_per_broker)
        self._max = max_per_broker or base.inter_broker_per_broker
        self._interval_ms = interval_ms
        self._last_adjust_ms = 0.0

    def adjust(self, limits: ConcurrencyLimits,
               broker_metrics: Dict[int, Dict[str, float]],
               has_min_isr_pressure: bool = False) -> ConcurrencyLimits:
        now_ms = time.monotonic() * 1000
        if self._interval_ms and now_ms - self._last_adjust_ms < self._interval_ms:
            return limits
        self._last_adjust_ms = now_ms
        stressed = has_min_isr_pressure
        for m in broker_metrics.values():
            if m.get("BROKER_REQUEST_QUEUE_SIZE", 0.0) > self.REQUEST_QUEUE_SIZE_CAP:
                stressed = True
            if m.get("BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT", 1.0) < self.MIN_IDLE_RATIO:
                stressed = True
        cur = limits.inter_broker_per_broker
        if stressed:
            new = max(self._min, cur // 2)
        else:
            new = min(self._max, self._base.inter_broker_per_broker, cur * 2)
        return dataclasses.replace(limits, inter_broker_per_broker=new)


class Executor:
    def __init__(self, admin: ClusterAdmin,
                 metadata_client,
                 limits: Optional[ConcurrencyLimits] = None,
                 strategy: Optional[ReplicaMovementStrategy] = None,
                 throttle_rate_bytes_per_sec: Optional[int] = None,
                 removed_broker_retention_ms: int = 12 * 3600 * 1000,
                 demoted_broker_retention_ms: Optional[int] = None,
                 on_sampling_pause: Optional[Callable[[str], None]] = None,
                 on_sampling_resume: Optional[Callable[[], None]] = None,
                 logdir_by_disk: Optional[Dict[int, str]] = None,
                 min_isr_pressure_fn: Optional[Callable[[], bool]] = None,
                 progress_check_interval_ms: int = 0,
                 leader_movement_timeout_ms: int = 180_000,
                 concurrency_adjuster_enabled: bool = True,
                 concurrency_adjuster_interval_ms: int = 0,
                 concurrency_adjuster_min_per_broker: int = 1,
                 concurrency_adjuster_max_per_broker: Optional[int] = None,
                 ledger_enabled: bool = True,
                 clock_ms: Optional[Callable[[], int]] = None,
                 admin_max_retries: int = 3,
                 admin_retry_backoff_s: float = 0.05,
                 breaker_failure_threshold: int = 3,
                 breaker_cooldown_ms: int = 60_000):
        self._admin = admin
        self._metadata = metadata_client
        self._limits = limits or ConcurrencyLimits()
        self._strategy = strategy
        self._throttle = ReplicationThrottleHelper(admin, throttle_rate_bytes_per_sec)
        self._lock = threading.RLock()
        self._state = ExecutorState.NO_TASK_IN_PROGRESS  # guarded-by: _lock
        self._stop_requested = False  # guarded-by: _lock
        self._force_stop = False  # guarded-by: _lock
        self._reserved_for_proposals = False  # guarded-by: _lock
        self._retention_ms = removed_broker_retention_ms
        # demoted.broker.retention.time.ms may differ from removed
        # (ExecutorConfig: two distinct retention knobs).
        self._demoted_retention_ms = (demoted_broker_retention_ms
                                      if demoted_broker_retention_ms is not None
                                      else removed_broker_retention_ms)
        self._recently_removed: Dict[int, int] = {}  # broker → time_ms  # guarded-by: _lock
        self._recently_demoted: Dict[int, int] = {}  # guarded-by: _lock
        self._on_pause = on_sampling_pause
        self._on_resume = on_sampling_resume
        self._logdir_by_disk = logdir_by_disk or {}
        self._min_isr_pressure_fn = min_isr_pressure_fn or (lambda: False)
        # execution.progress.check.interval.ms / leader.movement.timeout.ms:
        # the wait-loop cadence and the leadership phase's wall-clock bound.
        self._progress_check_interval_s = progress_check_interval_ms / 1000.0
        self._leader_movement_timeout_ms = leader_movement_timeout_ms
        self._adjuster_enabled = concurrency_adjuster_enabled
        self._adjuster_args = (concurrency_adjuster_min_per_broker,
                               concurrency_adjuster_max_per_broker,
                               concurrency_adjuster_interval_ms)
        self._task_manager: Optional[ExecutionTaskManager] = None  # guarded-by: _lock
        self._adjuster = ConcurrencyAdjuster(self._limits, *self._adjuster_args)
        # Execution ledger (per-task lifecycle log + progress accounting).
        # The clock is pluggable so simulated executions record fleet time;
        # the ledger of the latest execution persists for post-run queries.
        self._ledger_enabled = ledger_enabled
        self._clock_ms = clock_ms or (lambda: int(time.time() * 1000))
        self._ledger: Optional[ExecutionLedger] = None  # guarded-by: _lock
        # Fault-tolerant dispatch: retry/backoff envelope around admin
        # calls + per-broker circuit breaker (broker → [consecutive
        # failures, open-until clock]).
        self._admin_max_retries = max(0, admin_max_retries)
        self._admin_retry_backoff_s = max(0.0, admin_retry_backoff_s)
        self._breaker_threshold = max(1, breaker_failure_threshold)
        self._breaker_cooldown_ms = max(0, breaker_cooldown_ms)
        self._breaker: Dict[int, List[float]] = {}  # guarded-by: _lock
        # Sensor registrations (Executor.registerGaugeSensors,
        # Executor.java:271; Sensors.md execution gauges).
        from cruise_control_tpu.executor.task import TaskType as _TT

        def _in_progress(task_type):
            def read() -> int:
                with self._lock:
                    tm = self._task_manager
                if tm is None:
                    return 0
                return sum(1 for t in tm.tasks_by_state()[TaskState.IN_PROGRESS]
                           if t.task_type == task_type)
            return read

        SENSORS.gauge("Executor.inter-broker-partition-movements-in-progress",
                      _in_progress(_TT.INTER_BROKER_REPLICA_ACTION),
                      help="Inter-broker replica movements currently in flight")
        SENSORS.gauge("Executor.intra-broker-partition-movements-in-progress",
                      _in_progress(_TT.INTRA_BROKER_REPLICA_ACTION),
                      help="Intra-broker (logdir) movements currently in flight")
        SENSORS.gauge("Executor.leadership-movements-in-progress",
                      _in_progress(_TT.LEADER_ACTION),
                      help="Leadership transfers currently in flight")
        SENSORS.gauge("Executor.execution-in-progress",
                      lambda: float(self.has_ongoing_execution),
                      help="1 while a proposal execution is running")
        self._sensor_started = SENSORS.counter(
            "Executor.executions-started",
            help="Proposal executions started since boot")
        self._sensor_stopped = SENSORS.counter(
            "Executor.executions-stopped",
            help="Proposal executions stopped by user request")
        self._sensor_completed = SENSORS.counter(
            "Executor.tasks-completed",
            help="Execution tasks finished in COMPLETED state")
        self._sensor_dead = SENSORS.counter(
            "Executor.tasks-dead",
            help="Execution tasks abandoned in DEAD state")

        # Ledger-driven progress gauges.  All read the latest execution's
        # ledger (live or finished); sentinel values cover the no-ledger
        # case so the families register deterministically at boot.
        def _ledger_read(fn, default=0.0):
            def read() -> float:
                led = self._ledger
                return default if led is None else float(fn(led))
            return read

        SENSORS.gauge("Executor.bytes-moved",
                      _ledger_read(lambda led: led.bytes_moved),
                      help="Bytes moved so far by the latest execution")
        SENSORS.gauge("Executor.bytes-total",
                      _ledger_read(lambda led: led.total_bytes),
                      help="Total bytes the latest execution plan moves")
        SENSORS.gauge("Executor.bytes-in-flight",
                      _ledger_read(lambda led: led.bytes_in_flight),
                      help="Bytes of movement currently in flight")
        SENSORS.gauge("Executor.movement-rate-bytes-per-sec",
                      _ledger_read(
                          lambda led: led.movement_rate_bytes_per_sec),
                      help="Observed data movement rate of the latest "
                           "execution")
        SENSORS.gauge("Executor.eta-seconds",
                      _ledger_read(lambda led: led.eta_seconds, -1.0),
                      help="Remaining bytes over the observed movement rate "
                           "(-1 while unknown)")
        SENSORS.gauge("Executor.throttle-utilization",
                      _ledger_read(lambda led: led.throttle_utilization, -1.0),
                      help="Observed movement rate over the replication-"
                           "throttle ceiling (-1 when unthrottled or idle)")
        SENSORS.gauge("Executor.max-broker-in-flight",
                      _ledger_read(lambda led: led.max_broker_in_flight),
                      help="Largest per-broker in-flight movement count")
        SENSORS.gauge("Executor.balancedness-score",
                      _ledger_read(lambda led: led.balancedness, -1.0),
                      help="Balancedness at the latest scored execution "
                           "checkpoint (-1 until one is scored)")
        self._sensor_adjuster = {
            d: SENSORS.counter(
                "Executor.adjuster-decisions", labels={"decision": d},
                help="Concurrency-adjuster decisions by outcome")
            for d in ("halve", "double", "hold")}
        for tt in _TT:
            SENSORS.histogram(
                "Executor.task-duration-seconds", labels={"type": tt.value},
                help="Completed execution task duration, by task type")

        # Interruptible-execution families: live replanning, crash resume,
        # and the admin retry/backoff + circuit-breaker envelope.
        self._sensor_replan = {
            "rounds": SENSORS.counter(
                "Executor.replan-rounds",
                help="Replan-while-executing rounds that produced a patch"),
            "cancelled": SENSORS.counter(
                "Executor.replan-tasks-cancelled",
                help="Pending tasks cancelled because the re-solve changed "
                     "their target"),
            "kept": SENSORS.counter(
                "Executor.replan-tasks-kept",
                help="Pending tasks kept verbatim across a replan round"),
            "added": SENSORS.counter(
                "Executor.replan-tasks-added",
                help="Tasks added by replan rounds for newly-needed moves"),
            "fallbacks": SENSORS.counter(
                "Executor.replan-fallbacks",
                help="Replan rounds that kept the static plan (replanner "
                     "declined, failed verification, or raised)"),
        }
        self._sensor_resume_started = SENSORS.counter(
            "Executor.resume-started",
            help="Journal resumes attempted")
        self._sensor_resume_completed = SENSORS.counter(
            "Executor.resume-completed",
            help="Journal resumes that reconstructed state and re-entered "
                 "the phase loop")
        self._sensor_resume_adopted = SENSORS.counter(
            "Executor.resume-tasks-adopted",
            help="In-flight tasks adopted from the journal on resume")
        self._sensor_resume_errors = SENSORS.counter(
            "Executor.resume-journal-errors",
            help="Resumes that hit a corrupt journal and fell back to a "
                 "clean abort")
        self._sensor_retries = SENSORS.counter(
            "Executor.admin-retries",
            help="Transient admin failures retried with exponential backoff")
        self._sensor_retry_giveups = SENSORS.counter(
            "Executor.admin-retry-giveups",
            help="Admin calls abandoned after exhausting the retry budget "
                 "(their tasks abort and await replan)")
        self._sensor_breaker_opens = SENSORS.counter(
            "Executor.admin-breaker-opens",
            help="Per-broker circuit-breaker trips after consecutive admin "
                 "failures")
        SENSORS.gauge(
            "Executor.admin-breaker-open-brokers",
            lambda: float(sum(
                1 for st in self._breaker.values()
                if st[1] > self._clock_ms())),
            help="Brokers whose admin circuit is currently open")

    # -- state -------------------------------------------------------------
    def state(self) -> ExecutorState:
        with self._lock:
            return self._state

    @property
    def limits(self) -> ConcurrencyLimits:
        with self._lock:
            return self._limits

    def set_concurrency(self, limits: ConcurrencyLimits) -> None:
        """Dynamically change movement concurrency (ADMIN endpoint;
        KafkaCruiseControl.setConcurrency analogue).  Updates the configured
        limits, the adjuster's base (so auto-adjustment re-expands to the new
        cap, not the stale one), and any live execution's task manager."""
        with self._lock:
            self._limits = limits
            self._adjuster = ConcurrencyAdjuster(limits, *self._adjuster_args)
            if self._task_manager is not None:
                self._task_manager.set_limits(limits)

    @property
    def has_ongoing_execution(self) -> bool:
        return self.state() not in (ExecutorState.NO_TASK_IN_PROGRESS,
                                    ExecutorState.GENERATING_PROPOSALS_FOR_EXECUTION)

    def state_summary(self) -> Dict[str, object]:
        with self._lock:
            out: Dict[str, object] = {"state": self._state.value}
            if self._task_manager is not None:
                out["tasks"] = self._task_manager.counts()
            out["recentlyRemovedBrokers"] = sorted(self.recently_removed_brokers())
            out["recentlyDemotedBrokers"] = sorted(self.recently_demoted_brokers())
            return out

    def progress(self, verbose: bool = False) -> Dict[str, object]:
        """Execution-ledger progress of the latest (or live) execution —
        the ``GET /executor_state`` payload (the reference's executor
        substate, ExecutorState.java:331-389, plus the ledger's bytes/ETA/
        curve accounting)."""
        with self._lock:
            out: Dict[str, object] = {"state": self._state.value,
                                      "ledgerEnabled": self._ledger_enabled}
            led = self._ledger
        if led is not None:
            out.update(led.to_dict(verbose=verbose))
        return out

    # -- reservation handshake (Executor.java:828) --------------------------
    def set_generating_proposals_for_execution(self) -> None:
        with self._lock:
            if self._state != ExecutorState.NO_TASK_IN_PROGRESS:
                raise OngoingExecutionError(
                    f"cannot reserve executor in state {self._state}")
            self._state = ExecutorState.GENERATING_PROPOSALS_FOR_EXECUTION
            self._reserved_for_proposals = True

    def failed_generating_proposals_for_execution(self) -> None:
        with self._lock:
            if self._reserved_for_proposals:
                self._reserved_for_proposals = False
                self._state = ExecutorState.NO_TASK_IN_PROGRESS

    # -- stop signals -------------------------------------------------------
    def stop_execution(self, force: bool = False) -> None:
        with self._lock:
            if self.has_ongoing_execution:
                self._stop_requested = True
                self._force_stop = force
                self._state = ExecutorState.STOPPING_EXECUTION
        if force:
            self._admin.cancel_reassignments()

    # -- broker history ------------------------------------------------------
    def _gc_history(self, history: Dict[int, int], now_ms: int,
                    retention_ms: Optional[int] = None) -> None:
        keep_ms = retention_ms if retention_ms is not None else self._retention_ms
        expired = [b for b, t in history.items() if now_ms - t > keep_ms]
        for b in expired:
            del history[b]

    def add_recently_removed_brokers(self, brokers: Sequence[int],
                                     now_ms: Optional[int] = None) -> None:
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        with self._lock:
            for b in brokers:
                self._recently_removed[b] = now

    def add_recently_demoted_brokers(self, brokers: Sequence[int],
                                     now_ms: Optional[int] = None) -> None:
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        with self._lock:
            for b in brokers:
                self._recently_demoted[b] = now

    def drop_recently_removed_brokers(self, brokers: Sequence[int]) -> None:
        with self._lock:
            for b in brokers:
                self._recently_removed.pop(b, None)

    def recently_removed_brokers(self, now_ms: Optional[int] = None) -> Set[int]:
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        with self._lock:
            self._gc_history(self._recently_removed, now)
            return set(self._recently_removed)

    def recently_demoted_brokers(self, now_ms: Optional[int] = None) -> Set[int]:
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        with self._lock:
            self._gc_history(self._recently_demoted, now,
                             self._demoted_retention_ms)
            return set(self._recently_demoted)

    @contextmanager
    def _phase_probe(self, phase: str, tasks: int,
                     ledger: Optional[ExecutionLedger] = None,
                     journal: Optional[ExecutionJournal] = None):
        """Span + duration histogram around one execution phase.  Yields the
        span so the phase runner can annotate polls/batches/bytes onto it."""
        hist = SENSORS.histogram(
            "Executor.phase-duration-seconds", labels={"phase": phase},
            help="Wall time spent in each execution phase")
        if ledger is not None:
            ledger.phase_started(phase)
        if journal is not None:
            journal.phase(phase, self._clock_ms())
        with TRACE.span(f"executor.{phase}", tasks=tasks) as sp, hist.time():
            yield sp

    # -- fault-tolerant dispatch (retry/backoff + per-broker breaker) --------
    def _circuit_open(self, brokers, now_ms: int) -> bool:
        """True when any involved broker's admin circuit is open.  An
        elapsed cooldown resets the entry (half-open: the next call gets a
        fresh retry budget)."""
        with self._lock:
            for b in brokers:
                st = self._breaker.get(b)
                if st is None:
                    continue
                if st[1] > now_ms:
                    return True
                if st[1]:
                    self._breaker.pop(b, None)
        return False

    def _record_admin_failure(self, brokers) -> None:
        now = self._clock_ms()
        with self._lock:
            for b in brokers:
                st = self._breaker.setdefault(b, [0, 0])
                st[0] += 1
                if st[0] >= self._breaker_threshold and st[1] <= now:
                    st[1] = now + self._breaker_cooldown_ms
                    self._sensor_breaker_opens.inc()

    def _record_admin_success(self, brokers) -> None:
        with self._lock:
            for b in brokers:
                self._breaker.pop(b, None)

    def _call_admin(self, fn: Callable[[], None], brokers) -> bool:
        """Retry/timeout envelope around one ClusterAdmin call: transient
        failures retry with exponential backoff; exhausting the budget
        records a per-broker failure (tripping the circuit breaker at the
        threshold) and returns False so the caller aborts the affected
        tasks instead of wedging the phase loop."""
        delay = self._admin_retry_backoff_s
        attempts = self._admin_max_retries
        while True:
            try:
                fn()
            except TransientAdminError:
                if attempts <= 0:
                    self._sensor_retry_giveups.inc()
                    self._record_admin_failure(brokers)
                    return False
                attempts -= 1
                self._sensor_retries.inc()
                if delay:
                    time.sleep(delay)
                    delay *= 2
                continue
            self._record_admin_success(brokers)
            return True

    # -- main entry ----------------------------------------------------------
    def execute_proposals(self, proposals: Sequence[ExecutionProposal],
                          partition_names: Sequence[Tp],
                          context: Optional[StrategyContext] = None,
                          max_polls: int = 10_000,
                          poll_interval_s: Optional[float] = None,
                          concurrency_adjust_metrics: Optional[
                              Callable[[], Dict[int, Dict[str, float]]]] = None,
                          strategy: Optional[ReplicaMovementStrategy] = None,
                          replication_throttle: Optional[int] = None,
                          balancedness_scorer=None,
                          replanner: Optional[Replanner] = None,
                          replan_interval_polls: int = 0,
                          journal_path: Optional[str] = None,
                          crash_after_polls: Optional[int] = None
                          ) -> ExecutionResult:
        """Run the full three-phase execution to completion.

        ``partition_names[p.partition]`` maps a proposal's dense partition id
        to its (topic, partition) — the naming seam between the tensor world
        and the cluster protocol.  ``poll_interval_s=None`` uses the
        configured execution.progress.check.interval.ms cadence.
        ``strategy`` / ``replication_throttle`` override the boot-time
        movement strategy and throttle rate for THIS execution only (the
        reference accepts both per request,
        ParameterUtils.java:418 + :733; KafkaCruiseControl.java:465-495).
        ``balancedness_scorer`` (a ``PlacementScorer``) attaches goal-distance
        re-scoring to the ledger's checkpoints — batched at phase boundaries,
        never per poll.

        Interruptible execution: ``journal_path`` appends the in-flight plan
        + every transition to a sidecar JSONL file (flushed once per ledger
        poll, host-side only) so :meth:`resume` can continue after a crash.
        ``replanner`` + ``replan_interval_polls`` N re-solve against the
        partially-moved cluster every N polls (at the same boundaries
        ``score_checkpoints`` dispatches) and patch the live queue —
        cancel-what-changed, keep-what-still-helps; the ``CRUISE_REPLAN=0``
        env kill-switch disables it.  ``crash_after_polls`` is the fault
        hook: raise :class:`SimulatedCrash` once the ledger's cumulative
        poll count reaches the given value (tests/bench kill-resume legs).
        """
        if poll_interval_s is None:
            poll_interval_s = self._progress_check_interval_s
        if journal_path is not None and not self._ledger_enabled:
            raise ValueError("journaling requires ledger_enabled=True")
        with self._lock:
            if self.has_ongoing_execution:
                raise OngoingExecutionError("an execution is already in progress")
            if self._admin.ongoing_reassignments():
                raise OngoingExecutionError(
                    "ongoing partition reassignments detected (started by another "
                    "tool or a previous run) — refusing to execute; force-stop to adopt")
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._reserved_for_proposals = False
        self._sensor_started.inc()

        if self._on_pause:
            self._on_pause("ongoing execution")
        try:
            effective_strategy = strategy if strategy is not None else self._strategy
            planner = ExecutionTaskPlanner(effective_strategy)
            throttle = (ReplicationThrottleHelper(self._admin, replication_throttle)
                        if replication_throttle is not None else self._throttle)
            plan = planner.plan(proposals, context)
            tm = ExecutionTaskManager(plan, self._limits)
            ledger: Optional[ExecutionLedger] = None
            journal: Optional[ExecutionJournal] = None
            if self._ledger_enabled:
                rate = (replication_throttle if replication_throttle is not None
                        else self._throttle.rate_bytes_per_sec)
                ledger = ExecutionLedger(self._clock_ms,
                                         throttle_rate_bytes_per_sec=rate,
                                         scorer=balancedness_scorer)
                ledger.attach(plan)
                if journal_path is not None:
                    journal = ExecutionJournal(journal_path)
                    journal.start(plan, partition_names, tm.limits, max_polls,
                                  replication_throttle, ledger.started_ms)
                    ledger.set_event_sink(journal.event)
            with self._lock:
                self._task_manager = tm
                self._ledger = ledger
            ctx = _ExecutionCtx(
                plan=plan, tm=tm, ledger=ledger, journal=journal,
                throttle=throttle, partition_names=partition_names,
                max_polls=max_polls, poll_interval_s=poll_interval_s,
                metrics_fn=concurrency_adjust_metrics,
                strategy=effective_strategy, replanner=replanner,
                replan_interval_polls=replan_interval_polls,
                crash_after_polls=crash_after_polls)
            return self._drive(ctx, n_proposals=len(proposals))
        finally:
            with self._lock:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
            if self._on_resume:
                self._on_resume()

    def resume(self, journal_path: str,
               balancedness_scorer=None,
               poll_interval_s: Optional[float] = None,
               concurrency_adjust_metrics: Optional[
                   Callable[[], Dict[int, Dict[str, float]]]] = None,
               replanner: Optional[Replanner] = None,
               replan_interval_polls: int = 0,
               max_polls: Optional[int] = None,
               crash_after_polls: Optional[int] = None) -> ExecutionResult:
        """Continue a journaled execution after a crash or stop.

        Replays the journal into a fresh plan/task-manager/ledger (see
        :mod:`cruise_control_tpu.executor.journal`), adopts the tasks that
        were in flight at the kill point (their reassignments persist in
        the cluster), and re-enters the phase loop mid-phase; completed
        phases are skipped.  The final placement and ledger totals are
        bit-identical to an uninterrupted run.

        A corrupt journal falls back to a clean abort: ongoing
        reassignments are cancelled, ``ongoing_execution`` is cleared, and
        the :class:`JournalError` propagates.
        """
        if poll_interval_s is None:
            poll_interval_s = self._progress_check_interval_s
        with self._lock:
            if self.has_ongoing_execution:
                raise OngoingExecutionError("an execution is already in progress")
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested = False
            self._force_stop = False
            self._reserved_for_proposals = False
        self._sensor_resume_started.inc()
        try:
            st = rebuild_journal(journal_path, scorer=balancedness_scorer)
        except JournalError:
            self._sensor_resume_errors.inc()
            # Clean abort: drop orphaned reassignments, clear state, let the
            # caller see the corruption.
            self._admin.cancel_reassignments()
            with self._lock:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
            raise
        journal = ExecutionJournal(journal_path, append=True)
        st.ledger.set_clock(self._clock_ms)
        st.ledger.set_event_sink(journal.event)
        throttle = (ReplicationThrottleHelper(self._admin,
                                              st.replication_throttle)
                    if st.replication_throttle is not None else self._throttle)
        with self._lock:
            self._task_manager = st.task_manager
            self._ledger = st.ledger
        self._sensor_resume_adopted.inc(len(st.in_flight))
        self._sensor_resume_completed.inc()
        if self._on_pause:
            self._on_pause("resumed execution")
        try:
            ctx = _ExecutionCtx(
                plan=st.plan, tm=st.task_manager, ledger=st.ledger,
                journal=journal, throttle=throttle,
                partition_names=st.partition_names,
                max_polls=(max_polls if max_polls is not None
                           else st.max_polls),
                poll_interval_s=poll_interval_s,
                metrics_fn=concurrency_adjust_metrics,
                strategy=self._strategy, replanner=replanner,
                replan_interval_polls=replan_interval_polls,
                crash_after_polls=crash_after_polls)
            return self._drive(ctx, n_proposals=st.plan.total_tasks,
                               done_phases=st.done_phases,
                               adopted=st.in_flight, polls_start=st.polls)
        finally:
            with self._lock:
                self._state = ExecutorState.NO_TASK_IN_PROGRESS
            if self._on_resume:
                self._on_resume()

    # -- the shared phase driver ---------------------------------------------
    def _drive(self, ctx: "_ExecutionCtx", n_proposals: int,
               done_phases: frozenset = frozenset(),
               adopted: Optional[Dict[int, ExecutionTask]] = None,
               polls_start: int = 0) -> ExecutionResult:
        plan, tm, ledger, journal = ctx.plan, ctx.tm, ctx.ledger, ctx.journal
        partition_names = ctx.partition_names
        polls = polls_start
        stopped = False
        try:
            with TRACE.span("executor.execute", proposals=n_proposals,
                            inter_broker_tasks=len(plan.inter_broker_tasks),
                            intra_broker_tasks=len(plan.intra_broker_tasks),
                            leadership_tasks=len(plan.leadership_tasks),
                            resumed=bool(polls_start)) as sp:
                # Phase 1: inter-broker replica movement (throttled).
                if plan.inter_broker_tasks and "inter_broker" not in done_phases:
                    with self._lock:
                        self._state = ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
                    ctx.throttle.set_throttles(plan.inter_broker_tasks,
                                               partition_names)
                    try:
                        with self._phase_probe("inter_broker",
                                               len(plan.inter_broker_tasks),
                                               ledger, journal) as psp:
                            phase_polls, stopped = self._run_inter_broker_phase(
                                ctx, psp, adopted=adopted,
                                polls_budget=max(1, ctx.max_polls - polls_start))
                            polls += phase_polls
                    finally:
                        ctx.throttle.clear_throttles(plan.inter_broker_tasks,
                                                     partition_names)
                    if ledger is not None:
                        ledger.score_checkpoints()

                # Phase 2: intra-broker (logdir) movement.
                if plan.intra_broker_tasks and "intra_broker" not in done_phases \
                        and not stopped and not self._stop_requested:
                    with self._lock:
                        self._state = ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
                    with self._phase_probe("intra_broker",
                                           len(plan.intra_broker_tasks),
                                           ledger, journal) as psp:
                        self._run_intra_broker_phase(ctx, psp)

                # Phase 3: leadership movement (batched preferred elections).
                if plan.leadership_tasks and "leadership" not in done_phases \
                        and not stopped and not self._stop_requested:
                    with self._lock:
                        self._state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
                    with self._phase_probe("leadership",
                                           len(plan.leadership_tasks),
                                           ledger, journal) as psp:
                        self._run_leadership_phase(ctx, psp, adopted=adopted)

                stopped = stopped or self._stop_requested
                if stopped and self._force_stop:
                    # Terminal-ize everything through the ledger observer so
                    # gauges and the curve reflect the abort instead of
                    # counting dead work as in-flight/pending forever.
                    self._finalize_force_stop(plan, tm)
                buckets = tm.tasks_by_state()
                if stopped:
                    self._sensor_stopped.inc()
                self._sensor_completed.inc(len(buckets[TaskState.COMPLETED]))
                self._sensor_dead.inc(len(buckets[TaskState.DEAD]))
                if ledger is not None:
                    ledger.finished()
                    ledger.poll(tm, force=True)
                    ledger.score_checkpoints()
                    sp.annotate(bytes_total=ledger.total_bytes,
                                bytes_moved=ledger.bytes_moved)
                sp.annotate(completed=len(buckets[TaskState.COMPLETED]),
                            dead=len(buckets[TaskState.DEAD]),
                            stopped=stopped, polls=polls)
                if journal is not None:
                    journal.close()
                return ExecutionResult(
                    completed=len(buckets[TaskState.COMPLETED]),
                    dead=len(buckets[TaskState.DEAD]),
                    aborted=len(buckets[TaskState.ABORTED]),
                    polls=polls, stopped=stopped)
        except SimulatedCrash:
            # A process death runs no finalization: the journal stays torn
            # at its last flushed poll line and the admin keeps its
            # in-flight reassignments — exactly what resume() expects.
            if journal is not None:
                journal.close()
            raise

    def _finalize_force_stop(self, plan: ExecutionPlan,
                             tm: ExecutionTaskManager) -> None:
        """Force-stop epilogue: every non-terminal task reaches a terminal
        state through its observer (in-flight → ABORTING → ABORTED, pending
        → cancelled), releasing in-flight accounting so ``Executor.*``
        gauges and the time-to-balanced curve record the abort."""
        now = self._clock_ms()
        for t in (plan.inter_broker_tasks + plan.intra_broker_tasks
                  + plan.leadership_tasks):
            if t.state == TaskState.IN_PROGRESS:
                t.aborting(now)
                t.aborted(now)
                tm.finished(t)
            elif t.state == TaskState.ABORTING:
                t.aborted(now)
                tm.finished(t)
            elif t.state == TaskState.PENDING:
                t.cancel(now)
                tm.finished(t)

    # -- phases --------------------------------------------------------------
    def _target_replicas(self, task: ExecutionTask) -> Tuple[int, ...]:
        return tuple(r.broker for r in task.proposal.new_replicas)

    def _poll_tick(self, ctx: "_ExecutionCtx") -> None:
        """One ledger poll + journal flush + crash fault hook (the journal
        write serializes host-side Python state only — no device fetch)."""
        if ctx.ledger is None:
            return
        ctx.ledger.poll(ctx.tm)
        if ctx.journal is not None:
            ctx.journal.poll(self._clock_ms())
        if ctx.crash_after_polls is not None \
                and ctx.ledger.polls >= ctx.crash_after_polls:
            raise SimulatedCrash(
                f"injected crash at ledger poll {ctx.ledger.polls}")

    def _adjust_concurrency(self, tm: ExecutionTaskManager, metrics_fn,
                            ledger: Optional[ExecutionLedger],
                            journal: Optional[ExecutionJournal] = None) -> None:
        """One adjuster evaluation; classifies the decision (halve / double /
        hold) by comparing the per-broker limit before and after, since the
        adjuster itself is interval-gated and may return the input."""
        before = tm.limits.inter_broker_per_broker
        tm.set_limits(self._adjuster.adjust(
            tm.limits, metrics_fn(),
            has_min_isr_pressure=self._min_isr_pressure_fn()))
        after = tm.limits.inter_broker_per_broker
        decision = ("halve" if after < before
                    else "double" if after > before else "hold")
        self._sensor_adjuster[decision].inc()
        if ledger is not None:
            ledger.adjuster_decision(decision)
        if decision != "hold" and journal is not None:
            journal.limits(tm.limits)

    # -- replan-while-executing ----------------------------------------------
    def _replan_round(self, ctx: "_ExecutionCtx",
                      submitted: Dict[int, ExecutionTask]) -> None:
        """One phase-boundary replan: score the curve (the same boundary
        where ``score_checkpoints`` dispatches), hand the landed/in-flight
        partition sets to the replanner, and patch the live queue against
        the directive — cancel-what-changed, keep-what-still-helps, add
        what's newly needed.  Any failure keeps the static plan."""
        ledger = ctx.ledger
        if ledger is not None:
            ledger.score_checkpoints()
        landed = frozenset(ledger._landed) if ledger is not None else frozenset()
        inflight = frozenset(t.proposal.partition for t in submitted.values())
        try:
            directive = ctx.replanner(landed, inflight)
        except Exception:
            self._sensor_replan["fallbacks"].inc()
            return
        if directive is None or directive.proposals is None:
            self._sensor_replan["fallbacks"].inc()
            return

        now = self._clock_ms()
        new_by_part = {p.partition: p for p in directive.proposals}
        all_tasks = (ctx.plan.inter_broker_tasks + ctx.plan.intra_broker_tasks
                     + ctx.plan.leadership_tasks)
        pending_by_part: Dict[int, List[ExecutionTask]] = {}
        for t in all_tasks:
            if t.state == TaskState.PENDING:
                pending_by_part.setdefault(t.proposal.partition, []).append(t)
        cancelled = kept = 0
        covered = set()
        for part, tasks in pending_by_part.items():
            np_ = new_by_part.get(part)
            if np_ is not None and np_.new_replicas == tasks[0].proposal.new_replicas:
                kept += len(tasks)
                covered.add(part)
            else:
                for t in tasks:
                    t.cancel(now)
                    ctx.tm.finished(t)
                    cancelled += 1
        add_props = [p for part, p in new_by_part.items()
                     if part not in covered and part not in inflight]
        added_tasks: List[ExecutionTask] = []
        if add_props:
            next_id = max((t.execution_id for t in all_tasks), default=-1) + 1
            planner = ExecutionTaskPlanner(ctx.strategy,
                                           first_execution_id=next_id)
            addition = planner.plan(add_props, None)
            added_tasks = (addition.inter_broker_tasks
                           + addition.intra_broker_tasks
                           + addition.leadership_tasks)
            ctx.plan.inter_broker_tasks.extend(addition.inter_broker_tasks)
            ctx.plan.intra_broker_tasks.extend(addition.intra_broker_tasks)
            ctx.plan.leadership_tasks.extend(addition.leadership_tasks)
            for b, ts in addition.tasks_by_broker.items():
                ctx.plan.tasks_by_broker.setdefault(b, []).extend(ts)
        if ledger is not None:
            ledger.replan_rebase(added_tasks, cancelled, kept,
                                 scorer=directive.scorer)
        if ctx.journal is not None:
            ctx.journal.replan(added_tasks, cancelled, kept, now)
        self._sensor_replan["rounds"].inc()
        self._sensor_replan["cancelled"].inc(cancelled)
        self._sensor_replan["kept"].inc(kept)
        self._sensor_replan["added"].inc(len(added_tasks))
        TRACE.annotate(replan_cancelled=cancelled, replan_kept=kept,
                       replan_added=len(added_tasks))

    def _run_inter_broker_phase(self, ctx: "_ExecutionCtx", span=None,
                                adopted: Optional[Dict[int, ExecutionTask]] = None,
                                polls_budget: Optional[int] = None
                                ) -> Tuple[int, bool]:
        tm, ledger, journal = ctx.tm, ctx.ledger, ctx.journal
        partition_names = ctx.partition_names
        max_polls = polls_budget if polls_budget is not None else ctx.max_polls
        # Resume path: adopt the tasks that were in flight at the crash —
        # their reassignments persist in the cluster, so the ordinary
        # completion checks below pick them up.
        submitted: Dict[int, ExecutionTask] = {
            eid: t for eid, t in (adopted or {}).items()
            if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION}
        polls = 0
        batches = 0
        can_replan = (ctx.replanner is not None
                      and ctx.replan_interval_polls > 0 and replan_enabled())
        crashed = False
        try:
            while polls < max_polls:
                if self._stop_requested:
                    # Graceful stop: let in-flight tasks finish, admit no more;
                    # force-stop also cancels in-flight (handled via admin above).
                    for t in list(submitted.values()):
                        if self._force_stop and t.state == TaskState.IN_PROGRESS:
                            now = self._clock_ms()
                            t.aborting(now)
                            t.aborted(now)
                            tm.finished(t)
                            del submitted[t.execution_id]
                    if self._force_stop:
                        return polls, True
                else:
                    new_tasks = tm.next_inter_broker_tasks()
                    if new_tasks:
                        batches += 1
                        now = self._clock_ms()
                        runnable: List[ExecutionTask] = []
                        for t in new_tasks:
                            if self._circuit_open(t.brokers_involved(), now):
                                # Circuit open on a destination: abort the
                                # task now (a later replan round re-plans
                                # the partition) instead of wedging.
                                t.cancel(now)
                                tm.finished(t)
                            else:
                                runnable.append(t)
                        if runnable:
                            reqs = []
                            for t in runnable:
                                t.in_progress(now)
                                reqs.append(ReassignmentRequest(
                                    tp=partition_names[t.proposal.partition],
                                    new_replicas=self._target_replicas(t)))
                            batch_brokers = {b for t in runnable
                                             for b in t.brokers_involved()}
                            if self._call_admin(
                                    lambda: self._admin.alter_partition_reassignments(reqs),
                                    batch_brokers):
                                for t in runnable:
                                    submitted[t.execution_id] = t
                                if journal is not None:
                                    journal.flush()
                            else:
                                now2 = self._clock_ms()
                                for t in runnable:
                                    t.aborting(now2)
                                    t.aborted(now2)
                                    tm.finished(t)

                ongoing = self._admin.ongoing_reassignments()
                cluster = self._metadata.cluster()
                by_tp = {p.tp: p for p in cluster.partitions}
                alive = set(cluster.alive_broker_ids())
                for t in list(submitted.values()):
                    tp = tuple(partition_names[t.proposal.partition])
                    target = set(self._target_replicas(t))
                    part = by_tp.get(tp)
                    if tp not in ongoing and part is not None and \
                            set(part.replicas) == target:
                        t.completed(self._clock_ms())
                        tm.finished(t)
                        del submitted[t.execution_id]
                    elif not target <= alive:
                        # Destination broker died mid-move (Executor.java:1548).
                        if t.state == TaskState.IN_PROGRESS:
                            t.kill(self._clock_ms())
                            tm.finished(t)
                            self._admin.cancel_reassignments([tp])
                            del submitted[t.execution_id]
                polls += 1
                self._poll_tick(ctx)
                if ctx.metrics_fn is not None and self._adjuster_enabled:
                    self._adjust_concurrency(tm, ctx.metrics_fn, ledger, journal)
                if can_replan and polls % ctx.replan_interval_polls == 0 \
                        and not self._stop_requested:
                    self._replan_round(ctx, submitted)
                if not submitted:
                    pending = [t for t in ctx.plan.inter_broker_tasks
                               if t.state == TaskState.PENDING]
                    if not pending or self._stop_requested:
                        return polls, False
                if ctx.poll_interval_s:
                    time.sleep(ctx.poll_interval_s)
            return polls, True
        except SimulatedCrash:
            crashed = True
            raise
        finally:
            # A (simulated) process death runs no phase finalization.
            if not crashed:
                if ledger is not None:
                    ledger.phase_finished(polls=polls, batches=batches)
                if journal is not None:
                    journal.phase_end("inter_broker", self._clock_ms(),
                                      polls, batches)
                if span is not None:
                    span.annotate(polls=polls, batches=batches)
                    if ledger is not None:
                        span.annotate(bytes_moved=ledger.bytes_moved)

    def _run_intra_broker_phase(self, ctx: "_ExecutionCtx", span=None) -> None:
        tm, ledger, journal = ctx.tm, ctx.ledger, ctx.journal
        partition_names = ctx.partition_names
        batches = 0
        crashed = False
        try:
            while True:
                tasks = tm.next_intra_broker_tasks()
                if not tasks:
                    break
                batches += 1
                moves = []
                now = self._clock_ms()
                for t in tasks:
                    t.in_progress(now)
                    for broker, _old_disk, new_disk in t.proposal._intra_broker_moves():
                        logdir = self._logdir_by_disk.get(new_disk, f"/logdir-{new_disk}")
                        moves.append((partition_names[t.proposal.partition], broker, logdir))
                batch_brokers = {b for t in tasks for b in t.brokers_involved()}
                ok = self._call_admin(
                    lambda: self._admin.alter_replica_logdirs(moves),
                    batch_brokers)
                now = self._clock_ms()
                for t in tasks:
                    if ok:
                        t.completed(now)
                    else:
                        t.aborting(now)
                        t.aborted(now)
                    tm.finished(t)
                self._poll_tick(ctx)
        except SimulatedCrash:
            crashed = True
            raise
        finally:
            if not crashed:
                if ledger is not None:
                    ledger.phase_finished(batches=batches)
                if journal is not None:
                    journal.phase_end("intra_broker", self._clock_ms(),
                                      0, batches)
                if span is not None:
                    span.annotate(batches=batches)

    def _run_leadership_phase(self, ctx: "_ExecutionCtx", span=None,
                              adopted: Optional[Dict[int, ExecutionTask]] = None
                              ) -> None:
        tm, ledger, journal = ctx.tm, ctx.ledger, ctx.journal
        partition_names = ctx.partition_names
        batches = 0
        total_polls = 0
        # Resume path: leadership tasks that were in flight at the crash
        # already have their preferred-order reassignments submitted (or
        # applied) — drive them through the wait/elect cycle WITHOUT
        # re-submitting.
        carried = [t for t in (adopted or {}).values()
                   if t.task_type == TaskType.LEADER_ACTION
                   and t.state == TaskState.IN_PROGRESS]
        crashed = False
        try:
            while not self._stop_requested:
                resubmit = not carried
                if carried:
                    tasks, carried = carried, []
                else:
                    tasks = tm.next_leadership_tasks()
                    if not tasks:
                        break
                batches += 1
                # Make the proposal's leader the preferred replica then trigger a
                # batched preferred-leader election (moveLeaderships,
                # Executor.java:1373-1399).
                now = self._clock_ms()
                if resubmit:
                    reqs = [ReassignmentRequest(
                        tp=partition_names[t.proposal.partition],
                        new_replicas=self._target_replicas(t))
                        for t in tasks]
                    for t in tasks:
                        t.in_progress(now)
                    batch_brokers = {b for t in tasks
                                     for b in t.brokers_involved()}
                    if not self._call_admin(
                            lambda: self._admin.alter_partition_reassignments(reqs),
                            batch_brokers):
                        now2 = self._clock_ms()
                        for t in tasks:
                            t.aborting(now2)
                            t.aborted(now2)
                            tm.finished(t)
                        self._poll_tick(ctx)
                        continue
                    if journal is not None:
                        journal.flush()
                polls = 0
                deadline = time.monotonic() + self._leader_movement_timeout_ms / 1000.0
                while self._admin.ongoing_reassignments() and polls < ctx.max_polls \
                        and not self._force_stop and time.monotonic() < deadline:
                    polls += 1
                    if ctx.poll_interval_s:
                        time.sleep(ctx.poll_interval_s)
                total_polls += polls
                timed_out = (polls >= ctx.max_polls or self._force_stop
                             or (self._admin.ongoing_reassignments()
                                 and time.monotonic() >= deadline))
                if not timed_out:
                    self._call_admin(
                        lambda: self._admin.elect_leaders(
                            [partition_names[t.proposal.partition]
                             for t in tasks]),
                        {b for t in tasks for b in t.brokers_involved()})
                else:
                    # Don't leave the preferred-order reassignments of killed
                    # tasks in flight (same cleanup as the inter-broker DEAD
                    # path; the reference deletes the reassignment znodes).
                    self._admin.cancel_reassignments(
                        [partition_names[t.proposal.partition] for t in tasks])
                now = self._clock_ms()
                for t in tasks:
                    if timed_out:
                        t.kill(now)
                    else:
                        t.completed(now)
                    tm.finished(t)
                self._poll_tick(ctx)
                if timed_out:
                    break
        except SimulatedCrash:
            crashed = True
            raise
        finally:
            if not crashed:
                if ledger is not None:
                    ledger.phase_finished(polls=total_polls, batches=batches)
                if journal is not None:
                    journal.phase_end("leadership", self._clock_ms(),
                                      total_polls, batches)
                if span is not None:
                    span.annotate(polls=total_polls, batches=batches)


@dataclasses.dataclass
class _ExecutionCtx:
    """Everything one execution's phase loop threads through — built once
    by ``execute_proposals`` (fresh run) or ``resume`` (journal replay),
    consumed by ``_drive`` and the phase runners."""

    plan: ExecutionPlan
    tm: ExecutionTaskManager
    ledger: Optional[ExecutionLedger]
    journal: Optional[ExecutionJournal]
    throttle: ReplicationThrottleHelper
    partition_names: Sequence[Tp]
    max_polls: int
    poll_interval_s: float
    metrics_fn: Optional[Callable[[], Dict[int, Dict[str, float]]]]
    strategy: Optional[ReplicaMovementStrategy]
    replanner: Optional[Replanner]
    replan_interval_polls: int
    crash_after_polls: Optional[int]
