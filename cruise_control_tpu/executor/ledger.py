"""Execution ledger: per-task lifecycle event log + live progress accounting.

The reference answers "how is the rebalance going?" through
``ExecutorState``'s executor substate (in-progress/finished data movement,
per-phase task counts — ExecutorState.java:331-389).  The ledger is that
surface plus the measurement substrate the executor perf work is judged
against: every task transition lands here (via ``ExecutionTask.observer``),
and once per wait-loop poll the executor calls :meth:`poll` so the ledger
can checkpoint bytes-moved / in-flight / per-broker occupancy over time.

Time is whatever clock the executor runs on (``Executor(clock_ms=...)``) —
wall time against a real cluster, virtual time against
``SimulatedClusterAdmin`` — so time-to-balanced curves from a simulated
7k-broker fleet read in fleet seconds, not host microseconds.

Balancedness over time: when a :class:`PlacementScorer
<cruise_control_tpu.analyzer.optimizer.PlacementScorer>` is attached, each
checkpoint snapshots the *landed-partition* mask (all of a partition's
tasks completed).  Scoring is deferred and batched: one compile-cached
dispatch over all unscored checkpoints at phase boundaries
(:meth:`score_checkpoints`), never per poll.

The ledger is purely observational — with it off the executor produces a
bit-identical ``ExecutionResult`` (pinned in tests/test_execution_ledger).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.common.timeseries import (REPLAN_ADDED_SERIES,
                                                  REPLAN_CANCELLED_SERIES,
                                                  REPLAN_KEPT_SERIES,
                                                  TASK_DURATION_SERIES,
                                                  TELEMETRY)
from cruise_control_tpu.executor.task import ExecutionTask, TaskState, TaskType

#: Checkpoint ring target: when full, thin to every other checkpoint and
#: double the sampling stride — bounded memory at any execution length
#: while keeping the curve's shape.
MAX_CHECKPOINTS = 256

#: to_dict(verbose=True) caps the event log it returns (the full log stays
#: in memory for the lifetime of the ledger).
MAX_EVENTS_IN_DUMP = 2048


class ExecutionLedger:
    def __init__(self, clock_ms, throttle_rate_bytes_per_sec: Optional[int] = None,
                 scorer=None, max_checkpoints: int = MAX_CHECKPOINTS,
                 event_sink=None):
        self._clock_ms = clock_ms
        self._throttle_rate = throttle_rate_bytes_per_sec
        self._scorer = scorer
        self._max_checkpoints = max(8, max_checkpoints)
        self._stride = 1          # checkpoint every Nth eligible poll
        self._polls_since_checkpoint = 0
        # Optional pass-through of every task transition (the execution
        # journal's hook; None costs nothing).
        self._event_sink = event_sink

        self.events: List[dict] = []
        self.checkpoints: List[dict] = []
        self.phases: List[dict] = []
        self.replans: List[dict] = []
        self.adjuster_decisions: Dict[str, int] = {
            "halve": 0, "double": 0, "hold": 0}
        self.task_durations_ms: Dict[str, List[int]] = {
            t.value: [] for t in TaskType}

        self.total_tasks = 0
        self.total_bytes = 0
        self.bytes_moved = 0
        self.bytes_in_flight = 0
        self.counts: Dict[str, int] = {s.value: 0 for s in TaskState}
        self.started_ms: Optional[int] = None
        self.last_event_ms: Optional[int] = None
        self.finished_ms: Optional[int] = None
        self.inflight_by_broker: Dict[int, int] = {}
        self.polls = 0

        # Landed-partition tracking for the balancedness curve: a partition
        # "lands" when every task referencing it completed; dead/aborted
        # tasks pin theirs at the pre-execution placement forever.
        self._outstanding_by_partition: Dict[int, int] = {}
        self._landed: set = set()
        self._stuck: set = set()
        # Partitions whose task was cancelled before it started (replan /
        # force-stop): they never moved, so draining their outstanding count
        # must not land them at the "after" placement.
        self._cancelled: set = set()

    # -- wiring --------------------------------------------------------------
    def attach(self, plan) -> None:
        """Hook every task of the plan and seed the totals."""
        now = self._clock_ms()
        self.started_ms = now
        tasks = (plan.inter_broker_tasks + plan.intra_broker_tasks
                 + plan.leadership_tasks)
        self.total_tasks = len(tasks)
        self.total_bytes = plan.total_bytes
        for t in tasks:
            t.observer = self.observe
            self.counts[t.state.value] += 1
            p = t.proposal.partition
            self._outstanding_by_partition[p] = \
                self._outstanding_by_partition.get(p, 0) + 1

    def set_event_sink(self, sink) -> None:
        """Attach/detach the per-transition pass-through (journal hook)."""
        self._event_sink = sink

    def set_clock(self, clock_ms) -> None:
        """Swap the clock source (resume replaces the journal-replay clock
        with the executor's live clock once replay is done)."""
        self._clock_ms = clock_ms

    def set_scorer(self, scorer) -> None:
        """Swap the balancedness scorer.  Replan rebasing swaps in a scorer
        whose "before" is the partially-moved cluster and whose "after" is
        the re-solved target, so post-replan checkpoints score against the
        plan actually being executed."""
        self._scorer = scorer

    def replan_rebase(self, added_tasks, cancelled: int, kept: int,
                      scorer=None) -> None:
        """Rebase the ledger on a live replan: hook the added tasks, grow
        the totals, and re-dirty their partitions (a landed/stuck/cancelled
        partition that the new plan moves again is live work once more).
        Cancellations arrive separately through observe() as
        PENDING→ABORTED transitions."""
        now = self._clock_ms()
        self.replans.append({"tMs": now, "poll": self.polls,
                             "cancelled": cancelled, "kept": kept,
                             "added": len(added_tasks)})
        # Replan publish boundary: the churn triple the SLA rollup's
        # cancelled/kept/added ratio is computed from.
        TELEMETRY.record(REPLAN_CANCELLED_SERIES, cancelled, t_ms=now)
        TELEMETRY.record(REPLAN_KEPT_SERIES, kept, t_ms=now)
        TELEMETRY.record(REPLAN_ADDED_SERIES, len(added_tasks), t_ms=now)
        if scorer is not None:
            self._scorer = scorer
        for t in added_tasks:
            t.observer = self.observe
            self.counts[t.state.value] += 1
            self.total_tasks += 1
            self.total_bytes += t.bytes_to_move
            p = t.proposal.partition
            self._outstanding_by_partition[p] = max(
                0, self._outstanding_by_partition.get(p, 0)) + 1
            self._landed.discard(p)
            self._stuck.discard(p)
            self._cancelled.discard(p)

    # -- event intake --------------------------------------------------------
    def observe(self, task: ExecutionTask, old_state: TaskState,
                new_state: TaskState, now_ms: int) -> None:
        self.counts[old_state.value] -= 1
        self.counts[new_state.value] += 1
        self.last_event_ms = now_ms
        b = task.bytes_to_move
        if new_state == TaskState.IN_PROGRESS:
            self.bytes_in_flight += b
        elif new_state == TaskState.COMPLETED:
            self.bytes_in_flight -= b
            self.bytes_moved += b
            self.task_durations_ms[task.task_type.value].append(
                max(0, task.end_time_ms - task.start_time_ms))
            SENSORS.histogram(
                "Executor.task-duration-seconds",
                labels={"type": task.task_type.value},
                help="Completed execution task duration, by task type"
            ).observe(max(0, task.end_time_ms - task.start_time_ms) / 1000.0)
            TELEMETRY.record(TASK_DURATION_SERIES,
                             max(0, task.end_time_ms - task.start_time_ms),
                             t_ms=now_ms)
            self._land(task.proposal.partition)
        elif new_state in (TaskState.ABORTED, TaskState.DEAD):
            if old_state in (TaskState.IN_PROGRESS, TaskState.ABORTING):
                # ABORTING→ABORTED: in-flight bytes were added at IN_PROGRESS
                # and not yet released (ABORTING releases nothing).
                self.bytes_in_flight -= b
                self._stuck.add(task.proposal.partition)
            else:
                # PENDING→ABORTED cancellation: the task never carried
                # in-flight bytes and its work leaves the plan entirely —
                # shrink the plan total so offTargetBytes still converges.
                self.total_bytes -= b
                self._cancelled.add(task.proposal.partition)
                self._land(task.proposal.partition)
        self.events.append({
            "id": task.execution_id, "type": task.task_type.value,
            "partition": task.proposal.partition,
            "from": old_state.value, "to": new_state.value,
            "tMs": now_ms, "bytes": b})
        if self._event_sink is not None:
            self._event_sink(task, old_state, new_state, now_ms)

    def _land(self, partition: int) -> None:
        n = self._outstanding_by_partition.get(partition, 0) - 1
        self._outstanding_by_partition[partition] = n
        if n <= 0 and partition not in self._stuck \
                and partition not in self._cancelled:
            self._landed.add(partition)

    def adjuster_decision(self, decision: str) -> None:
        self.adjuster_decisions[decision] = \
            self.adjuster_decisions.get(decision, 0) + 1

    # -- phases --------------------------------------------------------------
    def phase_started(self, phase: str) -> None:
        self.phases.append({"phase": phase, "startMs": self._clock_ms(),
                            "endMs": None, "polls": 0, "batches": 0})

    def phase_finished(self, polls: int = 0, batches: int = 0) -> None:
        if self.phases and self.phases[-1]["endMs"] is None:
            self.phases[-1].update(endMs=self._clock_ms(), polls=polls,
                                   batches=batches)

    def finished(self) -> None:
        self.finished_ms = self._clock_ms()

    # -- per-poll checkpointing ----------------------------------------------
    def poll(self, task_manager=None, force: bool = False) -> None:
        """Called once per executor wait-loop iteration.  Snapshots the
        in-flight broker map; appends a curve checkpoint when progress was
        made since the last one (stride-sampled so long executions thin
        themselves instead of growing without bound).  ``force`` bypasses
        the stride so the terminal state always lands on the curve."""
        self.polls += 1
        if task_manager is not None:
            self.inflight_by_broker = task_manager.inflight_by_broker()
        last = self.checkpoints[-1] if self.checkpoints else None
        progressed = last is None or (
            last["completed"] != self.counts[TaskState.COMPLETED.value]
            or last["dead"] != self.counts[TaskState.DEAD.value]
            or last["aborted"] != self.counts[TaskState.ABORTED.value])
        if not progressed:
            return
        self._polls_since_checkpoint += 1
        if self._polls_since_checkpoint < self._stride and not force:
            return
        self._polls_since_checkpoint = 0
        self._checkpoint()

    def _checkpoint(self) -> None:
        cp = {
            "tMs": self._clock_ms(),
            "poll": self.polls,
            "completed": self.counts[TaskState.COMPLETED.value],
            "dead": self.counts[TaskState.DEAD.value],
            "aborted": self.counts[TaskState.ABORTED.value],
            "inProgress": self.counts[TaskState.IN_PROGRESS.value],
            "bytesMoved": self.bytes_moved,
            "bytesInFlight": self.bytes_in_flight,
            "offTargetBytes": self.total_bytes - self.bytes_moved,
            "landedPartitions": len(self._landed),
            "maxBrokerInFlight": max(self.inflight_by_broker.values(),
                                     default=0),
            "balancedness": None,
        }
        if self._scorer is not None:
            cp["_landed_set"] = frozenset(self._landed)
        self.checkpoints.append(cp)
        # Checkpoint publish boundary: the progress curve's host scalars
        # (the balancedness point lands later, in score_checkpoints — the
        # batched phase-boundary scoring keeps this path fetch-free).
        TELEMETRY.record("executor.bytes-moved", self.bytes_moved,
                         t_ms=cp["tMs"])
        TELEMETRY.record("executor.off-target-bytes", cp["offTargetBytes"],
                         t_ms=cp["tMs"])
        if len(self.checkpoints) > self._max_checkpoints:
            self.checkpoints = self.checkpoints[::2]
            self._stride *= 2

    def score_checkpoints(self) -> None:
        """Batch-score every unscored checkpoint's balancedness — ONE
        compile-cached device dispatch for the whole batch (called at phase
        boundaries and end-of-execution, never per poll)."""
        if self._scorer is None:
            return
        pending = [cp for cp in self.checkpoints
                   if cp["balancedness"] is None and "_landed_set" in cp]
        if not pending:
            return
        scores = self._scorer.score_landed([cp["_landed_set"]
                                            for cp in pending])
        for cp, s in zip(pending, scores):
            cp["balancedness"] = float(s)
            del cp["_landed_set"]
            # Scored at the phase boundary, stamped with the checkpoint's
            # own (possibly virtual) time — the SLA balancedness series'
            # executor-side source.
            TELEMETRY.record("executor.balancedness", float(s),
                             t_ms=cp["tMs"])

    # -- derived metrics -----------------------------------------------------
    @property
    def elapsed_ms(self) -> int:
        if self.started_ms is None:
            return 0
        end = self.finished_ms if self.finished_ms is not None \
            else self.last_event_ms
        return max(0, (end or self.started_ms) - self.started_ms)

    @property
    def movement_rate_bytes_per_sec(self) -> float:
        """Observed rate from bytes completed over elapsed time (0 until the
        first completion)."""
        ms = self.elapsed_ms
        return self.bytes_moved / (ms / 1000.0) if ms > 0 else 0.0

    @property
    def eta_seconds(self) -> float:
        """Remaining bytes at the observed rate; -1 while rate is unknown."""
        rate = self.movement_rate_bytes_per_sec
        if rate <= 0:
            return -1.0
        return (self.total_bytes - self.bytes_moved) / rate

    @property
    def throttle_utilization(self) -> float:
        """Observed movement rate over the throttle-implied ceiling: the
        throttle caps each busy broker at the configured rate, so ceiling =
        rate × brokers-with-in-flight-work.  -1 when unthrottled/idle."""
        if not self._throttle_rate:
            return -1.0
        busy = len(self.inflight_by_broker)
        if busy == 0:
            return -1.0
        return self.movement_rate_bytes_per_sec / \
            (self._throttle_rate * busy)

    @property
    def max_broker_in_flight(self) -> int:
        return max(self.inflight_by_broker.values(), default=0)

    @property
    def balancedness(self) -> float:
        """Latest scored checkpoint's balancedness (-1 until one exists)."""
        for cp in reversed(self.checkpoints):
            if cp["balancedness"] is not None:
                return float(cp["balancedness"])
        return -1.0

    # -- dump ----------------------------------------------------------------
    def _duration_summary(self) -> Dict[str, dict]:
        out = {}
        for t, ds in self.task_durations_ms.items():
            if not ds:
                continue
            out[t] = {"count": len(ds),
                      "meanMs": sum(ds) / len(ds),
                      "maxMs": max(ds),
                      "minMs": min(ds)}
        return out

    def to_dict(self, verbose: bool = False) -> Dict[str, object]:
        out: Dict[str, object] = {
            "totalTasks": self.total_tasks,
            "taskCounts": dict(self.counts),
            "totalBytes": self.total_bytes,
            "bytesMoved": self.bytes_moved,
            "bytesInFlight": self.bytes_in_flight,
            "movementRateBytesPerSec": self.movement_rate_bytes_per_sec,
            "etaSeconds": self.eta_seconds,
            "throttleRateBytesPerSec": self._throttle_rate,
            "throttleUtilization": self.throttle_utilization,
            "adjusterDecisions": dict(self.adjuster_decisions),
            "startedMs": self.started_ms,
            "finishedMs": self.finished_ms,
            "elapsedMs": self.elapsed_ms,
            "polls": self.polls,
            "landedPartitions": len(self._landed),
            "balancedness": self.balancedness,
            "phases": [dict(p) for p in self.phases],
            "replans": [dict(r) for r in self.replans],
            "taskDurations": self._duration_summary(),
        }
        if verbose:
            out["perBrokerInFlight"] = {
                str(b): n for b, n in sorted(self.inflight_by_broker.items())}
            out["checkpoints"] = [
                {k: v for k, v in cp.items() if not k.startswith("_")}
                for cp in self.checkpoints]
            out["events"] = self.events[-MAX_EVENTS_IN_DUMP:]
        return out
