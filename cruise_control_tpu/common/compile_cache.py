"""Persistent XLA compilation cache wiring + restart-aware compile markers.

JAX ships a content-addressed on-disk compilation cache: point
``jax_compilation_cache_dir`` at a directory and every XLA executable is
persisted after its first build, so a process restart pays deserialization
(~100s of ms) instead of a full compile (~10s of seconds for the fused
goal-stack programs).  The knob is off by default and its entry-size /
compile-time floors would skip the small CPU programs the test suite
builds, so this module owns the one true way to switch it on.

The optimizer's ``GoalResult.fresh_compile`` flag is derived from a
python-dict cache miss, which cannot tell a warm disk hit from a cold
build — every goal in a restarted process would report a "fresh" compile
that actually cost milliseconds.  Sidecar marker files (one empty file per
program token, kept *inside* the cache dir so wiping the cache wipes the
markers with it) record which programs some process already built; the
optimizer reports ``fresh_compile=True`` only for programs with no marker.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Iterable, Optional

_LOG = logging.getLogger(__name__)

#: Environment override for the cache directory.  Takes precedence over the
#: ``compile.cache.dir`` config key; the sentinels below disable persistence.
ENV_CACHE_DIR = "CRUISE_COMPILE_CACHE_DIR"

_DISABLE_SENTINELS = ("off", "none", "false", "0")

_enabled_dir: Optional[str] = None


def default_cache_dir() -> str:
    """Default location under the per-user app data dir (XDG cache dir)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    return os.path.join(base, "cruise-control-tpu", "compile-cache")


def resolve_cache_dir(configured: str = "") -> Optional[str]:
    """Resolve the active cache dir: env override > config value > default.

    Returns None (persistence disabled) when the winning value is one of
    the disable sentinels ('off', 'none', 'false', '0').
    """
    raw = os.environ.get(ENV_CACHE_DIR)
    if raw is None:
        raw = configured or ""
    raw = raw.strip()
    if raw.lower() in _DISABLE_SENTINELS:
        return None
    return raw or default_cache_dir()


def enable_persistent_cache(path: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) and drop the compile-time / entry-size floors so even small
    CPU programs persist.  Idempotent per path; returns the active dir."""
    global _enabled_dir
    if path is None:
        path = default_cache_dir()
    path = os.path.abspath(path)
    if _enabled_dir == path:
        return _enabled_dir
    import jax
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_enable_compilation_cache", True)
    try:
        # The cache module latches "no cache" after the first compile that
        # ran without a dir configured; enabling lazily (env-triggered from
        # the optimizer, after backend init already compiled something)
        # needs the latch reset or the new dir is silently ignored.
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception as e:  # noqa: BLE001 — private API; persistence only
        _LOG.warning("compilation cache reset unavailable (%s); persistence "
                     "may require enabling before first compile", e)
    _enabled_dir = path
    _LOG.info("persistent compile cache enabled at %s", path)
    return _enabled_dir


def maybe_enable_from_env() -> Optional[str]:
    """Enable the cache when ``CRUISE_COMPILE_CACHE_DIR`` is set.

    Library entry points (bench, tests, notebooks) hit this lazily from the
    optimizer; the service wires the ``compile.cache.dir`` config key
    through app startup instead."""
    if _enabled_dir is not None:
        return _enabled_dir
    raw = os.environ.get(ENV_CACHE_DIR)
    if raw is None:
        return None
    raw = raw.strip()
    if not raw or raw.lower() in _DISABLE_SENTINELS:
        return None
    return enable_persistent_cache(raw)


def cache_dir() -> Optional[str]:
    """The directory persistence is currently enabled at, or None."""
    return _enabled_dir


# ---------------------------------------------------------------------------
# Compile markers (restart-aware fresh_compile)
# ---------------------------------------------------------------------------

def program_token(kind: str, key: object, arg_signature: Iterable) -> str:
    """Stable token for one jitted program.

    ``key`` is the optimizer's python-cache key (specs, constraint, widths,
    ... — all dataclasses of primitives, so their repr is deterministic
    across processes); ``arg_signature`` captures the traced-argument
    shapes/dtypes the python key does not.  jax version and backend are
    folded in because the persisted executable is specific to both.
    """
    import jax
    payload = repr((kind, key, tuple(arg_signature), jax.__version__,
                    jax.default_backend()))
    return hashlib.sha256(payload.encode()).hexdigest()


def _marker_file(token: str) -> str:
    assert _enabled_dir is not None
    return os.path.join(_enabled_dir, "markers", token + ".seen")


def seen(token: str) -> bool:
    """True when some process already compiled (and persisted) ``token``."""
    if _enabled_dir is None:
        return False
    return os.path.exists(_marker_file(token))


def mark(token: str) -> None:
    """Record that ``token`` has been compiled by this process."""
    if _enabled_dir is None:
        return
    path = _marker_file(token)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a"):
            pass
    except OSError as e:  # marker loss only costs a pessimistic report
        _LOG.warning("could not write compile marker %s: %s", path, e)


# ---------------------------------------------------------------------------
# AOT executable shipping (CRUISE_AOT_PRELOWER)
# ---------------------------------------------------------------------------
# The artifact store for ahead-of-time-compiled executables.  This is a
# DIFFERENT code path from jax's own ``jax_compilation_cache_dir``
# machinery on purpose: this jaxlib segfaults inside
# ``compilation_cache.put_executable_and_time`` when serializing the large
# goal-stack executables (tests/conftest.py), while
# ``jax.experimental.serialize_executable.serialize`` on an already-built
# ``jax.stages.Compiled`` does not go through that path.  Artifacts land in
# an ``aot/`` subdir of the persistent cache dir (or the default XDG dir
# when the jax cache is not enabled — shipping works standalone), one
# ``<token>.aotx`` per program, written atomically.

SHIP_COUNTERS = {"shipped": 0, "shipped_bytes": 0, "hits": 0, "failed": 0}


def shipping_dir() -> Optional[str]:
    """The AOT artifact directory (created on demand), or None when it
    cannot be created.  Uses the enabled persistent-cache dir when one is
    active, else resolves the default location WITHOUT touching jax's own
    compilation-cache config (see the segfault note above)."""
    base = _enabled_dir or resolve_cache_dir()
    if base is None:
        return None
    path = os.path.join(os.path.abspath(base), "aot")
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        _LOG.warning("could not create AOT shipping dir %s: %s", path, e)
        return None
    return path


def _artifact_file(token: str) -> Optional[str]:
    d = shipping_dir()
    return None if d is None else os.path.join(d, token + ".aotx")


def ship_executable(token: str, compiled) -> int:
    """Serialize an AOT-compiled executable into the artifact store.

    Returns the bytes written (the ``executables-shipped-bytes`` sensor's
    unit); 0 when the artifact already exists (shipped once, by design),
    when serialization is unavailable on this backend, or when the store
    cannot be written — shipping is an optimization, never a correctness
    gate."""
    path = _artifact_file(token)
    if path is None:
        return 0
    if os.path.exists(path):
        SHIP_COUNTERS["hits"] += 1
        return 0
    try:
        from jax.experimental import serialize_executable as se
        payload = se.serialize(compiled)
        blob = payload[0] if isinstance(payload, tuple) else payload
        data = bytes(blob)
    except Exception as e:  # noqa: BLE001 — backend/version specific
        SHIP_COUNTERS["failed"] += 1
        _LOG.warning("could not serialize AOT executable %s: %s", token, e)
        return 0
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except OSError as e:
        SHIP_COUNTERS["failed"] += 1
        _LOG.warning("could not ship AOT executable %s: %s", path, e)
        return 0
    SHIP_COUNTERS["shipped"] += 1
    SHIP_COUNTERS["shipped_bytes"] += len(data)
    return len(data)


def shipped_bytes(token: str) -> int:
    """Size of ``token``'s shipped artifact, or 0 when absent."""
    path = _artifact_file(token)
    try:
        return os.path.getsize(path) if path else 0
    except OSError:
        return 0
