"""Lightweight span tracing for admin operations.

The reference logs operation progress through ``OperationLogger`` and
exposes step durations via per-sensor timers; debugging a slow rebalance
still means correlating log lines by hand.  Here every admin operation
builds one *trace*: a tree of named spans (monitor snapshot → model build →
per-goal fixpoint → proposal materialization → executor phases) with wall
durations and small attribute dicts (steps, actions, fresh_compile, task
counts).

Design constraints:
- Zero hard dependencies, no background thread, O(1) per span.
- Thread-local span stack: concurrent operations (one per UserTask worker
  thread) never interleave spans.
- Bounded memory: finished ROOT traces land in a ring buffer
  (``maxlen=256``); children live only inside their root's tree.
- Post-hoc children via ``record()``: the fused goal-stack optimizer gets
  per-goal durations back from a single device dispatch AFTER the fact, so
  per-goal spans are recorded retroactively rather than via ``with``.

Surfaces: ``GET /trace?task_id=...`` (api/server.py), per-task attachment
in ``UserTaskManager``, and a rollup inside ``/state``'s Sensors block.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

_TRACE_RING = 256


class Span:
    """One timed node in a trace tree."""

    __slots__ = ("name", "start_ms", "duration_ms", "attrs", "children",
                 "trace_id", "_t0")

    def __init__(self, name: str, start_ms: float,
                 attrs: Dict[str, Any]):
        self.name = name
        self.start_ms = start_ms
        self.duration_ms: float = 0.0
        self.attrs = attrs
        self.children: List["Span"] = []
        self.trace_id: Optional[str] = None  # set on roots at finish

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "startMs": round(self.start_ms, 3),
            "durationMs": round(self.duration_ms, 3),
        }
        if self.trace_id is not None:
            d["traceId"] = self.trace_id
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class _SpanCtx:
    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def annotate(self, **attrs: Any) -> None:
        self._span.attrs.update(attrs)

    @property
    def trace_id(self) -> Optional[str]:
        """The trace id, set at exit when this span turned out to be a
        root; None while open or for child spans."""
        return self._span.trace_id

    def __enter__(self) -> "_SpanCtx":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self._span.attrs.setdefault("error", exc_type.__name__)
        self._tracer._finish(self._span)
        return False


class Tracer:
    """Thread-local span stack + bounded ring of finished root traces."""

    def __init__(self, ring: int = _TRACE_RING):
        self._local = threading.local()
        self._lock = threading.Lock()
        self._finished: Deque[Dict[str, Any]] = deque(maxlen=ring)
        self._by_id: Dict[str, Dict[str, Any]] = {}
        self._seq = 0

    def _stack(self) -> List[Span]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- span lifecycle -----------------------------------------------------
    def span(self, name: str, **attrs: Any) -> _SpanCtx:
        s = Span(name, time.time() * 1000.0, attrs)
        st = self._stack()
        s._t0 = time.monotonic()
        if st:
            st[-1].children.append(s)
        st.append(s)
        return _SpanCtx(self, s)

    def _finish(self, span: Span) -> None:
        span.duration_ms = (time.monotonic() - span._t0) * 1000.0
        st = self._stack()
        # Pop through any orphans left by mispaired exits.
        while st and st[-1] is not span:
            st.pop()
        if st:
            st.pop()
        if not st:  # root finished → into the ring
            with self._lock:
                self._seq += 1
                span.trace_id = f"t{self._seq:06d}"
                d = span.to_dict()
                if len(self._finished) == self._finished.maxlen:
                    evicted = self._finished[0]
                    self._by_id.pop(evicted.get("traceId", ""), None)
                self._finished.append(d)
                self._by_id[span.trace_id] = d

    def record(self, name: str, duration_s: float, **attrs: Any) -> None:
        """Attach an already-measured child span to the current span (or as
        a degenerate root when none is active).  Used where durations come
        back in bulk after one fused device dispatch."""
        now_ms = time.time() * 1000.0
        s = Span(name, now_ms - duration_s * 1000.0, attrs)
        s.duration_ms = duration_s * 1000.0
        st = self._stack()
        if st:
            st[-1].children.append(s)
        else:
            with self._lock:
                self._seq += 1
                s.trace_id = f"t{self._seq:06d}"
                d = s.to_dict()
                if len(self._finished) == self._finished.maxlen:
                    evicted = self._finished[0]
                    self._by_id.pop(evicted.get("traceId", ""), None)
                self._finished.append(d)
                self._by_id[s.trace_id] = d

    def annotate(self, **attrs: Any) -> None:
        """Add attributes to the innermost active span; no-op outside one."""
        st = self._stack()
        if st:
            st[-1].attrs.update(attrs)

    def current(self) -> Optional[Span]:
        st = self._stack()
        return st[-1] if st else None

    # -- read surfaces ------------------------------------------------------
    def recent(self, n: int = 20) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._finished)
        return items[-n:][::-1]

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._by_id.get(trace_id)

    def rollup(self) -> Dict[str, Dict[str, float]]:
        """Per-root-name {count, totalMs, maxMs} summary for /state."""
        with self._lock:
            items = list(self._finished)
        out: Dict[str, Dict[str, float]] = {}
        for t in items:
            r = out.setdefault(t["name"],
                               {"count": 0, "totalMs": 0.0, "maxMs": 0.0})
            r["count"] += 1
            r["totalMs"] = round(r["totalMs"] + t["durationMs"], 3)
            r["maxMs"] = max(r["maxMs"], t["durationMs"])
        return out

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._by_id.clear()
            self._seq = 0
        self._local = threading.local()


#: Process-wide tracer, mirroring ``SENSORS`` in common/sensors.py.
TRACE = Tracer()
