"""Observability sensors: counters, gauges, timers, histograms.

Parity with the reference's Dropwizard MetricRegistry → JMX domain
``kafka.cruisecontrol`` (KafkaCruiseControlApp.java:39-41; sensor list in
docs/wiki/User Guide/Sensors.md; registrations at LoadMonitor.java:180-195,
Executor.registerGaugeSensors Executor.java:271, AnomalyDetectorState.java).
A JVM-free build has no JMX; sensors surface through ``/state`` JSON and a
``/metrics`` Prometheus text endpoint instead.

Sensor kinds:
- Counter: monotonically increasing count (anomaly counts, completed tasks).
- Gauge: instantaneous value, either set explicitly or computed by a
  callback at read time (valid-windows, in-progress movements).
- Timer: event durations — count, mean, max, and a decaying last-N
  percentile window (proposal-computation-timer).  Exposed to Prometheus
  as a summary (``{quantile="0.99"}`` + ``_sum`` + ``_count``).
- Histogram: fixed exponential buckets — the Prometheus-native duration
  sensor (``_bucket``/``_sum``/``_count`` series), used by the request
  latency and phase-duration instrumentation.

Every sensor accepts an optional ``labels`` dict; each distinct label set
is its own series under one metric family (one ``# HELP``/``# TYPE`` pair
in the exposition).  The exposition is text-format 0.0.4 compliant: label
values are escaped, histogram buckets are cumulative and close with
``+Inf``, and name mangling (``.``/``-`` → ``_``) is collision-checked at
registration time (``a.b`` vs ``a-b`` would otherwise silently overwrite
each other — the later family gets a numeric suffix instead).
"""

from __future__ import annotations

import logging
import math
import re
import threading
import time
from collections import deque
from typing import (Callable, Deque, Dict, List, Optional, Sequence, Tuple)

log = logging.getLogger(__name__)

#: A series key: (metric family name, sorted (label, value) pairs).
LabelKey = Tuple[Tuple[str, str], ...]

#: Default exponential bucket ladder: 1 ms × 4^i — spans sub-ms endpoint
#: hits up to multi-minute 1M-replica optimizations in 10 buckets.
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(0.001 * 4 ** i for i in range(10))


def _label_key(labels: Optional[Dict[str, object]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _render_labels(key: LabelKey, extra: Sequence[Tuple[str, str]] = ()) -> str:
    pairs = list(key) + list(extra)
    if not pairs:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label_value(v)}"'
                          for k, v in pairs) + "}"


def _fmt_value(v: float) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _series_name(name: str, key: LabelKey) -> str:
    """JSON snapshot key for one series: bare family name when unlabeled,
    ``name{k="v",...}`` otherwise (stable: labels are sorted)."""
    return name + _render_labels(key)


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def count(self) -> int:
        return self._v


class Gauge:
    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._fn = fn
        self._v: float = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._v


class _TimeCtx:
    """Context manager timing a block into an ``update(seconds)`` sensor."""

    __slots__ = ("_sensor", "_t0")

    def __init__(self, sensor):
        self._sensor = sensor

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._sensor.update(time.monotonic() - self._t0)
        return False


class Timer:
    """Duration sensor with a bounded sample window for percentiles."""

    def __init__(self, window: int = 128):
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._max = 0.0
        self._samples: Deque[float] = deque(maxlen=window)

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._max = max(self._max, seconds)
            self._samples.append(seconds)

    def time(self) -> _TimeCtx:
        return _TimeCtx(self)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = self._count
            mean = self._total / n if n else 0.0
            samples = sorted(self._samples)
            p99 = samples[int(0.99 * (len(samples) - 1))] if samples else 0.0
            return {"count": n, "mean_s": mean, "max_s": self._max,
                    "p99_s": p99, "sum_s": self._total}


class Histogram:
    """Cumulative-bucket duration/size sensor (Prometheus histogram type).

    Buckets are fixed upper bounds (sorted ascending); observations land in
    the first bucket whose bound is >= the value, with an implicit ``+Inf``
    bucket equal to the total count.  ``update`` aliases ``observe`` so
    ``Histogram`` is a drop-in for ``Timer`` under ``.time()``.
    """

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        bs = tuple(sorted(float(b) for b in buckets)) if buckets \
            else DEFAULT_BUCKETS
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        self._buckets = bs
        self._counts = [0] * len(bs)  # per-bucket, non-cumulative
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    @property
    def buckets(self) -> Tuple[float, ...]:
        return self._buckets

    def observe(self, v: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            for i, le in enumerate(self._buckets):
                if v <= le:
                    self._counts[i] += 1
                    break

    update = observe

    def time(self) -> _TimeCtx:
        return _TimeCtx(self)

    def snapshot(self) -> Dict[str, object]:
        """count / sum plus CUMULATIVE bucket counts keyed by bound."""
        with self._lock:
            cum, running = {}, 0
            for le, c in zip(self._buckets, self._counts):
                running += c
                cum[_fmt_value(le)] = running
            cum["+Inf"] = self._count
            return {"count": self._count, "sum_s": self._sum, "buckets": cum}


_CLEAN_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _clean(name: str) -> str:
    return _CLEAN_RE.sub("_", name)


class MetricRegistry:
    """Name → sensor registry; one per process (``SENSORS``).

    A metric *family* (one name, one kind, one optional help string) holds
    one series per distinct label set.  Families register on first use;
    the Prometheus exposition name is fixed then, with collision detection
    on the mangled form.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, LabelKey], Gauge] = {}
        self._timers: Dict[Tuple[str, LabelKey], Timer] = {}
        self._histograms: Dict[Tuple[str, LabelKey], Histogram] = {}
        # family name → (kind, help text); exposition-name bookkeeping.
        self._meta: Dict[str, Tuple[str, str]] = {}
        self._expo: Dict[str, str] = {}           # family → mangled name
        self._mangled_owner: Dict[str, str] = {}  # mangled name → family

    # -- family registration (under self._lock) ----------------------------
    def _register_family(self, name: str, kind: str, help_text: str) -> None:
        existing = self._meta.get(name)
        if existing is not None:
            if existing[0] != kind:
                log.warning("sensor %r already registered as %s; ignoring "
                            "re-registration as %s", name, existing[0], kind)
            elif help_text and not existing[1]:
                self._meta[name] = (kind, help_text)
            return
        self._meta[name] = (kind, help_text)
        base = _clean(name)
        expo, n = base, 2
        while expo in self._mangled_owner and \
                self._mangled_owner[expo] != name:
            expo = f"{base}_{n}"
            n += 1
        if expo != base:
            # a.b and a-b both mangle to a_b: without this, the second
            # family silently overwrites the first in the exposition.
            log.warning("prometheus name collision: %r and %r both mangle "
                        "to %r; exposing %r as %r",
                        self._mangled_owner[base], name, base, name, expo)
        self._mangled_owner[expo] = name
        self._expo[name] = expo

    # -- sensor accessors ---------------------------------------------------
    def counter(self, name: str, labels: Optional[Dict[str, object]] = None,
                help: str = "") -> Counter:
        key = (name, _label_key(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = Counter()
                self._counters[key] = c
                self._register_family(name, "counter", help)
            return c

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None,
              labels: Optional[Dict[str, object]] = None,
              help: str = "") -> Gauge:
        key = (name, _label_key(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = Gauge(fn)
                self._gauges[key] = g
                self._register_family(name, "gauge", help)
            elif fn is not None:
                if g._fn is None:
                    g._fn = fn  # upgrade a set-style gauge to a callback
                elif g._fn is not fn:
                    # Keep the FIRST registration: replacing would let two
                    # subsystems silently shadow each other's gauge.
                    log.warning("gauge %r already has a callback; ignoring "
                                "duplicate registration", _series_name(*key))
            return g

    def timer(self, name: str, labels: Optional[Dict[str, object]] = None,
              help: str = "") -> Timer:
        key = (name, _label_key(labels))
        with self._lock:
            t = self._timers.get(key)
            if t is None:
                t = Timer()
                self._timers[key] = t
                self._register_family(name, "summary", help)
            return t

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  labels: Optional[Dict[str, object]] = None,
                  help: str = "") -> Histogram:
        """First registration of a family fixes its bucket ladder; later
        calls (any label set) reuse it so the family's series align."""
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                family = next((v for (n, _), v in self._histograms.items()
                               if n == name), None)
                h = Histogram(buckets if family is None else family.buckets)
                self._histograms[key] = h
                self._register_family(name, "histogram", help)
            return h

    # -- read surfaces ------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """All sensors as one JSON-able dict (the /state surface).  A gauge
        whose callback failed reports None — ``json.dumps`` would otherwise
        emit a bare ``NaN`` literal that strict parsers reject, letting one
        broken sensor break the whole /state payload."""
        out: Dict[str, object] = {}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
            histograms = dict(self._histograms)
        for (name, lk), c in sorted(counters.items()):
            out[_series_name(name, lk)] = c.count
        for (name, lk), g in sorted(gauges.items()):
            v = g.value
            out[_series_name(name, lk)] = v if math.isfinite(v) else None
        for (name, lk), t in sorted(timers.items()):
            out[_series_name(name, lk)] = t.snapshot()
        for (name, lk), h in sorted(histograms.items()):
            out[_series_name(name, lk)] = h.snapshot()
        return out

    def catalog(self) -> List[Dict[str, object]]:
        """Sensor-family inventory (docs/OBSERVABILITY.md is generated from
        this via ``python -m cruise_control_tpu.tools.dump_sensors``)."""
        with self._lock:
            meta = dict(self._meta)
            expo = dict(self._expo)
            keys = (list(self._counters) + list(self._gauges) +
                    list(self._timers) + list(self._histograms))
        label_names: Dict[str, set] = {}
        for name, lk in keys:
            label_names.setdefault(name, set()).update(k for k, _ in lk)
        return [{"name": name, "kind": kind,
                 "prometheus": expo.get(name, _clean(name)),
                 "labels": sorted(label_names.get(name, ())),
                 "help": help_text}
                for name, (kind, help_text) in sorted(meta.items())]

    def prometheus_text(self, prefix: str = "kafka_cruisecontrol") -> str:
        """Prometheus text-format 0.0.4 exposition (the /metrics surface):
        ``# HELP``/``# TYPE`` per family, label-rendered series, histogram
        ``_bucket``/``_sum``/``_count``, timer summaries."""
        with self._lock:
            meta = dict(self._meta)
            expo = dict(self._expo)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
            histograms = dict(self._histograms)

        def series_of(table, family):
            return sorted((lk, s) for (n, lk), s in table.items()
                          if n == family)

        lines: List[str] = []
        for name, (kind, help_text) in sorted(meta.items()):
            metric = f"{prefix}_{expo.get(name, _clean(name))}"
            body: List[str] = []
            if kind == "counter":
                for lk, c in series_of(counters, name):
                    body.append(f"{metric}{_render_labels(lk)} {c.count}")
            elif kind == "gauge":
                for lk, g in series_of(gauges, name):
                    v = g.value
                    if math.isfinite(v):  # failed callbacks are omitted
                        body.append(f"{metric}{_render_labels(lk)} "
                                    f"{_fmt_value(v)}")
            elif kind == "summary":
                for lk, t in series_of(timers, name):
                    s = t.snapshot()
                    body.append(
                        f"{metric}{_render_labels(lk, [('quantile', '0.99')])}"
                        f" {_fmt_value(s['p99_s'])}")
                    body.append(f"{metric}_sum{_render_labels(lk)} "
                                f"{_fmt_value(s['sum_s'])}")
                    body.append(f"{metric}_count{_render_labels(lk)} "
                                f"{s['count']}")
            elif kind == "histogram":
                for lk, h in series_of(histograms, name):
                    s = h.snapshot()
                    for le, cum in s["buckets"].items():
                        body.append(
                            f"{metric}_bucket{_render_labels(lk, [('le', le)])}"
                            f" {cum}")
                    body.append(f"{metric}_sum{_render_labels(lk)} "
                                f"{_fmt_value(s['sum_s'])}")
                    body.append(f"{metric}_count{_render_labels(lk)} "
                                f"{s['count']}")
            if not body:
                continue
            lines.append(f"# HELP {metric} "
                         f"{(help_text or name).replace(chr(10), ' ')}")
            lines.append(f"# TYPE {metric} {kind}")
            lines.extend(body)
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._histograms.clear()
            self._meta.clear()
            self._expo.clear()
            self._mangled_owner.clear()


#: Process-wide registry (the reference's shared Dropwizard registry).
SENSORS = MetricRegistry()
