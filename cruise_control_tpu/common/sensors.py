"""Observability sensors: counters, gauges, timers.

Parity with the reference's Dropwizard MetricRegistry → JMX domain
``kafka.cruisecontrol`` (KafkaCruiseControlApp.java:39-41; sensor list in
docs/wiki/User Guide/Sensors.md; registrations at LoadMonitor.java:180-195,
Executor.registerGaugeSensors Executor.java:271, AnomalyDetectorState.java).
A JVM-free build has no JMX; sensors surface through ``/state`` JSON and a
``/metrics`` Prometheus text endpoint instead.

Sensor kinds:
- Counter: monotonically increasing count (anomaly counts, completed tasks).
- Gauge: instantaneous value, either set explicitly or computed by a
  callback at read time (valid-windows, in-progress movements).
- Timer: event durations — count, mean, max, and a decaying last-N
  percentile window (proposal-computation-timer).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def count(self) -> int:
        return self._v


class Gauge:
    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._fn = fn
        self._v: float = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._v


class Timer:
    """Duration sensor with a bounded sample window for percentiles."""

    def __init__(self, window: int = 128):
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._max = 0.0
        self._samples: Deque[float] = deque(maxlen=window)

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total += seconds
            self._max = max(self._max, seconds)
            self._samples.append(seconds)

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.monotonic()
                return self

            def __exit__(self, *exc):
                timer.update(time.monotonic() - self._t0)
                return False

        return _Ctx()

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = self._count
            mean = self._total / n if n else 0.0
            samples = sorted(self._samples)
            p99 = samples[int(0.99 * (len(samples) - 1))] if samples else 0.0
            return {"count": n, "mean_s": mean, "max_s": self._max, "p99_s": p99}


class MetricRegistry:
    """Name → sensor registry; one per process (``SENSORS``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._timers: Dict[str, Timer] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None or fn is not None:
                g = Gauge(fn) if fn is not None else (g or Gauge())
                self._gauges[name] = g
            return g

    def timer(self, name: str) -> Timer:
        with self._lock:
            return self._timers.setdefault(name, Timer())

    def snapshot(self) -> Dict[str, object]:
        """All sensors as one JSON-able dict (the /state surface).  A gauge
        whose callback failed reports None — ``json.dumps`` would otherwise
        emit a bare ``NaN`` literal that strict parsers reject, letting one
        broken sensor break the whole /state payload."""
        import math
        out: Dict[str, object] = {}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
        for name, c in sorted(counters.items()):
            out[name] = c.count
        for name, g in sorted(gauges.items()):
            v = g.value
            out[name] = v if math.isfinite(v) else None
        for name, t in sorted(timers.items()):
            out[name] = t.snapshot()
        return out

    def prometheus_text(self, prefix: str = "kafka_cruisecontrol") -> str:
        """Prometheus exposition text (the /metrics surface)."""
        def clean(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        lines = []
        snap = self.snapshot()
        for name, value in snap.items():
            metric = f"{prefix}_{clean(name)}"
            if isinstance(value, dict):  # timer
                for k, v in value.items():
                    lines.append(f"{metric}_{clean(k)} {v}")
            elif value is not None:  # failed gauge callbacks are omitted
                lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


#: Process-wide registry (the reference's shared Dropwizard registry).
SENSORS = MetricRegistry()
