"""Telemetry time-series store: fixed-memory rings, staged downsampling,
an incremental stream log, and the SLA rollup engine.

Every other observability surface in this codebase is point-in-time — a
``/state`` read re-serializes whatever the sensors say *now*.  This module
is the retention layer underneath them: the cruise loop, the detector
manager, the executor ledger and the sensor registry publish scalar points
into :data:`TELEMETRY` on their **existing** tick/phase boundaries (the
store never fetches anything from a device — publishing is appending a
host float to a ring), and three read surfaces answer over time:

- ``GET /timeseries?series=&window=&step=`` — windowed aggregates from the
  downsample rungs (api/server.py);
- ``GET /stream?since=`` — the sequence-numbered event log, resumable by
  cursor (api/server.py);
- the ``Sla`` block of ``/state`` — :meth:`TimeSeriesStore.sla` windowed
  rollups (balancedness floor/percentiles, heal latency, task durations,
  replan churn, standing-hit ratio, fetches-per-boundary).

Memory model — the fixed-memory guarantee is the whole point:

- each series owns one **raw ring** (a bounded deque of ``(t_ms, value)``
  points) plus one bounded ring per **downsample rung** (default
  raw → 10 s → 1 m).  Rungs are *staged*: a sealed 10 s bucket feeds the
  1 m rung as an aggregate, so count/sum/min/max/last at every rung agree
  exactly with a naive recompute from the raw points that built them;
- one global **stream log** (bounded deque) assigns each accepted point a
  monotone sequence number; a reader that reconnects with its last-seen
  cursor gets every retained event exactly once;
- the **byte budget** caps the worst case: a write that would *create a
  new series* whose fully-populated rings no longer fit under the budget
  is dropped (and counted) instead of admitted.  Writes to existing series
  can never grow the store past its admitted worst case — the rings are
  bounded by construction.

Accounting sensors (the ``Executor.journal-bytes`` idiom):
``Telemetry.store-bytes`` (estimated resident bytes),
``Telemetry.points-total`` and ``Telemetry.points-dropped`` (budget
rejections + ring-retention evictions).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.common.sensors import SENSORS

#: Default downsample ladder: (step_ms, ring capacity in sealed buckets).
#: 10 s × 360 = 1 h; 60 s × 240 = 4 h of retention per series.
DEFAULT_RUNGS: Tuple[Tuple[int, int], ...] = ((10_000, 360), (60_000, 240))
#: Raw ring capacity (points per series).
DEFAULT_RAW_CAPACITY = 512
#: Stream log capacity (events, global).
DEFAULT_STREAM_CAPACITY = 4096
#: Default byte budget (~4 MB resident worst case — headroom for the ~16
#: series the full service publishes plus the stream log's worst case,
#: with room for a few dozen more before admission control kicks in).
DEFAULT_BYTE_BUDGET = 4_000_000

# Approximate per-entry heap costs for the byte accounting.  These are
# deliberately round overestimates of CPython's real footprint (tuple of
# two floats ≈ 56 B + float boxes; a 6-tuple bucket ≈ 96 B; an event dict
# interned to 4 keys ≈ 120 B) so the budget errs toward dropping early.
POINT_BYTES = 72
BUCKET_BYTES = 112
EVENT_BYTES = 160
SERIES_BYTES = 640  # fixed per-series overhead: dict slot, deques, rungs

# Canonical series names the publishers use (facade / detector manager /
# executor ledger) — the SLA engine reads these.  Kept here so publisher
# and consumer cannot drift apart.
BALANCEDNESS_SERIES = ("detector.balancedness", "executor.balancedness")
HEAL_DURATION_SERIES = "detector.heal-duration-s"
HEAL_STARTED_SERIES = "detector.heal-started"
TASK_DURATION_SERIES = "executor.task-duration-ms"
REPLAN_CANCELLED_SERIES = "executor.replan.cancelled"
REPLAN_KEPT_SERIES = "executor.replan.kept"
REPLAN_ADDED_SERIES = "executor.replan.added"
STANDING_HIT_SERIES = "cruise.standing-hit"
FETCHES_SERIES = "cruise.fetches-per-boundary"

#: Sensor-registry families the service's state-updater loop bridges into
#: the store (one ``sensor.<family>`` cumulative point per sample tick) —
#: see :meth:`TimeSeriesStore.sample_sensors`.
SENSOR_SAMPLE_FAMILIES = (
    "AnomalyDetector.heals-started",
    "AnomalyDetector.heals-failed",
    "CruiseControl.standing-hits",
    "CruiseControl.warm-solves",
    "CruiseControl.warm-fallbacks",
)


class _Rung:
    """One downsample stage: bounded ring of sealed buckets + the open one.

    A bucket is the 6-tuple ``(t_ms, count, sum, min, max, last)`` where
    ``t_ms`` is the bucket's aligned start.  ``feed`` merges an aggregate
    into the open bucket; when the incoming key advances past it, the open
    bucket seals into the ring and is returned so the caller can cascade
    it into the next (coarser) rung — staged downsampling keeps every
    aggregate exact (sums of sums, mins of mins)."""

    __slots__ = ("step_ms", "ring", "_open")

    def __init__(self, step_ms: int, capacity: int):
        self.step_ms = int(step_ms)
        self.ring: deque = deque(maxlen=max(2, capacity))
        self._open: Optional[list] = None  # [t, count, sum, min, max, last]

    def feed(self, t_ms: int, count: int, vsum: float, vmin: float,
             vmax: float, last: float) -> Optional[tuple]:
        key = (t_ms // self.step_ms) * self.step_ms
        o = self._open
        if o is None:
            self._open = [key, count, vsum, vmin, vmax, last]
            return None
        if key <= o[0]:
            # Same bucket — or a late point, merged into the open bucket
            # rather than reopening a sealed one (publishers are monotone
            # per series; batch-scored checkpoints may lag slightly).
            o[1] += count
            o[2] += vsum
            o[3] = min(o[3], vmin)
            o[4] = max(o[4], vmax)
            o[5] = last
            return None
        sealed = tuple(o)
        self.ring.append(sealed)
        self._open = [key, count, vsum, vmin, vmax, last]
        return sealed

    def buckets(self) -> List[tuple]:
        """Sealed buckets plus the open one (partial, still filling)."""
        out = list(self.ring)
        if self._open is not None:
            out.append(tuple(self._open))
        return out

    def resident(self) -> int:
        return len(self.ring) + (1 if self._open is not None else 0)


class _Series:
    __slots__ = ("raw", "rungs")

    def __init__(self, raw_capacity: int,
                 rungs: Sequence[Tuple[int, int]]):
        self.raw: deque = deque(maxlen=max(8, raw_capacity))
        self.rungs: List[_Rung] = [_Rung(s, c) for s, c in rungs]

    def add(self, t_ms: int, value: float) -> bool:
        """Append one point; cascade the downsample rungs.  Returns True
        when the raw ring evicted a point to make room."""
        evicted = len(self.raw) == self.raw.maxlen
        self.raw.append((t_ms, value))
        carry: Optional[tuple] = (t_ms, 1, value, value, value, value)
        for rung in self.rungs:
            if carry is None:
                break
            carry = rung.feed(*carry)
        return evicted


def _percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank percentile: the smallest value with at least ``q`` of
    the sample at or below it (p99 of 6 samples is the 6th, not the 5th)."""
    if not values:
        return None
    ordered = sorted(values)
    rank = math.ceil(q * len(ordered))
    return ordered[max(0, rank - 1)]


class TimeSeriesStore:
    """Lock-guarded, fixed-memory telemetry store.  See the module doc."""

    def __init__(self, raw_capacity: int = DEFAULT_RAW_CAPACITY,
                 rungs: Sequence[Tuple[int, int]] = DEFAULT_RUNGS,
                 stream_capacity: int = DEFAULT_STREAM_CAPACITY,
                 byte_budget: int = DEFAULT_BYTE_BUDGET,
                 clock_ms: Optional[Callable[[], float]] = None,
                 register_sensors: bool = False):
        self._raw_capacity = max(8, int(raw_capacity))
        self._rung_spec = tuple((int(s), int(c)) for s, c in rungs)
        if any(b[0] >= a[0] for b, a in zip(self._rung_spec,
                                            self._rung_spec[1:])):
            raise ValueError("downsample rungs must have increasing steps")
        self._byte_budget = int(byte_budget)
        self._clock_ms = clock_ms or (lambda: time.time() * 1000.0)
        self._register = bool(register_sensors)
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}  # guarded-by: _lock
        self._log: deque = deque(maxlen=max(16, stream_capacity))  # guarded-by: _lock
        self._seq = 0          # guarded-by: _lock
        self._total = 0        # guarded-by: _lock
        self._dropped = 0      # guarded-by: _lock
        self._committed_bytes = self._stream_worst_bytes()  # guarded-by: _lock
        self._bytes_gauge = None  # identity probe for _ensure_sensors
        self._ensure_sensors()

    # -- configuration / accounting -----------------------------------------
    def _series_worst_bytes(self) -> int:
        return (SERIES_BYTES + self._raw_capacity * POINT_BYTES
                + sum((c + 1) * BUCKET_BYTES for _, c in self._rung_spec))

    def _stream_worst_bytes(self) -> int:
        return (self._log.maxlen or 0) * EVENT_BYTES

    def byte_budget(self) -> int:
        return self._byte_budget

    def store_bytes(self) -> int:
        """Estimated resident bytes (points/buckets/events actually held)."""
        with self._lock:
            total = len(self._log) * EVENT_BYTES
            for s in self._series.values():
                total += SERIES_BYTES + len(s.raw) * POINT_BYTES
                total += sum(r.resident() * BUCKET_BYTES for r in s.rungs)
            return total

    def committed_bytes(self) -> int:
        """Worst-case bytes of everything admitted so far — what the byte
        budget actually gates on (resident bytes only ever grow toward it)."""
        with self._lock:
            return self._committed_bytes

    @property
    def points_total(self) -> int:
        with self._lock:
            return self._total

    @property
    def points_dropped(self) -> int:
        with self._lock:
            return self._dropped

    def set_clock(self, clock_ms: Optional[Callable[[], float]]) -> None:
        """Swap the default timestamp source (the SLA soak pins it to the
        simulated fleet's virtual clock so series read in fleet time)."""
        self._clock_ms = clock_ms or (lambda: time.time() * 1000.0)

    def config_dict(self) -> Dict[str, object]:
        return {
            "rawCapacity": self._raw_capacity,
            "rungs": [{"stepMs": s, "capacity": c}
                      for s, c in self._rung_spec],
            "streamCapacity": self._log.maxlen,
            "byteBudget": self._byte_budget,
            "committedBytes": self.committed_bytes(),
            "storeBytes": self.store_bytes(),
            "pointsTotal": self.points_total,
            "pointsDropped": self.points_dropped,
        }

    def _ensure_sensors(self) -> None:
        """(Re-)register the accounting gauges.  Called on every record so
        a ``SENSORS.reset()`` between tests cannot silently un-catalog the
        family.  Probing first (identity check on the registered Gauge)
        keeps the common case to one dict lookup and avoids the registry's
        duplicate-callback warning; after a reset the probe materialises a
        callback-less gauge which the fn registration then upgrades."""
        if not self._register:
            return
        probe = SENSORS.gauge(
            "Telemetry.store-bytes",
            help="Estimated resident bytes of the telemetry "
                 "time-series store (rings + stream log)")
        if probe is self._bytes_gauge:
            return
        self._bytes_gauge = SENSORS.gauge("Telemetry.store-bytes",
                                          fn=self.store_bytes)
        SENSORS.gauge("Telemetry.points-total", fn=lambda: self.points_total,
                      help="Points accepted into the telemetry store")
        SENSORS.gauge("Telemetry.points-dropped",
                      fn=lambda: self.points_dropped,
                      help="Points dropped by the telemetry store: byte-"
                           "budget rejections plus ring-retention "
                           "evictions")

    # -- write path ----------------------------------------------------------
    def record(self, name: str, value: float,
               t_ms: Optional[float] = None) -> bool:
        """Publish one point.  Returns False when the byte budget rejected
        it (a new series no longer fits).  Pure host work — never touches
        a device."""
        self._ensure_sensors()
        t = int(t_ms if t_ms is not None else self._clock_ms())
        v = float(value)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                if (self._committed_bytes + self._series_worst_bytes()
                        > self._byte_budget):
                    self._dropped += 1
                    return False
                s = _Series(self._raw_capacity, self._rung_spec)
                self._series[name] = s
                self._committed_bytes += self._series_worst_bytes()
            if s.add(t, v):
                self._dropped += 1  # raw ring evicted its oldest point
            self._total += 1
            self._seq += 1
            self._log.append({"seq": self._seq, "tMs": t,
                              "series": name, "value": v})
            return True

    def sample_sensors(self, names: Sequence[str],
                       t_ms: Optional[float] = None,
                       prefix: str = "sensor.") -> int:
        """Publish selected sensor-registry counter/gauge families as
        series (one point per family, summed over label sets) — the sensor
        registry's bridge into the retention layer.  Returns #published."""
        snap = SENSORS.snapshot()
        wanted = tuple(names)
        totals: Dict[str, float] = {}
        for key, value in snap.items():
            if not isinstance(value, (int, float)):
                continue  # histogram/timer dicts summarize elsewhere
            family = key.split("{", 1)[0]
            if family in wanted:
                totals[family] = totals.get(family, 0.0) + float(value)
        for family, total in sorted(totals.items()):
            self.record(prefix + family, total, t_ms=t_ms)
        return len(totals)

    # -- read path -----------------------------------------------------------
    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def latest(self, name: str) -> Optional[Tuple[int, float]]:
        with self._lock:
            s = self._series.get(name)
            if s is None or not s.raw:
                return None
            return s.raw[-1]

    def _now_ms(self) -> int:
        return int(self._clock_ms())

    def query(self, name: str, window_ms: Optional[int] = None,
              step_ms: Optional[int] = None,
              now_ms: Optional[float] = None) -> List[Dict[str, object]]:
        """Windowed aggregates.  ``step_ms`` picks the source resolution:
        below the first rung's step the raw points are grouped directly;
        otherwise the finest rung whose step divides into the request is
        re-grouped (exact — staged aggregates merge losslessly).  Each
        point is ``{"tMs", "count", "sum", "min", "max", "last", "mean"}``
        for its aligned ``step_ms`` bucket."""
        step = int(step_ms) if step_ms else 0
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            raw = list(s.raw)
            source: List[tuple]
            if step <= 0 or not s.rungs or step < s.rungs[0].step_ms:
                source = [(t, 1, v, v, v, v) for t, v in raw]
                step = max(step, 1)
            else:
                idx = 0
                for i, r in enumerate(s.rungs):
                    if r.step_ms <= step:
                        idx = i
                source = s.rungs[idx].buckets()
                # Tail exactness: the newest points are still sitting in
                # finer rungs' OPEN buckets (they only cascade on seal).
                # Those opens are disjoint from the serving rung's
                # contents, so merging them in makes every bucket —
                # including the tail — agree with a naive recompute.
                # Appended last = newest, so the grouped "last" stays the
                # chronologically latest value.
                for r in s.rungs[:idx]:
                    if r._open is not None:
                        source.append(tuple(r._open))
        now = int(now_ms) if now_ms is not None else \
            (source[-1][0] if source else self._now_ms())
        lo = now - int(window_ms) if window_ms else None
        grouped: Dict[int, list] = {}
        for t, count, vsum, vmin, vmax, last in source:
            if lo is not None and t < lo:
                continue
            key = (t // step) * step
            g = grouped.get(key)
            if g is None:
                grouped[key] = [key, count, vsum, vmin, vmax, last]
            else:
                g[1] += count
                g[2] += vsum
                g[3] = min(g[3], vmin)
                g[4] = max(g[4], vmax)
                g[5] = last
        out = []
        for key in sorted(grouped):
            _, count, vsum, vmin, vmax, last = grouped[key]
            out.append({"tMs": key, "count": count, "sum": vsum,
                        "min": vmin, "max": vmax, "last": last,
                        "mean": vsum / count})
        return out

    def stream_since(self, since: int, limit: int = 1000
                     ) -> Tuple[List[dict], int, bool]:
        """Events with ``seq > since`` in order, capped at ``limit``.

        Returns ``(events, cursor, truncated)`` — ``cursor`` is the last
        returned seq (or ``since`` when nothing new), ``truncated`` is
        True when the log's ring already evicted events the cursor missed
        (the reader must re-sync from a full ``/timeseries`` read).
        Sequence numbers are assigned contiguously, so within retention a
        reconnect at its last cursor sees no gaps and no duplicates."""
        since = max(0, int(since))
        limit = max(1, int(limit))
        with self._lock:
            if not self._log:
                return [], since, False
            first = self._log[0]["seq"]
            truncated = since + 1 < first
            start = max(0, since + 1 - first)
            events = [dict(self._log[i])
                      for i in range(start,
                                     min(len(self._log), start + limit))]
        cursor = events[-1]["seq"] if events else since
        return events, cursor, truncated

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._log.clear()
            self._seq = 0
            self._total = 0
            self._dropped = 0
            self._committed_bytes = self._stream_worst_bytes()

    # -- SLA rollup engine ---------------------------------------------------
    def _window_values(self, name: str, lo: int) -> List[float]:  # holds-lock: _lock
        s = self._series.get(name)
        if s is None:
            return []
        return [v for t, v in s.raw if t >= lo]

    def _window_floor(self, name: str, lo: int) -> Optional[float]:  # holds-lock: _lock
        """Exact minimum over the window: raw points plus every rung
        bucket's min, so the floor survives raw-ring aging."""
        s = self._series.get(name)
        if s is None:
            return None
        lows = [v for t, v in s.raw if t >= lo]
        if s.raw and s.raw[0][0] <= lo:
            # The raw ring still reaches past the window start: exact.
            return min(lows) if lows else None
        # Raw aged out: merge rung bucket minima, including the bucket
        # that straddles ``lo`` — conservative (the floor can only read
        # lower than the true in-window minimum, never higher).
        for rung in s.rungs:
            lows.extend(b[3] for b in rung.buckets()
                        if b[0] + rung.step_ms > lo)
        return min(lows) if lows else None

    @staticmethod
    def _dist(values: Sequence[float]) -> Optional[Dict[str, float]]:
        if not values:
            return None
        return {"count": len(values),
                "mean": sum(values) / len(values),
                "p50": _percentile(values, 0.50),
                "p99": _percentile(values, 0.99),
                "max": max(values),
                "min": min(values)}

    def sla(self, window_ms: int = 3_600_000,
            now_ms: Optional[float] = None) -> Dict[str, object]:
        """Windowed SLA rollups over the canonical series (see module doc).
        Blocks whose source series have no points in the window are None —
        the consumer distinguishes "no heals happened" from "heal latency
        was zero"."""
        now = int(now_ms) if now_ms is not None else self._now_ms()
        lo = now - int(window_ms)
        with self._lock:
            # The two balancedness series are different quantities on
            # different scales — the detector's 0–100 fleet-health score
            # vs the executor's 0–1 goal-distance-closed checkpoints — so
            # they roll up as separate blocks, never merged.
            det_name, ex_name = BALANCEDNESS_SERIES
            bal = self._window_values(det_name, lo)
            bal_floor = self._window_floor(det_name, lo)
            ex_bal = self._window_values(ex_name, lo)
            ex_floor = self._window_floor(ex_name, lo)
            heal_durations = self._window_values(HEAL_DURATION_SERIES, lo)
            heal_flags = self._window_values(HEAL_STARTED_SERIES, lo)
            task_durations = self._window_values(TASK_DURATION_SERIES, lo)
            cancelled = sum(self._window_values(REPLAN_CANCELLED_SERIES, lo))
            kept = sum(self._window_values(REPLAN_KEPT_SERIES, lo))
            added = sum(self._window_values(REPLAN_ADDED_SERIES, lo))
            replans = len(self._window_values(REPLAN_CANCELLED_SERIES, lo))
            hits = self._window_values(STANDING_HIT_SERIES, lo)
            fetches = self._window_values(FETCHES_SERIES, lo)
        def roll(values, floor):
            if not values:
                return None
            return {"floor": floor if floor is not None else min(values),
                    "p50": _percentile(values, 0.50),
                    "p99": _percentile(values, 0.99),
                    "last": values[-1],
                    "samples": len(values)}

        balancedness = roll(bal, bal_floor)
        executor_balancedness = roll(ex_bal, ex_floor)
        churn = None
        if replans:
            moves = cancelled + kept + added
            churn = {"replans": replans, "cancelled": cancelled,
                     "kept": kept, "added": added,
                     "churnRatio": (cancelled + added) / moves
                     if moves else 0.0}
        return {
            "windowMs": int(window_ms),
            "nowMs": now,
            "balancedness": balancedness,
            "executorBalancedness": executor_balancedness,
            "healLatencySeconds": self._dist(heal_durations),
            "healsStarted": int(sum(1 for f in heal_flags if f > 0)),
            "healsFailed": int(sum(1 for f in heal_flags if f <= 0)),
            "taskDurationMs": self._dist(task_durations),
            "replanChurn": churn,
            "standingHitRatio": (sum(hits) / len(hits)) if hits else None,
            "fetchesPerBoundary": self._dist(fetches),
            "store": {"bytes": self.store_bytes(),
                      "budget": self._byte_budget,
                      "dropped": self.points_dropped},
        }


#: The process-wide store every publisher writes into (the SENSORS/TRACE
#: singleton idiom).  Tests build private stores; the singleton's
#: accounting gauges are the cataloged ones.
TELEMETRY = TimeSeriesStore(register_sensors=True)
