from cruise_control_tpu.common.resources import Resource, NUM_RESOURCES

__all__ = ["Resource", "NUM_RESOURCES"]
