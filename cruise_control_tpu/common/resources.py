"""Resource definitions.

Behavioral parity with the reference's resource model
(cruise-control/src/main/java/.../common/Resource.java:19-26): four balanced
resources — CPU, network inbound, network outbound, disk — each with a
host/broker scope flag and a comparison epsilon.  The reference derives its
epsilons from a stress-test finding that float summation over ~800k replicas
drifts by >0.1% (Resource.java:28-31); we keep the same guard because the
tensor model sums f32 loads with segment-sums at the same scale.

In the tensor model the resource axis is always axis ``-1`` of load arrays in
this fixed id order, so ``Resource.CPU.id == 0`` indexes column 0 of
``f32[R, 4]`` replica loads.
"""

from __future__ import annotations

import enum


class Resource(enum.IntEnum):
    """Balanced resource kinds; the int value is the tensor column index."""

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def resource_name(self) -> str:
        return _NAMES[self]

    @property
    def is_host_resource(self) -> bool:
        # CPU and both network directions are host-level (shared across
        # brokers co-located on a host); disk is broker-level only.
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

    @property
    def is_broker_resource(self) -> bool:
        return self in (Resource.CPU, Resource.DISK)

    @property
    def epsilon(self) -> float:
        return _EPSILONS[self]

    @classmethod
    def cached_values(cls) -> tuple["Resource", ...]:
        return _CACHED

    def epsilon_for(self, util1: float, util2: float) -> float:
        """Scale-aware epsilon: max(abs epsilon, EPSILON_PERCENT * total).

        Mirrors the reference's Resource.epsilon(double, double) which guards
        float-sum drift proportionally to the compared magnitudes.
        """
        return max(self.epsilon, EPSILON_PERCENT * (util1 + util2))


_NAMES = {
    Resource.CPU: "cpu",
    Resource.NW_IN: "networkInbound",
    Resource.NW_OUT: "networkOutbound",
    Resource.DISK: "disk",
}

# Absolute comparison units per resource (CPU is in [0, 100] percent-ish
# units; NW in KB/s; DISK in MB) — same magnitudes as the reference.
_EPSILONS = {
    Resource.CPU: 0.001,
    Resource.NW_IN: 10.0,
    Resource.NW_OUT: 10.0,
    Resource.DISK: 100.0,
}

EPSILON_PERCENT = 0.0008

_CACHED = tuple(Resource)

NUM_RESOURCES = len(_CACHED)

HOST_RESOURCES = tuple(r for r in _CACHED if r.is_host_resource)
BROKER_RESOURCES = tuple(r for r in _CACHED if r.is_broker_resource)
