"""Async user-task tracking.

Parity with ``UserTaskManager`` (servlet/UserTaskManager.java:55-67):
operations run on worker threads under a UUID; re-requesting the same
(method, path, query, session) returns the in-flight task's progress or the
completed result; completed tasks are retained for a TTL and listed by
``/user_tasks``; per-step ``OperationProgress`` mirrors
async/progress/OperationProgress.java.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.common.tracing import TRACE


@dataclasses.dataclass
class OperationStep:
    name: str
    start_ms: int
    end_ms: int = -1


class OperationProgress:
    """async/progress/OperationProgress.java: ordered step list."""

    def __init__(self):
        self._lock = threading.Lock()
        self._steps: List[OperationStep] = []

    def add_step(self, name: str) -> None:
        now = int(time.time() * 1000)
        with self._lock:
            if self._steps and self._steps[-1].end_ms < 0:
                self._steps[-1].end_ms = now
            self._steps.append(OperationStep(name, now))

    def finish(self) -> None:
        now = int(time.time() * 1000)
        with self._lock:
            if self._steps and self._steps[-1].end_ms < 0:
                self._steps[-1].end_ms = now

    def to_list(self) -> List[Dict[str, object]]:
        with self._lock:
            return [{"step": s.name, "startMs": s.start_ms,
                     "durationMs": (s.end_ms if s.end_ms >= 0
                                    else int(time.time() * 1000)) - s.start_ms}
                    for s in self._steps]


class TaskStatus:
    ACTIVE = "Active"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"
    KILLED = "Killed"


@dataclasses.dataclass
class UserTask:
    task_id: str
    endpoint: str
    request_key: Tuple
    status: str
    start_ms: int
    progress: OperationProgress
    result: Optional[object] = None
    error: Optional[str] = None
    end_ms: int = -1
    #: Finished span tree for this operation (set when the worker thread
    #: completes); served by GET /trace?task_id=<task_id>.
    trace: Optional[Dict[str, object]] = None

    def summary(self) -> Dict[str, object]:
        return {"UserTaskId": self.task_id, "RequestURL": self.endpoint,
                "Status": self.status, "StartMs": self.start_ms,
                "Progress": self.progress.to_list()}


class UserTaskManager:
    def __init__(self, completed_retention_ms: int = 6 * 3600 * 1000,
                 max_active_tasks: int = 25,
                 max_cached_completed: int = 100):
        # max.active.user.tasks / completed.user.task.retention.time.ms /
        # max.cached.completed.user.tasks (UserTaskManagerConfig).
        self._lock = threading.Lock()
        self._tasks: Dict[str, UserTask] = {}
        self._by_key: Dict[Tuple, str] = {}
        self._retention_ms = completed_retention_ms
        self._max_active = max_active_tasks
        self._max_cached_completed = max_cached_completed

    def _gc(self, now_ms: int) -> None:
        expired = [tid for tid, t in self._tasks.items()
                   if t.status != TaskStatus.ACTIVE
                   and now_ms - t.end_ms > self._retention_ms]
        for tid in expired:
            t = self._tasks.pop(tid)
            # Only drop the key mapping if it still points at THIS task — a
            # resubmitted identical request may own the key by now, and
            # popping it would break duplicate-request joining.
            if self._by_key.get(t.request_key) == t.task_id:
                self._by_key.pop(t.request_key, None)
        completed = sorted((t for t in self._tasks.values()
                            if t.status != TaskStatus.ACTIVE),
                           key=lambda t: t.end_ms)
        for t in completed[:max(0, len(completed) - self._max_cached_completed)]:
            self._tasks.pop(t.task_id, None)
            if self._by_key.get(t.request_key) == t.task_id:
                self._by_key.pop(t.request_key, None)

    def submit(self, endpoint: str, request_key: Tuple,
               fn: Callable[[OperationProgress], object],
               join_completed: bool = False) -> UserTask:
        """Start (or join) the task for this request.  An identical request
        joins the task only while it is ACTIVE (a repeat after completion
        re-executes — returning hours-stale results for a mutating operation
        would be wrong); ``join_completed`` opts into returning the finished
        result instead (the purgatory flow, where a review id must execute
        exactly once)."""
        now = int(time.time() * 1000)
        with self._lock:
            self._gc(now)
            existing = self._by_key.get(request_key)
            if existing is not None and existing in self._tasks:
                task = self._tasks[existing]
                if task.status == TaskStatus.ACTIVE or join_completed:
                    return task
            active = sum(1 for t in self._tasks.values()
                         if t.status == TaskStatus.ACTIVE)
            if active >= self._max_active:
                raise RuntimeError("too many active user tasks")
            task = UserTask(task_id=str(uuid.uuid4()), endpoint=endpoint,
                            request_key=request_key, status=TaskStatus.ACTIVE,
                            start_ms=now, progress=OperationProgress())
            self._tasks[task.task_id] = task
            self._by_key[request_key] = task.task_id

        def run():
            # The worker thread has an empty span stack, so this span is the
            # trace ROOT; every span the operation opens (facade → monitor →
            # analyzer → executor) nests under it.
            with TRACE.span(f"request.{endpoint}", task_id=task.task_id) as sp:
                try:
                    task.result = fn(task.progress)
                    task.status = TaskStatus.COMPLETED
                except Exception as e:  # noqa: BLE001 — surfaced via the API
                    task.error = f"{type(e).__name__}: {e}"
                    task.status = TaskStatus.COMPLETED_WITH_ERROR
                finally:
                    task.progress.finish()
                    task.end_ms = int(time.time() * 1000)
                    sp.annotate(status=task.status)
            if sp.trace_id is not None:
                task.trace = TRACE.get(sp.trace_id)

        threading.Thread(target=run, name=f"user-task-{task.task_id[:8]}",
                         daemon=True).start()
        return task

    def get(self, task_id: str) -> Optional[UserTask]:
        with self._lock:
            return self._tasks.get(task_id)

    def find_by_key(self, request_key: Tuple) -> Optional[UserTask]:
        with self._lock:
            tid = self._by_key.get(request_key)
            return self._tasks.get(tid) if tid else None

    def list_tasks(self) -> List[Dict[str, object]]:
        with self._lock:
            self._gc(int(time.time() * 1000))
            return [t.summary() for t in
                    sorted(self._tasks.values(), key=lambda t: t.start_ms)]
