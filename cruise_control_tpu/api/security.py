"""Pluggable security providers.

Parity with ``servlet/security/`` (SecurityProvider SPI; HTTP Basic in
server.py): JWT bearer-token auth (security/jwt/JwtSecurityProvider +
JwtAuthenticator) and trusted-proxy auth (security/trustedproxy/
TrustedProxySecurityProvider: an authenticated gateway forwards the end
user in a ``doAs`` parameter).  SPNEGO/Kerberos is out of scope for a
stdlib-only build (it needs a GSSAPI binding); the SPI seam accepts an
external provider the same way.

All stdlib: HS256 JWTs via hmac/hashlib/base64.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Dict, Iterable, Optional, Tuple

from cruise_control_tpu.api.server import (ROLE_ADMIN, ROLE_USER, ROLE_VIEWER,
                                           BasicSecurityProvider,
                                           SecurityProvider)

_ROLES = {ROLE_VIEWER, ROLE_USER, ROLE_ADMIN}


def _b64url_decode(part: str) -> bytes:
    return base64.urlsafe_b64decode(part + "=" * (-len(part) % 4))


def _b64url_encode(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def encode_jwt(claims: Dict[str, object], secret: bytes) -> str:
    """Mint an HS256 JWT (test/ops helper — the reference validates tokens
    minted by an external issuer)."""
    header = _b64url_encode(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    body = _b64url_encode(json.dumps(claims).encode())
    signing_input = f"{header}.{body}".encode()
    sig = _b64url_encode(hmac.new(secret, signing_input, hashlib.sha256).digest())
    return f"{header}.{body}.{sig}"


class JwtSecurityProvider(SecurityProvider):
    """Validates ``Authorization: Bearer <jwt>`` (HS256) and maps the token's
    role claim onto the endpoint role model (security/jwt/)."""

    def __init__(self, secret: bytes, roles_claim: str = "roles",
                 issuer: Optional[str] = None,
                 default_role: Optional[str] = None):
        self._secret = secret
        self._roles_claim = roles_claim
        self._issuer = issuer
        self._default_role = default_role

    def authenticate(self, headers) -> Optional[str]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        token = auth[7:].strip()
        try:
            header_part, body_part, sig_part = token.split(".")
            header = json.loads(_b64url_decode(header_part))
            if header.get("alg") != "HS256":
                return None  # alg confusion (e.g. "none") is rejected
            signing_input = f"{header_part}.{body_part}".encode()
            expected = hmac.new(self._secret, signing_input,
                                hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _b64url_decode(sig_part)):
                return None
            claims = json.loads(_b64url_decode(body_part))
            if not isinstance(claims, dict):
                return None
            exp = claims.get("exp")
            if exp is not None and time.time() > float(exp):
                return None
            if self._issuer is not None and claims.get("iss") != self._issuer:
                return None
            roles = claims.get(self._roles_claim, [])
            if isinstance(roles, str):
                roles = [roles]
            granted = [r.upper() for r in roles
                       if isinstance(r, str) and r.upper() in _ROLES]
        except (ValueError, KeyError, TypeError, AttributeError):
            # Malformed tokens (non-dict header/claims, non-numeric exp,
            # non-string roles, …) are an authentication failure (401),
            # never a 500.
            return None
        if not granted:
            return self._default_role
        # Highest granted role wins.
        for role in (ROLE_ADMIN, ROLE_USER, ROLE_VIEWER):
            if role in granted:
                return role
        return None


class TrustedProxySecurityProvider(SecurityProvider):
    """An authenticated gateway makes requests on behalf of end users
    (security/trustedproxy/): the proxy itself authenticates (HTTP Basic
    here; SPNEGO in the reference) and names the end user in a
    ``X-Cruise-Control-Do-As`` header (the servlet's ``doAs`` parameter);
    the end user's role comes from a local user→role table."""

    DO_AS_HEADER = "X-Cruise-Control-Do-As"

    def __init__(self, proxy_credentials: Dict[str, Tuple[str, str]],
                 user_roles: Dict[str, str],
                 allowed_proxies: Optional[Iterable[str]] = None):
        self._proxy_auth = BasicSecurityProvider(proxy_credentials)
        self._proxy_names = set(allowed_proxies
                                if allowed_proxies is not None
                                else proxy_credentials)
        self._user_roles = dict(user_roles)

    def authenticate(self, headers) -> Optional[str]:
        if self._proxy_auth.authenticate(headers) is None:
            return None
        auth = headers.get("Authorization", "")
        try:
            proxy_user = base64.b64decode(auth[6:]).decode().split(":", 1)[0]
        except Exception:  # noqa: BLE001
            return None
        if proxy_user not in self._proxy_names:
            return None
        do_as = headers.get(self.DO_AS_HEADER)
        if not do_as:
            return None
        return self._user_roles.get(do_as)
