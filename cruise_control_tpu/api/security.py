"""Pluggable security providers.

Parity with ``servlet/security/`` (SecurityProvider SPI; HTTP Basic in
server.py): JWT bearer-token auth (security/jwt/JwtSecurityProvider +
JwtAuthenticator), trusted-proxy auth (security/trustedproxy/
TrustedProxySecurityProvider: an authenticated gateway forwards the end
user in a ``doAs`` parameter), and SPNEGO/Kerberos over HTTP Negotiate
(security/spnego/SpnegoSecurityProvider: challenge flow, principal
short-name mapping, user-store roles; the GSS-API accept step is pluggable
— python-gssapi when available, any Kerberos stack otherwise — exactly the
step the reference delegates to Jetty's ConfigurableSpnegoLoginService).

All stdlib: HS256 JWTs via hmac/hashlib/base64.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Dict, Iterable, Optional, Tuple

from cruise_control_tpu.api.server import (ROLE_ADMIN, ROLE_USER, ROLE_VIEWER,
                                           BasicSecurityProvider,
                                           SecurityProvider)

_ROLES = {ROLE_VIEWER, ROLE_USER, ROLE_ADMIN}


def _b64url_decode(part: str) -> bytes:
    return base64.urlsafe_b64decode(part + "=" * (-len(part) % 4))


def _b64url_encode(raw: bytes) -> str:
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def encode_jwt(claims: Dict[str, object], secret: bytes) -> str:
    """Mint an HS256 JWT (test/ops helper — the reference validates tokens
    minted by an external issuer)."""
    header = _b64url_encode(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    body = _b64url_encode(json.dumps(claims).encode())
    signing_input = f"{header}.{body}".encode()
    sig = _b64url_encode(hmac.new(secret, signing_input, hashlib.sha256).digest())
    return f"{header}.{body}.{sig}"


class JwtSecurityProvider(SecurityProvider):
    """Validates ``Authorization: Bearer <jwt>`` (HS256) and maps the token's
    role claim onto the endpoint role model (security/jwt/)."""

    def __init__(self, secret: bytes, roles_claim: str = "roles",
                 issuer: Optional[str] = None,
                 default_role: Optional[str] = None):
        self._secret = secret
        self._roles_claim = roles_claim
        self._issuer = issuer
        self._default_role = default_role

    def authenticate(self, headers) -> Optional[str]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return None
        token = auth[7:].strip()
        try:
            header_part, body_part, sig_part = token.split(".")
            header = json.loads(_b64url_decode(header_part))
            if header.get("alg") != "HS256":
                return None  # alg confusion (e.g. "none") is rejected
            signing_input = f"{header_part}.{body_part}".encode()
            expected = hmac.new(self._secret, signing_input,
                                hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _b64url_decode(sig_part)):
                return None
            claims = json.loads(_b64url_decode(body_part))
            if not isinstance(claims, dict):
                return None
            exp = claims.get("exp")
            if exp is not None and time.time() > float(exp):
                return None
            if self._issuer is not None and claims.get("iss") != self._issuer:
                return None
            roles = claims.get(self._roles_claim, [])
            if isinstance(roles, str):
                roles = [roles]
            granted = [r.upper() for r in roles
                       if isinstance(r, str) and r.upper() in _ROLES]
        except (ValueError, KeyError, TypeError, AttributeError):
            # Malformed tokens (non-dict header/claims, non-numeric exp,
            # non-string roles, …) are an authentication failure (401),
            # never a 500.
            return None
        if not granted:
            return self._default_role
        # Highest granted role wins.
        for role in (ROLE_ADMIN, ROLE_USER, ROLE_VIEWER):
            if role in granted:
                return role
        return None


class KerberosName:
    """Kerberos principal name parsing (the subset of
    org.apache.kafka.common.security.kerberos.KerberosName the SPNEGO
    provider needs): ``service/host@REALM``, ``user@REALM``, or a bare
    short name; ``short_name`` is the first component — the default
    auth-to-local rule the reference applies to map principals onto the
    user store (SpnegoUserStoreAuthorizationService.java)."""

    def __init__(self, principal: str):
        self.principal = principal
        rest, _, self.realm = principal.partition("@")
        self.service_name, sep, self.host_name = rest.partition("/")
        if not sep:
            self.host_name = ""
        self.short_name = self.service_name

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"KerberosName({self.principal!r})"


class SpnegoSecurityProvider(SecurityProvider):
    """SPNEGO / Kerberos over HTTP Negotiate (RFC 4559), the analogue of
    ``servlet/security/spnego/SpnegoSecurityProvider.java``.

    The provider owns the HTTP mechanics — the ``Negotiate`` challenge on
    401, token extraction, principal → short-name mapping, and the
    user-store role lookup (the same Jetty-realm file the Basic provider
    reads; SpnegoUserStoreAuthorizationService semantics: principals not in
    the store are rejected).  The GSS-API *accept* step itself is pluggable
    (``gss_acceptor: bytes -> principal | None``): in production wrap your
    Kerberos stack (e.g. python-gssapi with the service keytab named by
    ``spnego.keytab.file`` / ``spnego.principal``); the reference equally
    delegates this step to Jetty's ConfigurableSpnegoLoginService."""

    def __init__(self, gss_acceptor=None,
                 user_roles: Optional[Dict[str, str]] = None,
                 keytab_path: str = "", principal: str = ""):
        self._acceptor = gss_acceptor
        self._user_roles = dict(user_roles or {})
        self.keytab_path = keytab_path
        self.principal = KerberosName(principal) if principal else None

    def configure(self, config: Dict[str, object]) -> None:
        from cruise_control_tpu.config import constants as C
        self.keytab_path = str(config.get(C.SPNEGO_KEYTAB_FILE_CONFIG, "") or "")
        principal = str(config.get(C.SPNEGO_PRINCIPAL_CONFIG, "") or "")
        self.principal = KerberosName(principal) if principal else None
        path = config.get(C.WEBSERVER_AUTH_CREDENTIALS_FILE_CONFIG)
        if path:
            from cruise_control_tpu.app import _load_credentials
            self._user_roles = {user: role for user, (_, role)
                                in _load_credentials(str(path)).items()}
        if self._acceptor is None:
            try:  # pragma: no cover - optional dependency
                self._acceptor = _gssapi_acceptor(self.keytab_path,
                                                  self.principal)
            except ImportError as e:
                raise RuntimeError(
                    "SpnegoSecurityProvider needs a GSS-API acceptor: "
                    "install python-gssapi or construct the provider with "
                    f"gss_acceptor=... ({e})")

    def challenge_headers(self) -> Dict[str, str]:
        return {"WWW-Authenticate": "Negotiate"}

    def authenticate(self, headers) -> Optional[str]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Negotiate "):
            return None
        try:
            token = base64.b64decode(auth[len("Negotiate "):].strip())
        except Exception:  # noqa: BLE001 — malformed token is a clean 401
            return None
        if self._acceptor is None:
            return None
        principal = self._acceptor(token)
        if not principal:
            return None
        short = KerberosName(principal).short_name
        role = self._user_roles.get(short)
        return role.upper() if role and role.upper() in _ROLES else None


def _gssapi_acceptor(keytab_path: str, principal: Optional[KerberosName]):
    """Build a real GSS-API acceptor from python-gssapi (raises ImportError
    when the binding is absent — the stdlib cannot validate Kerberos
    tickets)."""
    import gssapi  # noqa: F401 — optional, not in the base image

    store = {"keytab": keytab_path} if keytab_path else None
    name = None
    if principal is not None:
        name = gssapi.Name(principal.principal,
                           gssapi.NameType.kerberos_principal)
    creds = gssapi.Credentials(usage="accept", name=name, store=store)

    def accept(token: bytes) -> Optional[str]:
        ctx = gssapi.SecurityContext(creds=creds, usage="accept")
        ctx.step(token)
        return str(ctx.initiator_name) if ctx.complete else None

    return accept


class TrustedProxySecurityProvider(SecurityProvider):
    """An authenticated gateway makes requests on behalf of end users
    (security/trustedproxy/): the proxy itself authenticates (HTTP Basic
    here; SPNEGO in the reference) and names the end user in a
    ``X-Cruise-Control-Do-As`` header (the servlet's ``doAs`` parameter);
    the end user's role comes from a local user→role table."""

    DO_AS_HEADER = "X-Cruise-Control-Do-As"

    def __init__(self, proxy_credentials: Dict[str, Tuple[str, str]],
                 user_roles: Dict[str, str],
                 allowed_proxies: Optional[Iterable[str]] = None):
        self._proxy_auth = BasicSecurityProvider(proxy_credentials)
        self._proxy_names = set(allowed_proxies
                                if allowed_proxies is not None
                                else proxy_credentials)
        self._user_roles = dict(user_roles)

    def authenticate(self, headers) -> Optional[str]:
        if self._proxy_auth.authenticate(headers) is None:
            return None
        auth = headers.get("Authorization", "")
        try:
            proxy_user = base64.b64decode(auth[6:]).decode().split(":", 1)[0]
        except Exception:  # noqa: BLE001
            return None
        if proxy_user not in self._proxy_names:
            return None
        do_as = headers.get(self.DO_AS_HEADER)
        if not do_as:
            return None
        return self._user_roles.get(do_as)
