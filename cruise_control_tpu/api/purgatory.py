"""Two-step verification purgatory for POST requests.

Parity with ``Purgatory`` (servlet/purgatory/Purgatory.java:43 and the
2-step-verification wiki doc): when enabled, mutating POST requests park as
``PENDING_REVIEW``; an admin reviews via ``/review`` (approve/discard);
re-submitting the original request with ``review_id`` executes an APPROVED
request exactly once (→ SUBMITTED).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Tuple


class ReviewStatus:
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


@dataclasses.dataclass
class ReviewRequest:
    review_id: int
    endpoint: str
    query: Dict[str, str]
    status: str
    submitted_ms: int
    reason: str = ""

    def to_dict(self) -> Dict[str, object]:
        return {"Id": self.review_id, "EndPoint": self.endpoint,
                "Query": dict(self.query), "Status": self.status,
                "SubmittedMs": self.submitted_ms, "Reason": self.reason}


class Purgatory:
    def __init__(self, retention_ms: int = 7 * 24 * 3600 * 1000,
                 max_requests: int = 25):
        # two.step.purgatory.{retention.time.ms,max.requests}
        # (WebServerConfig): expiry of reviewed requests + a cap on parked
        # pending reviews.
        self._lock = threading.Lock()
        self._requests: Dict[int, ReviewRequest] = {}  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock
        self._retention_ms = retention_ms
        self._max_requests = max_requests

    def add(self, endpoint: str, query: Dict[str, str]) -> ReviewRequest:
        with self._lock:
            self._gc()
            pending = sum(1 for r in self._requests.values()
                          if r.status == ReviewStatus.PENDING_REVIEW)
            if pending >= self._max_requests:
                raise ValueError(
                    f"two-step purgatory is full ({pending} pending reviews >= "
                    f"two.step.purgatory.max.requests={self._max_requests})")
            req = ReviewRequest(self._next_id, endpoint, dict(query),
                                ReviewStatus.PENDING_REVIEW,
                                int(time.time() * 1000))
            self._requests[self._next_id] = req
            self._next_id += 1
            return req

    def _gc(self) -> None:  # holds-lock: _lock
        now = int(time.time() * 1000)
        for rid in [r for r, req in self._requests.items()
                    if now - req.submitted_ms > self._retention_ms]:
            del self._requests[rid]

    def review(self, approve_ids: Tuple[int, ...] = (),
               discard_ids: Tuple[int, ...] = (), reason: str = "") -> List[Dict]:
        with self._lock:
            for rid in approve_ids:
                req = self._requests.get(rid)
                if req and req.status == ReviewStatus.PENDING_REVIEW:
                    req.status = ReviewStatus.APPROVED
                    req.reason = reason
            for rid in discard_ids:
                req = self._requests.get(rid)
                if req and req.status in (ReviewStatus.PENDING_REVIEW,
                                          ReviewStatus.APPROVED):
                    req.status = ReviewStatus.DISCARDED
                    req.reason = reason
            return [r.to_dict() for r in self._requests.values()]

    def take_approved(self, review_id: int, endpoint: str) -> ReviewRequest:
        """Claim an APPROVED request for execution (→ SUBMITTED); raises on
        wrong endpoint/state (Purgatory.submit semantics)."""
        with self._lock:
            req = self._requests.get(review_id)
            if req is None:
                raise KeyError(f"unknown review id {review_id}")
            if req.endpoint != endpoint:
                raise ValueError(f"review {review_id} is for {req.endpoint}, "
                                 f"not {endpoint}")
            if req.status != ReviewStatus.APPROVED:
                raise ValueError(f"review {review_id} is {req.status}, not APPROVED")
            req.status = ReviewStatus.SUBMITTED
            return req

    def board(self) -> List[Dict[str, object]]:
        with self._lock:
            self._gc()
            return [r.to_dict() for r in
                    sorted(self._requests.values(), key=lambda r: r.review_id)]
