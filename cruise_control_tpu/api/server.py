"""REST API server.

Parity with the servlet layer (servlet/KafkaCruiseControlServlet.java:40 +
CruiseControlEndPoint.java:16-37): the 20 endpoints in their 4 permission
groups, served under ``/kafkacruisecontrol/<endpoint>`` by a stdlib
ThreadingHTTPServer (the Jetty analogue — no external deps):

GET  (KAFKA_MONITOR):   LOAD, PARTITION_LOAD, PROPOSALS, KAFKA_CLUSTER_STATE
GET  (CC_MONITOR):      STATE, USER_TASKS, REVIEW_BOARD
POST (KAFKA_ADMIN):     ADD_BROKER, REMOVE_BROKER, FIX_OFFLINE_REPLICAS,
                        REBALANCE, DEMOTE_BROKER, TOPIC_CONFIGURATION
POST (CC_ADMIN):        STOP_PROPOSAL_EXECUTION, PAUSE_SAMPLING,
                        RESUME_SAMPLING, ADMIN, REVIEW, BOOTSTRAP, TRAIN

Long-running operations run through the ``UserTaskManager`` — the response
carries a ``User-Task-ID`` header; polling the same URL (or ``user_tasks``)
returns progress until the result is ready (UserTaskManager.java:55-66).
POST endpoints optionally require 2-step verification via the purgatory
(``two_step_verification=True``).  Security is a pluggable
``SecurityProvider`` (servlet/security/SecurityProvider.java) with
HTTP-Basic and permissive defaults; roles ADMIN > USER > VIEWER.

Incremental telemetry (``/stream`` cursor semantics — the same
resume-by-id discipline as the purgatory's ``review_id`` above): every
point published into the telemetry store carries a contiguous, monotone
sequence number.  ``GET /stream?since=N`` returns the retained events with
``seq > N`` as newline-delimited JSON objects (``text/plain`` body, one
object per line) plus an ``X-Stream-Cursor`` header naming the last seq in
the batch; a client that reconnects with ``since=<last cursor>`` sees no
gaps and no duplicates while its cursor is inside the log's retention ring
(``X-Stream-Truncated: true`` says it fell behind and must re-sync from
``GET /timeseries``).
"""

from __future__ import annotations

import base64
import dataclasses
import hmac
import json
import mimetypes
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cruise_control_tpu.api.facade import CruiseControl
from cruise_control_tpu.api.purgatory import Purgatory
from cruise_control_tpu.api.user_tasks import TaskStatus, UserTaskManager
from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.common.timeseries import TELEMETRY
from cruise_control_tpu.common.tracing import TRACE
from cruise_control_tpu.detector.anomalies import AnomalyType

PREFIX = "/kafkacruisecontrol"

GET_ENDPOINTS = {"bootstrap", "train", "load", "partition_load", "proposals",
                 "state", "kafka_cluster_state", "user_tasks", "review_board",
                 "metrics", "trace", "flight", "executor_state",
                 "timeseries", "stream"}
POST_ENDPOINTS = {"add_broker", "remove_broker", "fix_offline_replicas",
                  "rebalance", "stop_proposal_execution", "pause_sampling",
                  "resume_sampling", "demote_broker", "admin", "review",
                  "topic_configuration"}

# Permission groups (CruiseControlEndPoint.java:16-37).
ROLE_VIEWER, ROLE_USER, ROLE_ADMIN = "VIEWER", "USER", "ADMIN"
_ENDPOINT_ROLE = {e: ROLE_VIEWER for e in GET_ENDPOINTS}
_ENDPOINT_ROLE.update({e: ROLE_ADMIN for e in POST_ENDPOINTS})
_ENDPOINT_ROLE.update({"user_tasks": ROLE_USER, "review_board": ROLE_USER,
                       "bootstrap": ROLE_ADMIN, "train": ROLE_ADMIN})
_ROLE_RANK = {ROLE_VIEWER: 0, ROLE_USER: 1, ROLE_ADMIN: 2}


class SecurityProvider:
    """servlet/security/SecurityProvider.java analogue."""

    def authenticate(self, headers) -> Optional[str]:
        """Return the caller's role, or None to reject."""
        return ROLE_ADMIN


class BasicSecurityProvider(SecurityProvider):
    """HTTP Basic (servlet/security/BasicSecurityProvider.java): credentials
    {user: (password, role)}."""

    def __init__(self, credentials: Optional[Dict[str, Tuple[str, str]]] = None):
        self._creds = credentials if credentials is not None else {}

    def configure(self, config: Dict[str, object]) -> None:
        """Plugin-style init (webserver.security.provider): loads the
        realm file named by webserver.auth.credentials.file."""
        from cruise_control_tpu.app import _load_credentials
        from cruise_control_tpu.config import constants as C
        path = config.get(C.WEBSERVER_AUTH_CREDENTIALS_FILE_CONFIG)
        if path:
            self._creds = _load_credentials(str(path))

    def authenticate(self, headers) -> Optional[str]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Basic "):
            return None
        try:
            user, pw = base64.b64decode(auth[6:]).decode().split(":", 1)
        except Exception:  # noqa: BLE001 — malformed header
            return None
        entry = self._creds.get(user)
        # Compare as bytes: compare_digest on str raises for non-ASCII input,
        # which would crash the request instead of returning 401.
        if entry is None or not hmac.compare_digest(entry[0].encode(), pw.encode()):
            return None
        return entry[1]


class BadRequest(Exception):
    pass


def _parse_bool(q: Dict[str, str], key: str, default: bool) -> bool:
    v = q.get(key)
    if v is None:
        return default
    if v.lower() in ("true", "1", "yes"):
        return True
    if v.lower() in ("false", "0", "no"):
        return False
    raise BadRequest(f"invalid boolean for {key!r}: {v!r}")


def _parse_ids(q: Dict[str, str], key: str) -> List[int]:
    raw = q.get(key, "")
    if not raw:
        return []
    try:
        return [int(x) for x in raw.split(",") if x]
    except ValueError as e:
        raise BadRequest(f"invalid id list for {key!r}: {raw!r}") from e


def _parse_goals(q: Dict[str, str]) -> Optional[List[str]]:
    raw = q.get("goals", "")
    return [g for g in raw.split(",") if g] or None


def _parse_excluded_topics(q: Dict[str, str]) -> Optional[str]:
    """Per-request excluded-topics regex (ParameterUtils.java:898) —
    overrides the boot topics.excluded.from.partition.movement pattern."""
    raw = q.get("excluded_topics")
    if not raw:
        return None
    try:
        re.compile(raw)
    except re.error as e:
        raise BadRequest(f"invalid excluded_topics regex {raw!r}: {e}") from e
    return raw


def _parse_strategies(q: Dict[str, str]) -> Optional[List[str]]:
    """Per-request movement-strategy chain (ParameterUtils.java:733)."""
    names = [s for s in q.get("replica_movement_strategies", "").split(",") if s]
    if not names:
        return None
    from cruise_control_tpu.executor.strategy import resolve_strategy
    try:
        resolve_strategy(names)
    except ValueError as e:
        raise BadRequest(str(e)) from e
    return names


def _parse_throttle(q: Dict[str, str]) -> Optional[int]:
    """Per-request replication throttle rate (ParameterUtils.java:418)."""
    raw = q.get("replication_throttle")
    if raw is None:
        return None
    try:
        rate = int(raw)
    except ValueError as e:
        raise BadRequest(f"invalid replication_throttle {raw!r}") from e
    if rate <= 0:
        raise BadRequest(f"replication_throttle must be positive, got {rate}")
    return rate


class CruiseControlApi:
    """Endpoint dispatch, decoupled from HTTP plumbing for testability."""

    def __init__(self, cc: CruiseControl, detector_manager=None, sampler=None,
                 two_step_verification: bool = False,
                 security: Optional[SecurityProvider] = None,
                 user_tasks: Optional[UserTaskManager] = None,
                 purgatory: Optional[Purgatory] = None,
                 telemetry=None):
        self.cc = cc
        self.detector_manager = detector_manager
        self.sampler = sampler
        # The telemetry time-series store /timeseries and /stream read
        # from; defaults to the process-wide singleton the facade /
        # detector / ledger publishers write into.
        self.telemetry = telemetry or TELEMETRY
        self.user_tasks = user_tasks or UserTaskManager()
        self.purgatory = (purgatory or Purgatory()) if two_step_verification \
            else None
        self.security = security or SecurityProvider()
        self.request_meters: Dict[str, int] = {}
        self._local = threading.local()  # per-request purgatory review key

    # -- dispatch ----------------------------------------------------------
    def handle(self, method: str, endpoint: str, query: Dict[str, str],
               headers=None) -> Tuple[int, Dict[str, object], Dict[str, str]]:
        """Returns (http_status, json_body, extra_headers).  Every request
        to a known endpoint is metered: a latency histogram and a
        status-code counter, both labeled by endpoint (the reference's
        successful-request-execution-timer per endpoint,
        KafkaCruiseControlServlet.java)."""
        endpoint = endpoint.lower()
        valid = GET_ENDPOINTS if method == "GET" else POST_ENDPOINTS
        if endpoint not in valid:
            # Unknown endpoints are NOT metered — arbitrary request paths
            # would make the label set unbounded.
            return 404, {"error": f"unknown {method} endpoint {endpoint!r}",
                         "validEndpoints": sorted(valid)}, {}
        t0 = time.monotonic()
        status, body, extra = self._handle(method, endpoint, query, headers)
        SENSORS.histogram(
            "webserver.request-duration-seconds",
            labels={"endpoint": endpoint},
            help="Wall time spent handling an API request, by endpoint",
        ).observe(time.monotonic() - t0)
        SENSORS.counter(
            "webserver.responses-total",
            labels={"endpoint": endpoint, "code": status},
            help="API responses by endpoint and HTTP status code",
        ).inc()
        return status, body, extra

    def _handle(self, method: str, endpoint: str, query: Dict[str, str],
                headers=None) -> Tuple[int, Dict[str, object], Dict[str, str]]:
        role = self.security.authenticate(headers or {})
        if role is None:
            # Challenge-based schemes (SPNEGO's Negotiate) advertise the
            # mechanism on rejection (RFC 4559 §4.1).
            challenge = getattr(self.security, "challenge_headers", None)
            return 401, {"error": "authentication required"}, \
                (challenge() if callable(challenge) else {})
        if _ROLE_RANK[role] < _ROLE_RANK[_ENDPOINT_ROLE[endpoint]]:
            return 403, {"error": f"endpoint {endpoint} requires "
                                  f"{_ENDPOINT_ROLE[endpoint]}"}, {}
        self.request_meters[endpoint] = self.request_meters.get(endpoint, 0) + 1

        # Purgatory gate for mutating POSTs (Purgatory.java:43).
        mutating = endpoint in ("add_broker", "remove_broker", "rebalance",
                                "demote_broker", "fix_offline_replicas",
                                "topic_configuration")
        review_key = None
        if self.purgatory is not None and method == "POST" and mutating:
            rid = query.get("review_id")
            if rid is None:
                req = self.purgatory.add(endpoint, query)
                return 202, {"reviewId": req.review_id,
                             "status": req.status,
                             "message": "request parked for review"}, {}
            try:
                rid = int(rid)
            except ValueError:
                return 400, {"error": f"invalid review_id {rid!r}"}, {}
            try:
                req = self.purgatory.take_approved(rid, endpoint)
            except (KeyError, ValueError) as e:
                # Polling an already-SUBMITTED review must keep returning the
                # running/completed task instead of failing the client.
                task = self.user_tasks.find_by_key(("review", endpoint, rid))
                if task is not None:
                    return self._task_response(task)
                return 400, {"error": str(e)}, {}
            # Execute EXACTLY the reviewed parameters — overriding them at
            # resubmission would bypass the review (two-step verification).
            passthrough = {k: v for k, v in query.items() if k == "max_wait_s"}
            query = {**req.query, **passthrough}
            review_key = ("review", endpoint, req.review_id)

        try:
            self._local.review_key = review_key
            return getattr(self, f"_ep_{endpoint}")(query)
        except BadRequest as e:
            return 400, {"error": str(e)}, {}
        except Exception as e:  # noqa: BLE001 — servlet-style error payload
            return 500, {"error": f"{type(e).__name__}: {e}",
                         "stackTrace": True}, {}

    def _async(self, endpoint: str, query: Dict[str, str],
               fn: Callable) -> Tuple[int, Dict[str, object], Dict[str, str]]:
        """Run via UserTaskManager; identical re-requests join while active.
        A purgatory-approved request is keyed by its review id so it executes
        exactly once and re-polls keep returning its result."""
        review_key = getattr(self._local, "review_key", None)
        key = review_key or (endpoint, tuple(sorted(query.items())))
        task = self.user_tasks.submit(endpoint, key, fn,
                                      join_completed=review_key is not None)
        return self._task_response(task, float(query.get("max_wait_s", "10")))

    def _task_response(self, task, max_wait_s: float = 0.0
                       ) -> Tuple[int, Dict[str, object], Dict[str, str]]:
        deadline = time.monotonic() + max_wait_s
        while task.status == TaskStatus.ACTIVE and time.monotonic() < deadline:
            time.sleep(0.02)
        headers = {"User-Task-ID": task.task_id}
        if task.status == TaskStatus.ACTIVE:
            return 202, {"progress": task.progress.to_list(),
                         "userTaskId": task.task_id}, headers
        if task.status == TaskStatus.COMPLETED_WITH_ERROR:
            return 500, {"error": task.error, "userTaskId": task.task_id}, headers
        result = task.result
        if dataclasses.is_dataclass(result):
            result = result.to_dict()
        elif not isinstance(result, (dict, list)):
            result = {"result": result}
        return 200, result, headers

    # -- GET endpoints -----------------------------------------------------
    def _ep_state(self, q):
        payload = self.cc.state(self.detector_manager)
        substates = q.get("substates")
        if substates:
            # Accept monitor / executor / analyzer / anomaly_detector in any
            # underscore/camel spelling.
            want = {s.strip().lower().replace("_", "") for s in substates.split(",")}
            payload = {k: v for k, v in payload.items()
                       if k.lower().replace("state", "") in want}
        return 200, payload, {}

    def _ep_kafka_cluster_state(self, q):
        return 200, self.cc.kafka_cluster_state(), {}

    def _ep_executor_state(self, q):
        """Execution-ledger progress: live per-broker in-flight, bytes
        moved/total, ETA, adjuster decisions, per-phase records and the
        balancedness-over-time checkpoints.  ``?verbose=true`` adds the
        per-broker map, checkpoint curve and recent lifecycle events (the
        reference's ExecutorState verbose JSON, ExecutorState.java:332)."""
        verbose = _parse_bool(q, "verbose", False)
        return 200, self.cc.executor.progress(verbose=verbose), {}

    def _ep_metrics(self, q):
        """Sensor registry (Sensors.md): JSON by default; Prometheus
        exposition text with ?format=prometheus (the /metrics surface the
        reference exports via JMX)."""
        if q.get("format") == "prometheus":
            return 200, PlainText(SENSORS.prometheus_text()), {}
        return 200, SENSORS.snapshot(), {}

    def _ep_timeseries(self, q):
        """Windowed rollups from the telemetry time-series store
        (docs/OBSERVABILITY.md "Telemetry time-series & SLA").  Without
        ``?series=`` lists the known series names and store config; with a
        comma-separated ``?series=`` returns per-step aggregate buckets
        (count/sum/min/max/last/mean) over ``?window=`` seconds at
        ``?step=`` seconds granularity.  Entirely host-side reads — never
        triggers a device fetch."""
        names = q.get("series")
        if not names:
            return 200, {"series": self.telemetry.series_names(),
                         "config": self.telemetry.config_dict()}, {}
        try:
            window_s = float(q.get("window", "3600"))
            step_s = float(q.get("step", "60"))
        except ValueError as exc:
            raise BadRequest(f"bad window/step: {exc}")
        if window_s <= 0:
            raise BadRequest("window must be > 0 seconds")
        out = {}
        for name in (n.strip() for n in names.split(",")):
            if not name:
                continue
            out[name] = self.telemetry.query(
                name, window_ms=int(window_s * 1000),
                step_ms=int(step_s * 1000))
        return 200, {"windowMs": int(window_s * 1000),
                     "stepMs": int(step_s * 1000), "series": out}, {}

    def _ep_stream(self, q):
        """Incremental point stream, resumable by sequence number (the
        cursor discipline documented in the module docstring above).  Body
        is JSON lines; ``X-Stream-Cursor`` carries the next ``since`` and
        ``X-Stream-Truncated: true`` means the client fell behind the ring
        and must re-sync from ``/timeseries``."""
        try:
            since = int(q.get("since", "0"))
            limit = int(q.get("limit", "1000"))
        except ValueError as exc:
            raise BadRequest(f"bad since/limit: {exc}")
        if since < 0 or limit <= 0:
            raise BadRequest("since must be >= 0 and limit > 0")
        events, cursor, truncated = self.telemetry.stream_since(since, limit)
        body = "".join(json.dumps(e, sort_keys=True) + "\n" for e in events)
        return 200, PlainText(body), {
            "X-Stream-Cursor": str(cursor),
            "X-Stream-Truncated": "true" if truncated else "false"}

    def _ep_trace(self, q):
        """Finished operation traces.  ``?task_id=`` returns the span tree
        attached to that user task; ``?trace_id=`` looks up the global ring
        buffer; with neither, lists recent root traces."""
        task_id = q.get("task_id")
        if task_id:
            task = self.user_tasks.get(task_id)
            if task is None:
                return 404, {"error": f"unknown task_id {task_id!r}"}, {}
            if task.trace is None:
                if task.status == TaskStatus.ACTIVE:
                    return 202, {"userTaskId": task.task_id,
                                 "status": task.status,
                                 "message": "trace not finished yet"}, {}
                return 404, {"error": f"no trace recorded for task "
                                      f"{task_id!r}"}, {}
            return 200, {"userTaskId": task.task_id, "status": task.status,
                         "trace": task.trace}, {}
        trace_id = q.get("trace_id")
        if trace_id:
            t = TRACE.get(trace_id)
            if t is None:
                return 404, {"error": f"unknown trace_id {trace_id!r}"}, {}
            return 200, {"trace": t}, {}
        limit = int(q.get("limit", "20"))
        return 200, {"traces": TRACE.recent(limit),
                     "rollup": TRACE.rollup()}, {}

    def _ep_flight(self, q):
        """Flight-recorder convergence timelines of a task's optimization:
        the per-goal per-step telemetry the analyzer attached to its
        ``analyzer.goal`` spans (CRUISE_FLIGHT_RECORDER=1 runs only).
        ``?task_id=`` is required; 202 while the task is still ACTIVE."""
        task_id = q.get("task_id")
        if not task_id:
            return 400, {"error": "flight requires ?task_id="}, {}
        task = self.user_tasks.get(task_id)
        if task is None:
            return 404, {"error": f"unknown task_id {task_id!r}"}, {}
        if task.trace is None:
            if task.status == TaskStatus.ACTIVE:
                return 202, {"userTaskId": task.task_id,
                             "status": task.status,
                             "message": "trace not finished yet"}, {}
            return 404, {"error": f"no trace recorded for task "
                                  f"{task_id!r}"}, {}
        goals = []

        def walk(span):
            attrs = span.get("attrs") or {}
            if span.get("name") == "analyzer.goal" and "flight" in attrs:
                goals.append({"goal": attrs.get("goal"),
                              "steps": attrs.get("steps"),
                              "actions": attrs.get("actions"),
                              "durationMs": span.get("durationMs"),
                              "flight": attrs["flight"]})
            for c in span.get("children") or []:
                walk(c)

        walk(task.trace)
        if not goals:
            return 404, {"error": "no flight data on this task's trace — "
                                  "was CRUISE_FLIGHT_RECORDER=1 (or "
                                  "analyzer.flight.recorder) set when the "
                                  "task ran?"}, {}
        return 200, {"userTaskId": task.task_id, "status": task.status,
                     "goals": goals}, {}

    def _ep_load(self, q):
        def fn(progress):
            progress.add_step("WaitingForClusterModel")
            progress.add_step("GeneratingClusterModel")
            return self.cc.broker_load()
        return self._async("load", q, fn)

    def _ep_partition_load(self, q):
        max_entries = int(q.get("entries", "100"))
        return 200, {"records": self.cc.partition_load(max_entries)}, {}

    def _ep_proposals(self, q):
        ignore_cache = _parse_bool(q, "ignore_proposal_cache", False)
        goals = _parse_goals(q)
        excluded = _parse_excluded_topics(q)
        # Tri-state: absent defers to analyzer.warm.start.enabled,
        # warm=true/false overrides per request.
        warm = None if "warm" not in q else _parse_bool(q, "warm", True)

        def fn(progress):
            progress.add_step("GeneratingClusterModel")
            progress.add_step("OptimizationProposalGeneration")
            return self.cc.proposals(goals=goals, ignore_proposal_cache=ignore_cache,
                                     excluded_topics_pattern=excluded,
                                     warm=warm)
        return self._async("proposals", q, fn)

    def _ep_user_tasks(self, q):
        return 200, {"userTasks": self.user_tasks.list_tasks()}, {}

    def _ep_review_board(self, q):
        if self.purgatory is None:
            return 400, {"error": "two-step verification is disabled"}, {}
        return 200, {"requests": self.purgatory.board()}, {}

    def _ep_bootstrap(self, q):
        if self.sampler is None:
            return 400, {"error": "no sampler configured for bootstrap"}, {}
        start = int(q.get("start", "0"))
        end = int(q.get("end", str(start + 1)))

        def fn(progress):
            progress.add_step("Bootstrapping")
            n = self.cc.load_monitor.bootstrap(self.sampler, start, end)
            return {"samplesLoaded": n}
        return self._async("bootstrap", q, fn)

    def _ep_train(self, q):
        from cruise_control_tpu.model.cpu_model import CpuModelTrainer
        from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF

        def fn(progress):
            progress.add_step("Training")
            trainer = CpuModelTrainer()
            agg = self.cc.load_monitor.broker_aggregator.aggregate()
            cpu = KAFKA_METRIC_DEF.metric_info("CPU_USAGE").metric_id
            bin_ = KAFKA_METRIC_DEF.metric_info("LEADER_BYTES_IN").metric_id
            bout = KAFKA_METRIC_DEF.metric_info("LEADER_BYTES_OUT").metric_id
            rep = KAFKA_METRIC_DEF.metric_info("REPLICATION_BYTES_IN_RATE").metric_id
            for row in range(agg.values.shape[0]):
                for w in range(agg.values.shape[1]):
                    if agg.window_valid[row, w]:
                        v = agg.values[row, w]
                        trainer.add_observation(v[bin_], v[bout], v[rep], v[cpu])
            params = trainer.train()
            return {"trained": params.trained, "numSamples": params.num_samples,
                    "coefficients": {
                        "leaderBytesIn": params.coef_leader_bytes_in,
                        "leaderBytesOut": params.coef_leader_bytes_out,
                        "followerBytesIn": params.coef_follower_bytes_in}}
        return self._async("train", q, fn)

    # -- POST endpoints ----------------------------------------------------
    def _ep_rebalance(self, q):
        dryrun = _parse_bool(q, "dryrun", True)
        goals = _parse_goals(q)
        dests = _parse_ids(q, "destination_broker_ids")
        fast = _parse_bool(q, "fast_mode", False)
        rebalance_disk = _parse_bool(q, "rebalance_disk", False)
        excluded = _parse_excluded_topics(q)
        strategies = _parse_strategies(q)
        throttle = _parse_throttle(q)
        warm = None if "warm" not in q else _parse_bool(q, "warm", True)

        def fn(progress):
            progress.add_step("GeneratingClusterModel")
            progress.add_step("OptimizationForGoals")
            return self.cc.rebalance(goals=goals, dryrun=dryrun,
                                     destination_broker_ids=dests or None,
                                     fast_mode=fast,
                                     rebalance_disk=rebalance_disk,
                                     excluded_topics_pattern=excluded,
                                     replica_movement_strategies=strategies,
                                     replication_throttle=throttle,
                                     warm=warm)
        return self._async("rebalance", q, fn)

    def _ep_add_broker(self, q):
        ids = _parse_ids(q, "brokerid")
        if not ids:
            raise BadRequest("brokerid parameter is required")
        dryrun = _parse_bool(q, "dryrun", True)
        excluded = _parse_excluded_topics(q)
        strategies = _parse_strategies(q)
        throttle = _parse_throttle(q)

        def fn(progress):
            progress.add_step("OptimizationForGoals")
            return self.cc.add_brokers(ids, dryrun=dryrun,
                                       excluded_topics_pattern=excluded,
                                       replica_movement_strategies=strategies,
                                       replication_throttle=throttle)
        return self._async("add_broker", q, fn)

    def _ep_remove_broker(self, q):
        ids = _parse_ids(q, "brokerid")
        if not ids:
            raise BadRequest("brokerid parameter is required")
        dryrun = _parse_bool(q, "dryrun", True)
        excluded = _parse_excluded_topics(q)
        strategies = _parse_strategies(q)
        throttle = _parse_throttle(q)

        def fn(progress):
            progress.add_step("OptimizationForGoals")
            ok = self.cc.remove_brokers(ids, dryrun=dryrun,
                                        excluded_topics_pattern=excluded,
                                        replica_movement_strategies=strategies,
                                        replication_throttle=throttle)
            return {"ok": ok, "removedBrokers": ids, "dryrun": dryrun}
        return self._async("remove_broker", q, fn)

    def _ep_demote_broker(self, q):
        ids = _parse_ids(q, "brokerid")
        if not ids:
            raise BadRequest("brokerid parameter is required")
        dryrun = _parse_bool(q, "dryrun", True)

        def fn(progress):
            progress.add_step("OptimizationForGoals")
            ok = self.cc.demote_brokers(ids, dryrun=dryrun)
            return {"ok": ok, "demotedBrokers": ids, "dryrun": dryrun}
        return self._async("demote_broker", q, fn)

    def _ep_fix_offline_replicas(self, q):
        dryrun = _parse_bool(q, "dryrun", True)

        def fn(progress):
            progress.add_step("OptimizationForGoals")
            ok = self.cc.fix_offline_replicas(dryrun=dryrun)
            return {"ok": ok, "dryrun": dryrun}
        return self._async("fix_offline_replicas", q, fn)

    def _ep_topic_configuration(self, q):
        topic = q.get("topic")
        rf = q.get("replication_factor")
        if not topic or rf is None:
            raise BadRequest("topic and replication_factor are required")
        dryrun = _parse_bool(q, "dryrun", True)

        def fn(progress):
            progress.add_step("UpdatingTopicConfiguration")
            ok = self.cc.update_topic_replication_factor({topic: int(rf)},
                                                         dryrun=dryrun)
            return {"ok": ok, "topic": topic, "replicationFactor": int(rf),
                    "dryrun": dryrun}
        return self._async("topic_configuration", q, fn)

    def _ep_stop_proposal_execution(self, q):
        force = _parse_bool(q, "force_stop", False)
        self.cc.stop_proposal_execution(force=force)
        return 200, {"message": "execution stop requested", "force": force}, {}

    def _ep_pause_sampling(self, q):
        self.cc.pause_sampling(reason=q.get("reason", ""))
        return 200, {"message": "sampling paused"}, {}

    def _ep_resume_sampling(self, q):
        self.cc.resume_sampling()
        return 200, {"message": "sampling resumed"}, {}

    def _ep_admin(self, q):
        """ADMIN endpoint (servlet AdminRequest): self-healing toggles,
        concurrency changes, dropping recently-removed brokers."""
        out: Dict[str, object] = {}
        enable = q.get("enable_self_healing_for")
        disable = q.get("disable_self_healing_for")
        if (enable or disable) and self.detector_manager is None:
            raise BadRequest("anomaly detector is not configured")
        for raw, value in ((enable, True), (disable, False)):
            if raw:
                for name in raw.split(","):
                    try:
                        at = AnomalyType[name.strip().upper()]
                    except KeyError as e:
                        raise BadRequest(f"unknown anomaly type {name!r}") from e
                    old = self.detector_manager.notifier.set_self_healing_for(at, value)
                    out.setdefault("selfHealing", {})[at.name] = \
                        {"before": old, "after": value}
        conc = q.get("concurrent_partition_movements_per_broker")
        if conc is not None:
            limits = dataclasses.replace(self.cc.executor.limits,
                                         inter_broker_per_broker=int(conc))
            self.cc.executor.set_concurrency(limits)
            out["interBrokerPartitionMovementConcurrency"] = int(conc)
        drop = _parse_ids(q, "drop_recently_removed_brokers")
        if drop:
            self.cc.executor.drop_recently_removed_brokers(drop)
            out["droppedRecentlyRemovedBrokers"] = drop
        return 200, out or {"message": "no admin action requested"}, {}

    def _ep_review(self, q):
        if self.purgatory is None:
            return 400, {"error": "two-step verification is disabled"}, {}
        approve = tuple(_parse_ids(q, "approve"))
        discard = tuple(_parse_ids(q, "discard"))
        return 200, {"requests": self.purgatory.review(
            approve, discard, q.get("reason", ""))}, {}


class PlainText(str):
    """Marker: endpoint result is preformatted text, not JSON."""


class HtmlText(str):
    """Marker: endpoint result is an HTML page."""


# Minimal status UI (the reference bundles the separate cruise-control-ui
# webapp behind the same Jetty server, KafkaCruiseControlApp.java:100-195;
# this build ships a single self-contained page driven by the JSON API).
_INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>cruise-control-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;max-width:72rem}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 pre{background:#f6f8fa;padding:0.8rem;border-radius:6px;overflow:auto}
 a{color:#0969da;text-decoration:none} .row a{margin-right:1rem}
</style></head>
<body>
<h1>cruise-control-tpu</h1>
<div class="row">
 <a href="%PREFIX%/state">state</a>
 <a href="%PREFIX%/kafka_cluster_state">kafka_cluster_state</a>
 <a href="%PREFIX%/proposals">proposals</a>
 <a href="%PREFIX%/metrics">metrics</a>
 <a href="%PREFIX%/executor_state?verbose=true">executor_state</a>
 <a href="%PREFIX%/trace">trace</a>
 <a href="%PREFIX%/user_tasks">user_tasks</a>
</div>
<h2>State</h2><pre id="state">loading…</pre>
<h2>Sensors</h2><pre id="sensors">loading…</pre>
<script>
 fetch("%PREFIX%/state").then(r=>r.json()).then(s=>{
   document.getElementById("sensors").textContent =
     JSON.stringify(s.Sensors ?? {}, null, 2);
   delete s.Sensors;
   document.getElementById("state").textContent = JSON.stringify(s, null, 2);
 }).catch(e=>{document.getElementById("state").textContent = String(e)});
</script>
</body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    api: CruiseControlApi = None  # injected by serve()
    ui_dir: Optional[str] = None  # webserver.ui.diskpath static assets

    def _serve_static(self, path: str) -> bool:
        """Serve a file from ``ui_dir`` (the reference mounts the
        cruise-control-ui webapp dist dir this way,
        KafkaCruiseControlApp.java:100-143).  Returns False when the path
        resolves outside the dir or to no file — callers fall through to
        the built-in status page / 404."""
        rel = urllib.parse.unquote(path).lstrip("/") or "index.html"
        base = os.path.realpath(self.ui_dir)
        full = os.path.realpath(os.path.join(base, rel))
        if full != base and not full.startswith(base + os.sep):
            return False
        if os.path.isdir(full):
            full = os.path.join(full, "index.html")
        if not os.path.isfile(full):
            return False
        with open(full, "rb") as f:
            payload = f.read()
        ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)
        return True

    def _dispatch(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        under_api = parsed.path.startswith(PREFIX + "/")
        if method == "GET" and not under_api and self.ui_dir:
            # The UI sits behind the same security provider as the API
            # (the reference's Jetty security handler covers the mounted
            # webapp context too).
            if self.api.security.authenticate(dict(self.headers)) is None:
                challenge = getattr(self.api.security, "challenge_headers", None)
                self._reply(401, {"error": "authentication required"},
                            challenge() if callable(challenge) else {})
                return
            if self._serve_static(parsed.path):
                return
        if method == "GET" and parsed.path.rstrip("/") in ("", PREFIX):
            self._reply(200, HtmlText(_INDEX_HTML.replace("%PREFIX%", PREFIX)),
                        {})
            return
        if not under_api:
            self._reply(404, {"error": f"paths live under {PREFIX}/"}, {})
            return
        endpoint = parsed.path[len(PREFIX) + 1:].strip("/")
        query = {k: v[-1] for k, v in
                 urllib.parse.parse_qs(parsed.query, keep_blank_values=True).items()}
        status, body, headers = self.api.handle(method, endpoint, query,
                                                dict(self.headers))
        self._reply(status, body, headers)

    def _reply(self, status: int, body: Dict, headers: Dict[str, str]) -> None:
        if isinstance(body, HtmlText):
            payload = str(body).encode()
            ctype = "text/html; charset=utf-8"
        elif isinstance(body, PlainText):
            payload = str(body).encode()
            ctype = "text/plain; version=0.0.4"
        else:
            payload = json.dumps(body, default=str).encode()
            ctype = "application/json"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def log_message(self, fmt, *args):  # NCSA-style access log, stderr
        import sys
        print(f"{self.address_string()} - [{self.log_date_time_string()}] "
              f"{fmt % args}", file=sys.stderr)


def serve(api: CruiseControlApi, host: str = "127.0.0.1", port: int = 9090,
          ui_dir: Optional[str] = None) -> ThreadingHTTPServer:
    """Start the HTTP server on a daemon thread; returns the server object
    (KafkaCruiseControlApp.start analogue).  ``ui_dir`` serves static
    web-UI assets at / (webserver.ui.diskpath)."""
    handler = type("BoundHandler", (_Handler,), {"api": api, "ui_dir": ui_dir})
    server = ThreadingHTTPServer((host, port), handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="cc-http-server").start()
    return server
