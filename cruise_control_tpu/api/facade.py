"""The CruiseControl facade: one object tying monitor → analyzer → executor
→ detectors together.

Parity with ``KafkaCruiseControl`` (KafkaCruiseControl.java:73) +
``GoalOptimizer``'s proposal cache (GoalOptimizer.java:63: precomputed
proposals invalidated by model generation, ``optimizations`` cached path
:291-339): admin operations (rebalance, add/remove/demote brokers, fix
offline replicas, topic RF update) each build a cluster model, run the goal
stack under operation-specific options, and optionally execute — exactly
the servlet runnables' computeResult flow
(GoalBasedOperationRunnable.java:153-186, RebalanceRunnable.java:109-123,
AddBrokersRunnable / RemoveBrokersRunnable / DemoteBrokerRunnable /
FixOfflineReplicasRunnable / UpdateTopicConfigurationRunnable).

This facade is also the self-healing context consumed by
``detector.anomalies`` fix() methods.
"""

from __future__ import annotations

import dataclasses
import functools
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.sensors import SENSORS
from cruise_control_tpu.common.timeseries import (FETCHES_SERIES,
                                                  STANDING_HIT_SERIES,
                                                  TELEMETRY)
from cruise_control_tpu.common.tracing import TRACE

from cruise_control_tpu.analyzer import optimizer as opt
from cruise_control_tpu.analyzer import proposals as props
from cruise_control_tpu.analyzer.balancing_constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals.specs import (DEFAULT_GOAL_ORDER,
                                                     DEFAULT_HARD_GOALS,
                                                     GOAL_SPECS,
                                                     INTRA_BROKER_GOAL_ORDER)
from cruise_control_tpu.analyzer.state import (OptimizationOptions, WarmStart,
                                               model_delta)
from cruise_control_tpu.analyzer.verifier import VerificationError, verify_run
from cruise_control_tpu.executor.admin import ClusterAdmin, ReassignmentRequest
from cruise_control_tpu.executor.executor import (Executor,
                                                  OngoingExecutionError,
                                                  ReplanDirective)
from cruise_control_tpu.executor.strategy import resolve_strategy
from cruise_control_tpu.model.stats import compute_stats
from cruise_control_tpu.model.tensor_model import BrokerState, TensorClusterModel
from cruise_control_tpu.monitor.load_monitor import (LoadMonitor,
                                                     ModelCompletenessRequirements)


@dataclasses.dataclass
class OperationResult:
    """OptimizationResult JSON payload (servlet/response/OptimizationResult)."""

    ok: bool
    dryrun: bool
    proposals: List[props.ExecutionProposal]
    violated_goals_before: List[str]
    violated_goals_after: List[str]
    provision_status: str
    stats_before: Dict[str, object]
    stats_after: Dict[str, object]
    execution: Optional[object] = None  # ExecutionResult when not dryrun
    reason: str = ""
    # Goals whose step loop hit max_steps while still applying actions: the
    # run may not be a true fixpoint for them (GoalResult.capped).
    capped_goals: List[str] = dataclasses.field(default_factory=list)
    # On-demand balancedness (OptimizerResult.java:117-118).
    balancedness_before: float = 100.0
    balancedness_after: float = 100.0

    def to_dict(self) -> Dict[str, object]:
        out = {
            "ok": self.ok,
            "dryrun": self.dryrun,
            "numProposals": len(self.proposals),
            "proposals": [p.to_dict() for p in self.proposals[:200]],
            "violatedGoalsBefore": self.violated_goals_before,
            "violatedGoalsAfter": self.violated_goals_after,
            "provisionStatus": self.provision_status,
            "statsBefore": self.stats_before,
            "statsAfter": self.stats_after,
            "reason": self.reason,
            "cappedGoals": self.capped_goals,
            "onDemandBalancednessScoreBefore": round(self.balancedness_before, 3),
            "onDemandBalancednessScoreAfter": round(self.balancedness_after, 3),
        }
        if self.execution is not None:
            out["execution"] = dataclasses.asdict(self.execution)
        return out


def _traced_op(fn):
    """Wrap an admin operation in a ``facade.<op>`` span.  Under a user
    task this nests below the task's ``request.<endpoint>`` root; called
    directly (tests, self-healing fixes) it becomes its own root trace."""
    name = f"facade.{fn.__name__}"

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        attrs = {k: kwargs[k] for k in ("dryrun", "reason", "self_healing")
                 if k in kwargs}
        with TRACE.span(name, **attrs) as sp:
            out = fn(self, *args, **kwargs)
            if isinstance(out, OperationResult):
                sp.annotate(ok=out.ok, proposals=len(out.proposals))
            elif isinstance(out, bool):
                sp.annotate(ok=out)
            return out

    return wrapper


class CruiseControl:
    def __init__(self, load_monitor: LoadMonitor, executor: Executor,
                 admin: ClusterAdmin,
                 goals: Optional[Sequence[str]] = None,
                 hard_goals: Optional[Sequence[str]] = None,
                 constraint: Optional[BalancingConstraint] = None,
                 requirements: Optional[ModelCompletenessRequirements] = None,
                 proposal_expiration_ms: int = 60_000,
                 max_steps_per_goal: int = 256,
                 max_candidates_per_step: Optional[int] = None,
                 balancedness_priority_weight: float = 1.1,
                 balancedness_strictness_weight: float = 1.5,
                 supported_goals: Optional[Sequence[str]] = None,
                 intra_broker_goals: Optional[Sequence[str]] = None,
                 allow_capacity_estimation: bool = True,
                 excluded_topics_pattern: Optional[str] = None,
                 self_healing_exclude_recently_demoted: bool = True,
                 self_healing_exclude_recently_removed: bool = True,
                 warm_start_enabled: bool = False,
                 warm_start_delta_threshold: float = 0.05,
                 replan_interval_polls: int = 0):
        self.load_monitor = load_monitor
        self.executor = executor
        self.admin = admin
        self.goals = list(goals or DEFAULT_GOAL_ORDER)
        self.hard_goals = list(hard_goals or DEFAULT_HARD_GOALS)
        # goals (AnalyzerConfig GOALS_CONFIG): every requestable goal; a
        # request naming a goal outside it is rejected up front.
        self.supported_goals = list(supported_goals or GOAL_SPECS)
        # intra.broker.goals: the stack for rebalance_disk=true requests.
        self.intra_broker_goals = list(intra_broker_goals or
                                       INTRA_BROKER_GOAL_ORDER)
        self.allow_capacity_estimation = allow_capacity_estimation
        # topics.excluded.from.partition.movement (a regex in the reference).
        self._excluded_topics_pattern = (re.compile(excluded_topics_pattern)
                                         if excluded_topics_pattern else None)
        self._self_heal_exclude_demoted = self_healing_exclude_recently_demoted
        self._self_heal_exclude_removed = self_healing_exclude_recently_removed
        self.constraint = constraint or BalancingConstraint.default()
        self.requirements = requirements or ModelCompletenessRequirements()
        self._proposal_expiration_ms = proposal_expiration_ms
        self._max_steps_per_goal = max_steps_per_goal
        self._max_candidates_per_step = max_candidates_per_step
        self._balancedness_weights = (balancedness_priority_weight,
                                      balancedness_strictness_weight)
        # analyzer.warm.start.*: per-request warm seeding policy.  Off by
        # default for direct requests (warm=None resolves to this flag);
        # the cruise loop passes warm=True explicitly, so cruise refreshes
        # are warm even when requests stay cold.
        self._warm_start_enabled = warm_start_enabled
        self._warm_delta_threshold = warm_start_delta_threshold
        # execution.replan.interval.polls: 0 (default) executes plans
        # statically; N > 0 re-solves against the partially-moved cluster
        # every N executor polls and patches the live task queue.
        self._replan_interval_polls = replan_interval_polls
        # The run whose converged model the LAST successful mid-execution
        # replan targeted — what _absorb_execution should re-base onto
        # instead of the original run when a replanned execution lands ok.
        # Written by the executor's replan hook (executor poll thread) and
        # consumed by _absorb_execution (request thread).
        self._executed_run_override: Optional[opt.OptimizerRun] = None  # guarded-by: _cache_lock
        self._cache_lock = threading.Lock()
        # The STANDING PROPOSAL: (model_generation, monotonic time,
        # pre-optimization model, converged run, renumbered proposals).
        # The pre-model is the delta-probe baseline (the converged
        # run.model differs from it by exactly the proposed moves, so
        # diffing fresh-vs-pre answers "did the cluster move under us"),
        # and the run.model is the warm seed.
        self._cached: Optional[Tuple[Tuple[int, int], float,
                                     TensorClusterModel, opt.OptimizerRun,
                                     List[props.ExecutionProposal]]] = None  # guarded-by: _cache_lock

    # ------------------------------------------------------------------
    # Model + optimization plumbing
    # ------------------------------------------------------------------
    def _model(self) -> TensorClusterModel:
        return self.load_monitor.cluster_model(
            self.requirements,
            allow_capacity_estimation=self.allow_capacity_estimation)

    def _model_naming(self) -> Tuple[TensorClusterModel, Dict[str, object]]:
        """Model + id↔name maps from ONE metadata snapshot.  The tensor model
        uses dense broker indices (sorted-id order); the cluster protocol uses
        real, possibly non-contiguous ids.  All translation in an operation
        must use this naming — a fresh ``naming()`` read could reflect a
        membership change and misaddress every proposal."""
        return self.load_monitor.cluster_model_and_naming(self.requirements)

    @staticmethod
    def _to_dense(naming: Dict[str, object], broker_ids: Sequence[int]) -> List[int]:
        to_dense = {b: i for i, b in enumerate(naming["brokers"])}
        missing = [b for b in broker_ids if b not in to_dense]
        if missing:
            raise ValueError(f"unknown broker ids {missing}")
        return [to_dense[b] for b in broker_ids]

    def _base_options(self, model: TensorClusterModel,
                      naming: Dict[str, object],
                      excluded_topics_pattern: Optional[str] = None
                      ) -> OptimizationOptions:
        """Default per-request options with the excluded topics applied.
        A per-request ``excluded_topics`` regex OVERRIDES the boot-time
        topics.excluded.from.partition.movement pattern (the reference's
        param-else-config resolution, ParameterUtils.java:898)."""
        options = OptimizationOptions.none(model)
        pattern = (re.compile(excluded_topics_pattern)
                   if excluded_topics_pattern
                   else self._excluded_topics_pattern)
        if pattern is not None:
            tmask = np.array([bool(pattern.fullmatch(t))
                              for t in naming["topics"]], bool)
            if tmask.any():
                options = options.replace(topic_excluded=jnp.asarray(tmask))
        return options

    @staticmethod
    def _request_strategy(names: Optional[Sequence[str]]):
        """Resolve a per-request movement-strategy chain (None -> use the
        executor's boot-time strategy)."""
        return resolve_strategy(list(names)) if names else None

    def _validate_goals(self, goals: Sequence[str]) -> None:
        """User-requested goals must be in goals= (the supported set);
        short and fully-qualified names both resolve, as in
        goals_by_priority.  Internal stacks (demote's
        PreferredLeaderElectionGoal, self-healing) are not gated — the
        reference only sanity-checks request parameters against
        GOALS_CONFIG."""
        supported = {g.rsplit(".", 1)[-1] for g in self.supported_goals}
        unsupported = [g for g in goals
                       if g.rsplit(".", 1)[-1] not in supported]
        if unsupported:
            raise ValueError(
                f"goals {unsupported} are not supported; supported: "
                f"{sorted(supported)}")

    def _self_heal_excludes(self, options: OptimizationOptions,
                            naming: Dict[str, object]) -> OptimizationOptions:
        """self.healing.exclude.recently.{removed,demoted}.brokers
        (AnomalyDetectorConfig): an autonomous fix must not undo a recent
        operator decision by moving replicas back onto a just-removed broker
        or leadership onto a just-demoted one.  Applied by every
        self-healing entry point (rebalance, fix_offline_replicas)."""
        to_dense = {b: i for i, b in enumerate(naming["brokers"])}
        if self._self_heal_exclude_removed:
            removed = [to_dense[b] for b in
                       self.executor.recently_removed_brokers()
                       if b in to_dense]
            if removed:
                emask = np.array(options.broker_excluded_replica_move)
                emask[removed] = True
                options = options.replace(
                    broker_excluded_replica_move=jnp.asarray(emask))
        if self._self_heal_exclude_demoted:
            demoted = [to_dense[b] for b in
                       self.executor.recently_demoted_brokers()
                       if b in to_dense]
            if demoted:
                lmask = np.array(options.broker_excluded_leadership)
                lmask[demoted] = True
                options = options.replace(
                    broker_excluded_leadership=jnp.asarray(lmask))
        return options

    def _optimize(self, model: TensorClusterModel, goals: Optional[Sequence[str]],
                  options: Optional[OptimizationOptions] = None,
                  fast_mode: bool = False,
                  naming: Optional[Dict[str, object]] = None,
                  warm_start: Optional[WarmStart] = None) -> opt.OptimizerRun:
        goal_list = list(goals) if goals else self.goals
        if options is None and naming is not None:
            # Config-excluded topics apply to EVERY goal-based operation,
            # not just /rebalance (the reference applies them in all
            # GoalBasedOperationRunnables).
            options = self._base_options(model, naming)
        # Requested non-hard-only goal subsets still honor hard goals first
        # (GoalBasedOperationRunnable skip-hard-goal-check semantics are an
        # explicit flag in the reference; default keeps them).
        with SENSORS.timer(
                "GoalOptimizer.proposal-computation-timer",
                help="End-to-end goal-stack optimization wall time").time():
            # Donate the working model's buffers through the goal-stack
            # dispatches (intermediate models reuse one buffer set instead
            # of piling up); the explicit copy keeps the caller's
            # pre-optimization model alive for proposals.diff / verify_run.
            work = opt.donation_copy(model)
            return opt.optimize(work, goal_list, constraint=self.constraint,
                                options=options, raise_on_hard_failure=False,
                                fused=True, fast_mode=fast_mode,
                                max_steps_per_goal=self._max_steps_per_goal,
                                max_candidates_per_step=self._max_candidates_per_step,
                                balancedness_priority_weight=self._balancedness_weights[0],
                                balancedness_strictness_weight=self._balancedness_weights[1],
                                donate_model=True, warm_start=warm_start)

    def _finish(self, model: TensorClusterModel, run: opt.OptimizerRun,
                dryrun: bool, reason: str, naming: Dict[str, object],
                verify: bool = True, strategy=None,
                replication_throttle: Optional[int] = None) -> OperationResult:
        # Verification runs on dense indices (the model's own numbering);
        # everything leaving the facade — REST payloads and the executor's
        # ReassignmentRequests / throttle entries — carries cluster ids from
        # the SAME snapshot the model was built from.
        with TRACE.span("analyzer.proposals", verify=verify) as sp:
            dense_proposals = props.diff(model, run.model)
            capped = [g.name for g in run.goal_results if g.capped]
            if verify:
                try:
                    verify_run(model, run, [g.name for g in run.goal_results],
                               constraint=self.constraint,
                               proposals=dense_proposals)
                except VerificationError as e:
                    sp.annotate(verification_failed=True)
                    return OperationResult(
                        ok=False, dryrun=dryrun,
                        proposals=props.renumber_brokers(
                            dense_proposals, naming["brokers"]),
                        violated_goals_before=run.violated_goals_before,
                        violated_goals_after=run.violated_goals_after,
                        provision_status=run.provision_response.status.value,
                        stats_before=run.stats_before.to_dict(),
                        stats_after=run.stats_after.to_dict(),
                        reason=f"{reason} [verification failed: {e}]",
                        capped_goals=capped,
                        balancedness_before=run.balancedness_before,
                        balancedness_after=run.balancedness_after)
            proposals = props.renumber_brokers(dense_proposals,
                                               naming["brokers"])
            sp.annotate(proposals=len(proposals))
        execution = None
        ok = True
        if not dryrun and proposals:
            # The scorer re-scores balancedness over the ledger's landed-set
            # checkpoints (dense partition ids survive renumber_brokers, so
            # ledger masks address the model directly).
            scorer = opt.PlacementScorer.for_run(
                model, run, self.constraint, *self._balancedness_weights)
            # Live broker health feeds the ConcurrencyAdjuster during the
            # wait loop (Executor.java:335-447 reads request-queue depth /
            # handler idle ratio each interval).
            with self._cache_lock:
                self._executed_run_override = None
            replanner = (self._make_replanner(run, naming)
                         if self._replan_interval_polls > 0 else None)
            execution = self.executor.execute_proposals(
                proposals, naming["partitions"],
                concurrency_adjust_metrics=self.load_monitor.broker_health_metrics,
                strategy=strategy, replication_throttle=replication_throttle,
                balancedness_scorer=scorer,
                replanner=replanner,
                replan_interval_polls=self._replan_interval_polls)
            ok = execution.ok
        return OperationResult(
            ok=ok, dryrun=dryrun, proposals=proposals,
            violated_goals_before=run.violated_goals_before,
            violated_goals_after=run.violated_goals_after,
            provision_status=run.provision_response.status.value,
            stats_before=run.stats_before.to_dict(),
            stats_after=run.stats_after.to_dict(),
            execution=execution, reason=reason, capped_goals=capped,
            balancedness_before=run.balancedness_before,
            balancedness_after=run.balancedness_after)

    def _make_replanner(self, run: opt.OptimizerRun,
                        naming: Dict[str, object]):
        """Build the executor's replan-while-executing hook.

        Called at phase boundaries (where ``score_checkpoints`` already
        dispatches) with the ledger's landed/in-flight partition sets; the
        fresh load-monitor model IS the partially-moved blend — landed
        moves are in the cluster metadata, so the warm re-solve (seeded
        from the previous converged placement, frontier = the delta the
        execution + churn created) targets exactly the remaining work.
        Returns ``None`` on any soundness failure (membership/naming
        drift, incompatible delta, verification failure) — the executor
        counts a fallback and keeps the current plan."""
        state = {"run": run}

        def replanner(landed: frozenset, inflight: frozenset
                      ) -> Optional[ReplanDirective]:
            fresh, naming2 = self._model_naming()
            if (list(naming2["brokers"]) != list(naming["brokers"])
                    or list(naming2["partitions"]) != list(naming["partitions"])):
                # Mid-execution membership/naming drift: task partition ids
                # would no longer address the same partitions — keep the
                # static plan and let an anomaly path deal with it.
                return None
            crun = state["run"]
            delta = model_delta(crun.model, fresh)
            if delta is None:
                return None
            goal_names = [g.name for g in crun.goal_results]
            run2 = self._optimize(
                fresh, goal_names, naming=naming2,
                warm_start=WarmStart(prev_model=crun.model,
                                     active_mask=delta.changed_mask))
            dense = props.diff(fresh, run2.model)
            try:
                verify_run(fresh, run2, goal_names,
                           constraint=self.constraint, proposals=dense)
            except VerificationError:
                return None
            proposals = props.renumber_brokers(dense, naming2["brokers"])
            scorer = opt.PlacementScorer.for_run(
                fresh, run2, self.constraint, *self._balancedness_weights)
            state["run"] = run2
            with self._cache_lock:
                self._executed_run_override = run2
            return ReplanDirective(
                proposals=proposals, scorer=scorer,
                info={"landed": len(landed), "inflight": len(inflight)})

        return replanner

    # ------------------------------------------------------------------
    # Standing proposal (cruise mode / warm start)
    # ------------------------------------------------------------------
    def _warm_allowed(self, warm: Optional[bool]) -> bool:
        """Resolve the tri-state per-request ``warm`` parameter: None
        defers to analyzer.warm.start.enabled; the cruise loop passes
        True explicitly (warm is default-on only for cruise)."""
        return self._warm_start_enabled if warm is None else bool(warm)

    def _confirm_standing(self, crun: opt.OptimizerRun) -> bool:
        """ONE fused on-device satisfaction sweep over the standing
        converged placement: every goal the standing run left satisfied
        must still pass, and no replica may have gone offline.  This is
        the entire device cost of a zero-delta request — no fixpoint
        program is dispatched and no frontier-driver fetch happens."""
        specs = opt.goals_by_priority([g.name for g in crun.goal_results])
        sweep_fn = opt._get_sweep_fn(tuple(specs), self.constraint)
        opt.SWEEP_COUNTERS["dispatches"] += 1
        sat_np, off_np = jax.device_get(sweep_fn(crun.model))
        if bool(off_np):
            return False
        sat = {s.name: bool(v) for s, v in zip(specs, np.asarray(sat_np))}
        return all(sat.get(g.name, False)
                   for g in crun.goal_results if g.satisfied_after)

    def _absorb_execution(self, run: opt.OptimizerRun, execution) -> None:
        """Executor completion feeds the standing baseline: once a
        default-stack plan fully lands, the cluster's placement IS the
        converged ``run.model``, so the standing entry re-bases onto it —
        pre-model = converged model, no outstanding proposals — instead of
        the next tick's delta probe re-discovering the very moves the
        executor just made (each executed partition showed up as "cluster
        changed under us" and forced a warm re-solve).  A failed or partial
        execution absorbs nothing: the placement is then neither the old
        baseline nor the converged model, and the ordinary delta probe is
        the honest path."""
        with self._cache_lock:
            override = self._executed_run_override
            self._executed_run_override = None
        if execution is None or not getattr(execution, "ok", False):
            return
        if override is not None:
            # The execution was replanned mid-flight: the placement that
            # actually landed is the LAST re-solve's converged model, not
            # the original run's.
            run = override
        gen = self.load_monitor.model_generation().as_tuple()
        with self._cache_lock:
            self._cached = (gen, time.monotonic(), run.model, run, [])

    def _consult_standing(self, model: TensorClusterModel,
                          warm: Optional[bool], ignore_proposal_cache: bool,
                          op: str):
        """Decide how a default-stack request uses the standing proposal.

        Returns ``("hit", standing_entry)`` when the fresh model is
        delta-free against the standing baseline and the confirm sweep
        passes (serve the cached proposals outright), ``("warm",
        WarmStart)`` when the delta is small enough to seed a warm solve,
        and ``("cold", None)`` otherwise (warm disabled, no standing entry,
        incompatible membership, or delta above the threshold)."""
        labels = {"op": op}
        hits = SENSORS.counter(
            "CruiseControl.standing-hits", labels=labels,
            help="Requests answered from the standing proposal after a "
                 "zero-delta confirm sweep")
        warms = SENSORS.counter(
            "CruiseControl.warm-solves", labels=labels,
            help="Requests solved warm — seeded from the standing "
                 "converged placement")
        SENSORS.counter(
            "CruiseControl.warm-fallbacks", labels=labels,
            help="Warm solves that failed verification and fell back to a "
                 "cold solve")
        if not self._warm_allowed(warm):
            return "cold", None
        with self._cache_lock:
            standing = self._cached
        if standing is None:
            return "cold", None
        _cgen, ctime, pre_model, crun, _cprops = standing
        delta = model_delta(pre_model, model)
        if delta is None:
            return "cold", None  # membership/shape drift: warm unsound
        fresh = (time.monotonic() - ctime) * 1000 < self._proposal_expiration_ms
        if delta.is_zero and fresh and not ignore_proposal_cache:
            if self._confirm_standing(crun):
                hits.inc(1)
                return "hit", standing
            return "cold", None
        if delta.magnitude <= self._warm_delta_threshold:
            # Seed frontier = brokers the cluster changed under us ∪
            # brokers the standing proposal itself touches (its moves are
            # not applied yet, so they stay live optimization surface).
            active = delta.changed_mask.copy()
            touched = model_delta(pre_model, crun.model)
            if touched is not None:
                active |= touched.changed_mask
            warms.inc(1)
            return "warm", WarmStart(prev_model=crun.model,
                                     active_mask=active)
        return "cold", None

    def _heal_warm_start(self, model: TensorClusterModel,
                         options: OptimizationOptions,
                         op: str) -> Optional[WarmStart]:
        """Seed a self-healing solve from the standing proposal.

        A detected anomaly mutates a small part of the fleet, so a heal is
        exactly the small-delta warm case cruise mode already handles: diff
        the wounded model against the standing CONVERGED placement and seed
        the fixpoint from it.  Every dead/demoted broker force-joins the
        seed frontier — ``model_delta``'s state clause only catches state
        *changes*, but a broker that was already dead when the standing
        entry was built must still be live optimization surface (its
        offline replicas are the heal's whole point).  No delta-magnitude
        gate: the dense confirm chunk validates convergence, so an
        oversized delta costs steps, never correctness.  Falls cold when
        self-heal exclusions are active — the standing placement predates
        them and could seed moves onto excluded brokers."""
        labels = {"op": op}
        warms = SENSORS.counter(
            "CruiseControl.heal-warm-solves", labels=labels,
            help="Self-healing solves seeded warm from the standing "
                 "proposal's converged placement")
        colds = SENSORS.counter(
            "CruiseControl.heal-cold-solves", labels=labels,
            help="Self-healing solves that ran cold (no standing entry, "
                 "membership drift, warm start disabled, or active "
                 "self-heal exclusions)")
        excluded = bool(
            np.asarray(options.broker_excluded_replica_move).any()
            or np.asarray(options.broker_excluded_leadership).any())
        if not self._warm_start_enabled or excluded:
            colds.inc(1)
            return None
        with self._cache_lock:
            standing = self._cached
        if standing is None:
            colds.inc(1)
            return None
        crun = standing[3]
        delta = model_delta(crun.model, model)
        if delta is None:
            colds.inc(1)
            return None  # membership/shape drift: warm unsound
        active = delta.changed_mask.copy()
        active |= ((np.asarray(model.broker_state) != BrokerState.ALIVE)
                   & np.asarray(model.broker_valid))
        warms.inc(1)
        TRACE.annotate(heal_warm=True,
                       heal_seed_frontier=int(active.sum()))
        return WarmStart(prev_model=crun.model, active_mask=active)

    @staticmethod
    def _standing_result(crun: opt.OptimizerRun,
                         cprops: List[props.ExecutionProposal],
                         reason: str) -> OperationResult:
        """OperationResult view of a cached/standing run (always a
        verified-ok run — only those are stored)."""
        return OperationResult(
            ok=True, dryrun=True, proposals=cprops,
            violated_goals_before=crun.violated_goals_before,
            violated_goals_after=crun.violated_goals_after,
            provision_status=crun.provision_response.status.value,
            stats_before=crun.stats_before.to_dict(),
            stats_after=crun.stats_after.to_dict(),
            reason=reason,
            capped_goals=[g.name for g in crun.goal_results if g.capped],
            balancedness_before=crun.balancedness_before,
            balancedness_after=crun.balancedness_after)

    def refresh_standing_proposals(self, force: bool = False,
                                   warm: Optional[bool] = None
                                   ) -> OperationResult:
        """The cruise loop's tick: bring the standing proposal up to the
        current model generation.  With ``force=False`` an unchanged
        generation is a pure cache read; an advanced generation runs the
        delta probe → zero-delta confirm / warm solve / cold solve.
        ``force=True`` recomputes even on an unchanged generation
        (ignore-cache semantics — which also repopulate the cache).

        This tick is the cruise loop's telemetry publish boundary: the
        tick wall time, whether the standing proposal answered (hit), and
        the device-fetch delta across the tick land in :data:`TELEMETRY`
        as points — host floats already on hand, no extra device work."""
        hits = SENSORS.counter("CruiseControl.standing-hits",
                               labels={"op": "proposals"})
        h0 = hits.count
        f0 = opt.FETCH_COUNTERS["device_fetches"]
        t0 = time.monotonic()
        result = self.proposals(ignore_proposal_cache=force, warm=warm)
        TELEMETRY.record("cruise.tick-wall-s", time.monotonic() - t0)
        TELEMETRY.record(STANDING_HIT_SERIES,
                         1.0 if hits.count > h0 else 0.0)
        TELEMETRY.record(FETCHES_SERIES,
                         opt.FETCH_COUNTERS["device_fetches"] - f0)
        TELEMETRY.record("cruise.proposal-count", len(result.proposals))
        return result

    # ------------------------------------------------------------------
    # Proposals (cached)
    # ------------------------------------------------------------------
    @_traced_op
    def proposals(self, goals: Optional[Sequence[str]] = None,
                  ignore_proposal_cache: bool = False,
                  excluded_topics_pattern: Optional[str] = None,
                  warm: Optional[bool] = None) -> OperationResult:
        """GET /proposals — cached while the model generation is unchanged
        and the cache is younger than proposal.expiration.ms.

        When warm start applies (config default or ``warm=True``), a
        generation bump first runs the host-side delta probe against the
        standing proposal: a zero-delta model serves the standing
        proposals after one confirm sweep (no fixpoint dispatch), a small
        delta seeds a warm solve from the standing converged placement,
        and a large delta (or a warm solve failing verification) falls
        back to the cold path."""
        gen = self.load_monitor.model_generation().as_tuple()
        default_stack = not goals and not excluded_topics_pattern
        use_cache = not ignore_proposal_cache and default_stack
        if use_cache:
            with self._cache_lock:
                if self._cached is not None:
                    cgen, ctime, _cmodel, crun, cprops = self._cached
                    fresh = (time.monotonic() - ctime) * 1000 < self._proposal_expiration_ms
                    if cgen == gen and fresh:
                        return self._standing_result(crun, cprops, "cached")
        model, naming = self._model_naming()
        if goals:
            self._validate_goals(goals)
        options = self._base_options(model, naming, excluded_topics_pattern)
        warm_start = None
        if default_stack:
            mode, payload = self._consult_standing(
                model, warm, ignore_proposal_cache, "proposals")
            if mode == "hit":
                _cgen, ctime, pre_model, crun, cprops = payload
                with self._cache_lock:
                    # Re-key the standing entry to the advanced generation
                    # so the next request takes the pure gen fast path.
                    self._cached = (gen, ctime, pre_model, crun, cprops)
                return self._standing_result(crun, cprops, "standing")
            if mode == "warm":
                warm_start = payload
        run = self._optimize(model, goals, options, warm_start=warm_start)
        result = self._finish(model, run, dryrun=True, reason="proposals",
                              naming=naming)
        if warm_start is not None and not result.ok:
            # Warm solve failed verification: cold fallback (correctness
            # never rests on the seed).
            SENSORS.counter(
                "CruiseControl.warm-fallbacks", labels={"op": "proposals"},
                help="Warm solves that failed verification and fell back "
                     "to a cold solve").inc(1)
            run = self._optimize(model, goals, options)
            result = self._finish(model, run, dryrun=True,
                                  reason="proposals", naming=naming)
        # Only verified-good runs are cacheable: a cached entry is always
        # served with ok=True.  ignore_proposal_cache recomputes AND
        # repopulates (reference semantics) — only the read is skipped.
        if default_stack and result.ok:
            with self._cache_lock:
                self._cached = (gen, time.monotonic(), model, run,
                                result.proposals)
        return result

    def invalidate_proposal_cache(self) -> None:
        with self._cache_lock:
            self._cached = None

    # ------------------------------------------------------------------
    # Admin operations (also the self-healing context SPI)
    # ------------------------------------------------------------------
    @_traced_op
    def rebalance(self, goals: Optional[Sequence[str]] = None, dryrun: bool = False,
                  destination_broker_ids: Optional[Sequence[int]] = None,
                  excluded_topics: Optional[Sequence[int]] = None,
                  reason: str = "rebalance",
                  fast_mode: bool = False,
                  rebalance_disk: bool = False,
                  self_healing: bool = False,
                  excluded_topics_pattern: Optional[str] = None,
                  replica_movement_strategies: Optional[Sequence[str]] = None,
                  replication_throttle: Optional[int] = None,
                  warm: Optional[bool] = None) -> OperationResult:
        model, naming = self._model_naming()
        if goals and not self_healing:
            # Self-healing fixes run detection goals, which an operator may
            # configure beyond the request-facing goals= set — internal
            # stacks are not gated (see _validate_goals).
            self._validate_goals(goals)
        strategy = self._request_strategy(replica_movement_strategies)
        options = self._base_options(model, naming, excluded_topics_pattern)
        if destination_broker_ids:
            mask = np.zeros(model.num_brokers, bool)
            mask[self._to_dense(naming, destination_broker_ids)] = True
            options = options.replace(requested_dest_only=jnp.asarray(mask))
        if excluded_topics:
            tmask = np.array(options.topic_excluded)
            tmask[list(excluded_topics)] = True
            options = options.replace(topic_excluded=jnp.asarray(tmask))
        warm_start = None
        if self_healing:
            options = self._self_heal_excludes(options, naming)
            # Heal pipeline: detector fired → delta probe → warm solve
            # seeded from the standing converged placement.
            warm_start = self._heal_warm_start(model, options, "rebalance")
        if rebalance_disk and goals is None:
            # rebalance_disk=true runs the intra-broker (JBOD) stack
            # (intra.broker.goals) instead of the inter-broker default.
            goals = self.intra_broker_goals
        # Standing-proposal consult applies only to the default stack with
        # no per-request model/constraint tweaks — anything else must solve
        # against its own options.
        default_stack = (not goals and not destination_broker_ids
                         and not excluded_topics and not rebalance_disk
                         and not self_healing and not excluded_topics_pattern
                         and not fast_mode)
        if default_stack:
            mode, payload = self._consult_standing(model, warm, False,
                                                   "rebalance")
            if mode == "hit":
                _cgen, _ctime, pre_model, crun, cprops = payload
                result = self._standing_result(crun, cprops, reason)
                result.dryrun = dryrun
                if not dryrun and cprops:
                    scorer = opt.PlacementScorer.for_run(
                        pre_model, crun, self.constraint,
                        *self._balancedness_weights)
                    execution = self.executor.execute_proposals(
                        cprops, naming["partitions"],
                        concurrency_adjust_metrics=self.load_monitor.broker_health_metrics,
                        strategy=strategy,
                        replication_throttle=replication_throttle,
                        balancedness_scorer=scorer)
                    result.execution = execution
                    result.ok = execution.ok
                    self._absorb_execution(crun, execution)
                return result
            if mode == "warm":
                warm_start = payload
        run = self._optimize(model, goals, options, fast_mode=fast_mode,
                             warm_start=warm_start)
        result = self._finish(model, run, dryrun, reason, naming,
                              strategy=strategy,
                              replication_throttle=replication_throttle)
        if warm_start is not None and not result.ok \
                and result.execution is None:
            # Warm solve failed verification (not an execution failure):
            # cold fallback.
            SENSORS.counter(
                "CruiseControl.warm-fallbacks", labels={"op": "rebalance"},
                help="Warm solves that failed verification and fell back "
                     "to a cold solve").inc(1)
            run = self._optimize(model, goals, options, fast_mode=fast_mode)
            result = self._finish(model, run, dryrun, reason, naming,
                                  strategy=strategy,
                                  replication_throttle=replication_throttle)
        if default_stack and not dryrun and result.ok:
            self._absorb_execution(run, result.execution)
        return result

    @_traced_op
    def add_brokers(self, broker_ids: Sequence[int], dryrun: bool = False,
                    reason: str = "add_brokers",
                    excluded_topics_pattern: Optional[str] = None,
                    replica_movement_strategies: Optional[Sequence[str]] = None,
                    replication_throttle: Optional[int] = None) -> OperationResult:
        """Move load onto NEW brokers (AddBrokersRunnable)."""
        model, naming = self._model_naming()
        for b in self._to_dense(naming, broker_ids):
            model = model.set_broker_state(b, BrokerState.NEW)
        self.executor.drop_recently_removed_brokers(list(broker_ids))
        strategy = self._request_strategy(replica_movement_strategies)
        options = self._base_options(model, naming, excluded_topics_pattern)
        run = self._optimize(model, self.goals, options)
        return self._finish(model, run, dryrun, reason, naming,
                            strategy=strategy,
                            replication_throttle=replication_throttle)

    @_traced_op
    def remove_brokers(self, broker_ids: Sequence[int], dryrun: bool = False,
                       reason: str = "remove_brokers",
                       self_healing: bool = False,
                       excluded_topics_pattern: Optional[str] = None,
                       replica_movement_strategies: Optional[Sequence[str]] = None,
                       replication_throttle: Optional[int] = None) -> bool:
        """Decommission: drain all replicas off the brokers
        (RemoveBrokersRunnable)."""
        model, naming = self._model_naming()
        for b in self._to_dense(naming, broker_ids):
            model = model.set_broker_state(b, BrokerState.DEAD)
        strategy = self._request_strategy(replica_movement_strategies)
        options = self._base_options(model, naming, excluded_topics_pattern)
        warm_start = None
        if self_healing:
            options = self._self_heal_excludes(options, naming)
            warm_start = self._heal_warm_start(model, options,
                                               "remove_brokers")
        run = self._optimize(model, self.goals, options,
                             warm_start=warm_start)
        result = self._finish(model, run, dryrun, reason, naming,
                              strategy=strategy,
                              replication_throttle=replication_throttle)
        if warm_start is not None and not result.ok \
                and result.execution is None:
            # Warm heal failed verification: cold fallback.
            SENSORS.counter(
                "CruiseControl.warm-fallbacks",
                labels={"op": "remove_brokers"},
                help="Warm solves that failed verification and fell back "
                     "to a cold solve").inc(1)
            run = self._optimize(model, self.goals, options)
            result = self._finish(model, run, dryrun, reason, naming,
                                  strategy=strategy,
                                  replication_throttle=replication_throttle)
        if result.ok and not dryrun:
            self.executor.add_recently_removed_brokers(list(broker_ids))
        return result.ok

    @_traced_op
    def demote_brokers(self, broker_ids: Sequence[int], dryrun: bool = False,
                       reason: str = "demote_brokers") -> bool:
        """Transfer ALL leadership off the brokers (DemoteBrokerRunnable →
        PreferredLeaderElectionGoal).  Reference parity: the runnable moves
        demoted brokers' replicas to the end of the replica list and elects
        new leaders; here every leader replica on a DEMOTED broker becomes a
        mandatory leadership-transfer source (preferred_leader kernel), any
        eligible non-demoted sibling the destination.  Reports ok only when
        zero leaders remain on the demoted brokers."""
        model, naming = self._model_naming()
        dense = self._to_dense(naming, broker_ids)
        for b in dense:
            model = model.set_broker_state(b, BrokerState.DEMOTED)
        options = OptimizationOptions.none(model)
        mask = np.zeros(model.num_brokers, bool)
        mask[dense] = True
        options = options.replace(broker_excluded_leadership=jnp.asarray(mask))
        run = self._optimize(model, ["PreferredLeaderElectionGoal"], options)
        # Demotion must actually have happened: a no-op "ok" (leaders still
        # on demoted brokers inside the leader-balance band) was a round-1
        # advisory finding.  Leaders with no eligible non-demoted online
        # sibling (e.g. RF=1 partitions) are unmovable and don't count
        # against success — the reference succeeds after moving all movable
        # leadership (DemoteBrokerRunnable skips URPs likewise).
        leaders_left = self._movable_leaders_on(run.model, dense)
        result = self._finish(model, run, dryrun, reason, naming)
        ok = result.ok and leaders_left == 0
        if ok and not dryrun:
            self.executor.add_recently_demoted_brokers(list(broker_ids))
        return ok

    @staticmethod
    def _movable_leaders_on(model: TensorClusterModel, dense: Sequence[int]) -> int:
        """Count leader replicas on the given (dense-index) brokers that have
        at least one valid, online sibling on an alive non-demoted broker."""
        rb = np.asarray(model.replica_broker)
        lead = np.asarray(model.replica_is_leader)
        valid = np.asarray(model.replica_valid)
        part = np.asarray(model.replica_partition)
        pr = np.asarray(model.partition_replicas)
        state = np.asarray(model.broker_state)
        offline = np.asarray(model.replica_offline_now())
        count = 0
        for r in np.nonzero(lead & valid & np.isin(rb, list(dense)))[0]:
            for s in pr[part[r]]:
                if s < 0 or s == r or not valid[s] or offline[s]:
                    continue
                if state[rb[s]] not in (BrokerState.DEAD, BrokerState.DEMOTED):
                    count += 1
                    break
        return count

    @_traced_op
    def fix_offline_replicas(self, dryrun: bool = False,
                             reason: str = "fix_offline_replicas",
                             self_healing: bool = False) -> bool:
        """Heal offline replicas via the hard-goal stack
        (FixOfflineReplicasRunnable)."""
        model, naming = self._model_naming()
        options = self._base_options(model, naming)
        warm_start = None
        if self_healing:
            options = self._self_heal_excludes(options, naming)
            warm_start = self._heal_warm_start(model, options,
                                               "fix_offline_replicas")
        run = self._optimize(model, self.hard_goals, options,
                             warm_start=warm_start)
        result = self._finish(model, run, dryrun, reason, naming)
        if warm_start is not None and not result.ok \
                and result.execution is None:
            SENSORS.counter(
                "CruiseControl.warm-fallbacks",
                labels={"op": "fix_offline_replicas"},
                help="Warm solves that failed verification and fell back "
                     "to a cold solve").inc(1)
            run = self._optimize(model, self.hard_goals, options)
            result = self._finish(model, run, dryrun, reason, naming)
        return result.ok

    @_traced_op
    def update_topic_replication_factor(self, topics_rf: Dict[str, int],
                                        dryrun: bool = False,
                                        reason: str = "topic_rf_update") -> bool:
        """Set topics to the desired RF (UpdateTopicConfigurationRunnable):
        grow rack-aware onto least-loaded brokers, shrink by dropping
        non-leader replicas from most-loaded brokers."""
        cluster = self.load_monitor._metadata.cluster()
        model = self._model()
        load = np.asarray(model.broker_load()).sum(axis=1)
        naming = self.load_monitor.naming()
        broker_rack = {b.broker_id: b.rack for b in cluster.brokers}
        alive = set(cluster.alive_broker_ids())
        requests = []
        for p in cluster.partitions:
            want = topics_rf.get(p.topic)
            if want is None or len(p.replicas) == want:
                continue
            replicas = list(p.replicas)
            if len(replicas) < want:
                used_racks = {broker_rack[b] for b in replicas}
                pool = [b for b in alive if b not in replicas]
                while len(replicas) < want and pool:
                    # Re-rank each pick so freshly used racks are deprioritized
                    # (rack-aware growth, not just a one-shot sort).
                    pool.sort(key=lambda b: (broker_rack[b] in used_racks,
                                             load[naming["brokers"].index(b)]))
                    b = pool.pop(0)
                    replicas.append(b)
                    used_racks.add(broker_rack[b])
            else:
                followers = [b for b in replicas if b != p.leader]
                followers.sort(key=lambda b: -load[naming["brokers"].index(b)])
                for b in followers[: len(replicas) - want]:
                    replicas.remove(b)
            requests.append(ReassignmentRequest(tp=p.tp, new_replicas=tuple(replicas)))
        if not requests:
            return False
        if dryrun:
            return True
        self.admin.alter_partition_reassignments(requests)
        deadline = time.monotonic() + 600.0
        while self.admin.ongoing_reassignments():
            if time.monotonic() > deadline:
                return False  # stalled reassignment; leave it to the operator
            time.sleep(0.01)
        self.load_monitor._metadata.refresh(self.load_monitor._metadata.cluster())
        return True

    # ------------------------------------------------------------------
    # State / control
    # ------------------------------------------------------------------
    def state(self, detector_manager=None) -> Dict[str, object]:
        """GET /state payload (monitor + executor + analyzer + detector)."""
        lm = self.load_monitor
        out: Dict[str, object] = {
            "MonitorState": {
                "state": lm.state().value,
                "validWindows": lm.partition_aggregator.valid_windows(),
                "monitoredPartitionsPercentage": lm.monitored_partitions_percentage(),
                "pauseReason": lm.pause_reason,
            },
            "ExecutorState": self.executor.state_summary(),
            "AnalyzerState": {
                "goals": self.goals,
                "proposalsCached": self._cached is not None,
                # Whether the NEXT optimization records per-step flight
                # telemetry (CRUISE_FLIGHT_RECORDER env, possibly seeded
                # from analyzer.flight.recorder config) — operators check
                # here before expecting /flight data.
                "flightRecorder": opt._flight_recorder(),
                # Warm-start / standing-proposal policy and the generation
                # the standing entry was computed at (None = no standing).
                "warmStart": {
                    "enabled": self._warm_start_enabled,
                    "deltaThreshold": self._warm_delta_threshold,
                    "standingGeneration": (list(self._cached[0])
                                           if self._cached else None),
                },
            },
        }
        if detector_manager is not None:
            out["AnomalyDetectorState"] = detector_manager.state_dict()
        # Windowed SLA rollups from the telemetry time-series store (1 h
        # default window) — the long-horizon view next to the point-in-time
        # substates; see docs/OBSERVABILITY.md "Telemetry time-series & SLA".
        out["Sla"] = TELEMETRY.sla()
        sensors = SENSORS.snapshot()
        # Per-operation trace rollup (count/totalMs/maxMs by root span name)
        # rides inside the Sensors block so /state answers "where does a
        # rebalance spend its time" without a separate /trace query.
        sensors["traces"] = TRACE.rollup()
        out["Sensors"] = sensors
        return out

    def kafka_cluster_state(self) -> Dict[str, object]:
        """GET /kafka_cluster_state payload."""
        cluster = self.load_monitor._metadata.cluster()
        return {
            "brokers": [dataclasses.asdict(b) for b in cluster.brokers],
            "partitions": [
                {"topic": p.topic, "partition": p.partition, "leader": p.leader,
                 "replicas": list(p.replicas),
                 "offlineReplicas": list(p.offline_replicas)}
                for p in cluster.partitions],
        }

    def partition_load(self, max_entries: int = 100) -> List[Dict[str, object]]:
        """GET /partition_load: partitions sorted by utilization."""
        agg = self.load_monitor.partition_aggregator.aggregate()
        from cruise_control_tpu.monitor.metricdef import KAFKA_METRIC_DEF
        rows = []
        for row, tp in enumerate(agg.entities):
            if not agg.entity_valid[row]:
                continue
            m = {info.name: float(agg.collapsed[row, info.metric_id])
                 for info in KAFKA_METRIC_DEF.all_metric_infos()[:4]}
            rows.append({"topic": tp[0], "partition": tp[1], **m})
        rows.sort(key=lambda r: -r.get("DISK_USAGE", 0.0))
        return rows[:max_entries]

    def broker_load(self) -> Dict[str, object]:
        """GET /load: per-broker utilization + stats."""
        model = self._model()
        load = np.asarray(model.broker_load())
        cap = np.asarray(model.broker_capacity)
        valid = np.asarray(model.broker_valid)
        brokers = []
        naming = self.load_monitor.naming()
        for i, b in enumerate(naming["brokers"]):
            if not valid[i]:
                continue
            brokers.append({
                "broker": b,
                "cpu": float(load[i, 0]), "networkInbound": float(load[i, 1]),
                "networkOutbound": float(load[i, 2]), "disk": float(load[i, 3]),
                "diskPct": float(load[i, 3] / max(cap[i, 3], 1e-9) * 100),
                "replicas": int(np.asarray(model.broker_replica_counts())[i]),
                "leaders": int(np.asarray(model.broker_leader_counts())[i]),
            })
        return {"brokers": brokers, "stats": compute_stats(model).to_dict()}

    def stop_proposal_execution(self, force: bool = False) -> None:
        self.executor.stop_execution(force=force)

    def pause_sampling(self, reason: str = "") -> None:
        self.load_monitor.pause_sampling(reason)

    def resume_sampling(self) -> None:
        self.load_monitor.resume_sampling()
