"""Aggregate cluster statistics.

Parity with the reference's ``ClusterModelStats`` (model/ClusterModelStats.java:30):
per-resource avg/max/min/std-dev of broker utilization, replica-count and
leader-count statistics, topic-replica stats, and potential NW_OUT — the
values goal comparators order candidate states by
(Goal.ClusterModelStatsComparator, analyzer/goals/Goal.java).  Computed as a
single jitted reduction over the tensor model.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from flax import struct
from jax import Array

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.model.tensor_model import TensorClusterModel


@struct.dataclass
class ClusterModelStats:
    # per-resource broker utilization stats, f32[4]
    resource_util_mean: Array
    resource_util_max: Array
    resource_util_min: Array
    resource_util_std: Array
    # replica / leader count stats over alive brokers
    replica_count_mean: Array
    replica_count_max: Array
    replica_count_min: Array
    replica_count_std: Array
    leader_count_mean: Array
    leader_count_max: Array
    leader_count_min: Array
    leader_count_std: Array
    # potential NW_OUT stats
    potential_nw_out_mean: Array
    potential_nw_out_max: Array
    num_alive_brokers: Array
    num_replicas: Array

    def to_dict(self) -> Dict[str, object]:
        import numpy as np

        # One batched fetch for all 16 leaves (per-leaf np.asarray is one
        # device round trip each on a tunneled TPU).
        host = jax.device_get(self)

        def ser(x):
            arr = np.asarray(x)
            return arr.item() if arr.ndim == 0 else arr.tolist()

        out = {}
        for name in ("resource_util_mean", "resource_util_max", "resource_util_min",
                     "resource_util_std"):
            vals = ser(getattr(host, name))
            out[name] = {r.resource_name: vals[r.value] for r in Resource}
        for name in ("replica_count_mean", "replica_count_max", "replica_count_min",
                     "replica_count_std", "leader_count_mean", "leader_count_max",
                     "leader_count_min", "leader_count_std", "potential_nw_out_mean",
                     "potential_nw_out_max", "num_alive_brokers", "num_replicas"):
            out[name] = ser(getattr(host, name))
        return out


def _masked_stats(values: Array, mask: Array):
    n = jnp.maximum(mask.sum(), 1)
    mean = jnp.where(mask, values, 0.0).sum(axis=0) / n
    vmax = jnp.where(mask, values, -jnp.inf).max(axis=0)
    vmin = jnp.where(mask, values, jnp.inf).min(axis=0)
    var = (jnp.where(mask, (values - mean) ** 2, 0.0)).sum(axis=0) / n
    return mean, vmax, vmin, jnp.sqrt(var)


def compute_stats(model: TensorClusterModel) -> ClusterModelStats:
    """Populate stats over alive brokers (ClusterModelStats.populate,
    model/ClusterModelStats.java:84)."""
    alive = model.alive_broker_mask()
    util = model.broker_load()
    mean, vmax, vmin, std = _masked_stats(util, alive[:, None])

    rc = model.broker_replica_counts().astype(jnp.float32)
    rc_mean, rc_max, rc_min, rc_std = _masked_stats(rc, alive)
    lc = model.broker_leader_counts().astype(jnp.float32)
    lc_mean, lc_max, lc_min, lc_std = _masked_stats(lc, alive)

    pnw = model.potential_leadership_load()
    pnw_mean, pnw_max, _, _ = _masked_stats(pnw, alive)

    return ClusterModelStats(
        resource_util_mean=mean, resource_util_max=vmax, resource_util_min=vmin,
        resource_util_std=std,
        replica_count_mean=rc_mean, replica_count_max=rc_max, replica_count_min=rc_min,
        replica_count_std=rc_std,
        leader_count_mean=lc_mean, leader_count_max=lc_max, leader_count_min=lc_min,
        leader_count_std=lc_std,
        potential_nw_out_mean=pnw_mean, potential_nw_out_max=pnw_max,
        num_alive_brokers=alive.sum(), num_replicas=model.replica_valid.sum(),
    )


compute_stats_jit = jax.jit(compute_stats)


def utilization_variance(model: TensorClusterModel) -> Array:
    """f32[4] variance of broker utilization per resource
    (ClusterModel.variance, ClusterModel.java:1313)."""
    stats = compute_stats(model)
    return stats.resource_util_std ** 2
