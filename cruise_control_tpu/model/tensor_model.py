"""The struct-of-arrays tensor cluster model.

This is the TPU-native redesign of the reference's mutable object-graph
``ClusterModel`` (cruise-control/src/main/java/.../model/ClusterModel.java:46,
with Rack.java:30 / Host.java:26 / Broker.java:34 / Disk.java:29 /
Replica.java:25 / Partition.java:20 as nested objects).  Where the reference
cascades load bookkeeping through rack→host→broker object references on every
replica move (ClusterModel.java:377-431), here the entire cluster state is a
frozen pytree of flat arrays over three axes — replicas (R), brokers (B),
partitions (P) — and every aggregate (broker/host/rack load, replica counts,
potential leadership load, partition-rack occupancy) is a segment reduction
recomputed in one fused XLA kernel.  Mutations are pure functions returning a
new pytree, so candidate balancing actions can be *speculatively* evaluated
in parallel (vmap over action batches) without copying any state.

Load semantics: each replica carries two load rows — its utilization as a
leader and as a follower (f32[R, 4] each, resource axis per
``common.Resource``).  The actual load is selected by the leadership flag.
This makes leadership movement a pure index flip with the same incremental
load-delta semantics the reference implements imperatively in
``Rack.makeFollower``/``makeLeader`` (ClusterModel.java:406-431): the
follower rows keep only CPU+NW_IN+DISK components, matching how the
reference strips leader-only load (NW_OUT, leadership CPU) when leadership
transfers.

Padding: R/B/P axes may be padded; ``*_valid`` masks mark live rows.  All
shapes are static under ``jit``; broker/rack/host counts are static Python
ints (pytree aux data).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct
from jax import Array

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.ops.segment import masked_segment_count, masked_segment_sum


class BrokerState:
    """Broker liveness states (reference: model/Broker.java:37)."""

    ALIVE = 0
    DEAD = 1
    NEW = 2
    DEMOTED = 3
    BAD_DISKS = 4


@struct.dataclass
class TensorClusterModel:
    # --- replica axis (R) ---
    replica_broker: Array  # i32[R] current broker id
    replica_partition: Array  # i32[R] global partition id
    replica_topic: Array  # i32[R] topic id
    replica_is_leader: Array  # bool[R]
    replica_load_leader: Array  # f32[R, 4] utilization if leader
    replica_load_follower: Array  # f32[R, 4] utilization if follower
    replica_valid: Array  # bool[R] padding mask
    replica_original_broker: Array  # i32[R] broker at model build (immigrant tracking)
    replica_offline: Array  # bool[R] replica on dead broker/disk
    replica_disk: Array  # i32[R] global disk index (-1 when not JBOD)

    # --- broker axis (B) ---
    broker_capacity: Array  # f32[B, 4]
    broker_rack: Array  # i32[B]
    broker_host: Array  # i32[B]
    broker_state: Array  # i8[B] BrokerState
    broker_valid: Array  # bool[B]

    # --- disk axis (D) --- (D == B when not JBOD; one implicit disk/broker)
    disk_broker: Array  # i32[D]
    disk_capacity: Array  # f32[D], < 0 means dead disk
    disk_valid: Array  # bool[D]
    broker_first_disk: Array  # i32[B] — default landing disk for inter-broker moves
    broker_disks: Array  # i32[B, max_disks_per_broker] disk ids (-1 pad)

    # --- partition axis (P) ---
    partition_topic: Array  # i32[P]
    partition_valid: Array  # bool[P]
    # i32[P, max_rf] replica ids of each partition (-1 pad).  Membership is
    # static (moves change replica_broker, not partition membership), so this
    # is built once and lets rack/legit-move checks gather a partition's
    # sibling replicas in O(max_rf) instead of a P×B occupancy matrix.
    partition_replicas: Array

    # --- static metadata (aux data, not traced) ---
    num_brokers: int = struct.field(pytree_node=False)
    num_racks: int = struct.field(pytree_node=False)
    num_hosts: int = struct.field(pytree_node=False)
    num_topics: int = struct.field(pytree_node=False)
    num_partitions: int = struct.field(pytree_node=False)
    num_disks: int = struct.field(pytree_node=False)
    max_rf: int = struct.field(pytree_node=False)

    # ------------------------------------------------------------------
    # Shape helpers
    # ------------------------------------------------------------------
    @property
    def num_replicas_padded(self) -> int:
        return self.replica_broker.shape[0]

    # ------------------------------------------------------------------
    # Load queries (reference: Load.java:29, ClusterModel.java:1299-1330)
    # ------------------------------------------------------------------
    def replica_load(self) -> Array:
        """f32[R, 4] actual utilization given current leadership."""
        return jnp.where(self.replica_is_leader[:, None], self.replica_load_leader,
                         self.replica_load_follower)

    def broker_load(self) -> Array:
        """f32[B, 4] per-broker utilization — the generalization of
        ``ClusterModel.utilizationMatrix()`` (ClusterModel.java:1330)."""
        return masked_segment_sum(self.replica_load(), self.replica_broker,
                                  self.num_brokers, self.replica_valid)

    def host_load(self) -> Array:
        """f32[H, 4] per-host utilization (host-level resources)."""
        return masked_segment_sum(self.broker_load(), self.broker_host,
                                  self.num_hosts, self.broker_valid)

    def rack_load(self) -> Array:
        return masked_segment_sum(self.broker_load(), self.broker_rack,
                                  self.num_racks, self.broker_valid)

    def potential_leadership_load(self) -> Array:
        """f32[B] potential NW_OUT per broker if *all* its replicas led
        (reference: ClusterModel.potentialLeadershipLoadFor, ClusterModel.java:219)."""
        return masked_segment_sum(self.replica_load_leader[:, Resource.NW_OUT],
                                  self.replica_broker, self.num_brokers, self.replica_valid)

    def broker_replica_counts(self) -> Array:
        """i32[B] replicas per broker."""
        return masked_segment_count(self.replica_broker, self.num_brokers, self.replica_valid)

    def broker_leader_counts(self) -> Array:
        """i32[B] leader replicas per broker."""
        return masked_segment_count(self.replica_broker, self.num_brokers,
                                    self.replica_valid & self.replica_is_leader)

    def topic_leader_counts(self) -> Array:
        """i32[T, B] leaders of each topic on each broker
        (MinTopicLeadersPerBrokerGoal input, goals/MinTopicLeadersPerBrokerGoal.java:50)."""
        flat = self.replica_topic * self.num_brokers + self.replica_broker
        counts = masked_segment_count(flat, self.num_topics * self.num_brokers,
                                      self.replica_valid & self.replica_is_leader)
        return counts.reshape(self.num_topics, self.num_brokers)

    def preferred_leader_replica(self) -> Array:
        """i32[P] the preferred (first-listed) replica of each partition
        (PreferredLeaderElectionGoal.java:36 — replica[0] should lead)."""
        return self.partition_replicas[:, 0]

    def broker_leader_bytes_in(self) -> Array:
        """f32[B] leader NW_IN per broker (LeaderBytesInDistributionGoal input)."""
        load = jnp.where(self.replica_is_leader, self.replica_load_leader[:, Resource.NW_IN], 0.0)
        return masked_segment_sum(load, self.replica_broker, self.num_brokers, self.replica_valid)

    def topic_broker_replica_counts(self) -> Array:
        """i32[T, B] replicas of each topic on each broker (TopicReplicaDistributionGoal)."""
        flat = self.replica_topic * self.num_brokers + self.replica_broker
        counts = masked_segment_count(flat, self.num_topics * self.num_brokers, self.replica_valid)
        return counts.reshape(self.num_topics, self.num_brokers)

    def disk_load(self) -> Array:
        """f32[D] disk utilization (DISK resource only)."""
        disk_ids = jnp.where(self.replica_disk >= 0, self.replica_disk, 0)
        mask = self.replica_valid & (self.replica_disk >= 0)
        return masked_segment_sum(self.replica_load()[:, Resource.DISK], disk_ids,
                                  self.num_disks, mask)

    # ------------------------------------------------------------------
    # Topology / placement queries
    # ------------------------------------------------------------------
    def partition_rack_counts(self) -> Array:
        """i32[P, num_racks] — how many replicas of each partition sit in each
        rack (the vectorized form of RackAwareGoal's per-partition scan,
        goals/RackAwareGoal.java:33)."""
        replica_rack = self.broker_rack[self.replica_broker]
        flat = self.replica_partition * self.num_racks + replica_rack
        counts = masked_segment_count(flat, self.num_partitions * self.num_racks,
                                      self.replica_valid)
        return counts.reshape(self.num_partitions, self.num_racks)

    def partition_broker_counts(self) -> Array:
        """i32[P, B] replica multiplicity per (partition, broker) — used to
        forbid moving a replica onto a broker that already hosts the
        partition (legitMove, goals/GoalUtils.java)."""
        flat = self.replica_partition * self.num_brokers + self.replica_broker
        counts = masked_segment_count(flat, self.num_partitions * self.num_brokers,
                                      self.replica_valid)
        return counts.reshape(self.num_partitions, self.num_brokers)

    def partition_replication_factor(self) -> Array:
        """i32[P] current replication factor per partition."""
        return masked_segment_count(self.replica_partition, self.num_partitions,
                                    self.replica_valid)

    def partition_leader_replica(self) -> Array:
        """i32[P] replica index of each partition's leader (-1 if none)."""
        r_idx = jnp.arange(self.num_replicas_padded, dtype=jnp.int32)
        mask = self.replica_valid & self.replica_is_leader
        seg = jnp.where(mask, self.replica_partition, 0)
        out = jnp.full((self.num_partitions,), -1, jnp.int32)
        return out.at[seg].max(jnp.where(mask, r_idx, -1))

    def replica_offline_now(self) -> Array:
        """bool[R] — replica is *currently* offline: it sits on a dead broker
        or a dead disk (capacity < 0), or was reported offline by metadata at
        model build (``replica_offline``) and has not moved since.  Derived
        from placement rather than read directly, so moving a replica off
        dead hardware heals it — matching the reference where a relocated
        replica is a fresh online replica (Replica.java isCurrentOffline is
        placement-scoped)."""
        on_dead_broker = self.broker_state[self.replica_broker] == BrokerState.DEAD
        disk_ids = jnp.where(self.replica_disk >= 0, self.replica_disk, 0)
        on_dead_disk = (self.replica_disk >= 0) & (self.disk_capacity[disk_ids] < 0.0)
        sticky = self.replica_offline & (self.replica_broker == self.replica_original_broker)
        return (on_dead_broker | on_dead_disk | sticky) & self.replica_valid

    def alive_broker_mask(self) -> Array:
        """bool[B] brokers that can receive replicas (reference:
        ClusterModel.aliveBrokers — DEAD brokers excluded)."""
        return self.broker_valid & (self.broker_state != BrokerState.DEAD)

    def new_broker_mask(self) -> Array:
        return self.broker_valid & (self.broker_state == BrokerState.NEW)

    def demoted_broker_mask(self) -> Array:
        return self.broker_valid & (self.broker_state == BrokerState.DEMOTED)

    # ------------------------------------------------------------------
    # Mutations (pure; return a new model)
    # ------------------------------------------------------------------
    def relocate_replicas(self, replica_ids: Array, dest_brokers: Array,
                          apply_mask: Optional[Array] = None) -> "TensorClusterModel":
        """Move replicas to destination brokers (vectorized
        ``relocateReplica``, ClusterModel.java:377).  ``apply_mask`` lets a
        fixed-size batch apply only its accepted prefix under jit."""
        if apply_mask is None:
            apply_mask = jnp.ones(replica_ids.shape, bool)
        # Scatter-*add* of deltas: masked slots contribute 0, so duplicate
        # replica ids across a candidate batch (same replica × many probed
        # destinations, at most one selected) are well-defined — XLA leaves
        # write order for duplicate-index scatter-set unspecified, which
        # would let a masked no-op clobber the accepted write.  At most one
        # unmasked entry per replica is the caller's contract.
        current = self.replica_broker[replica_ids]
        delta = jnp.where(apply_mask, dest_brokers.astype(jnp.int32) - current, 0)
        new_broker = self.replica_broker.at[replica_ids].add(delta)
        # An inter-broker move lands the replica on the destination broker's
        # default disk (the reference picks a destination logdir in the
        # proposal; intra-broker rebalancing then refines placement via
        # relocate_replicas_to_disk).
        cur_disk = self.replica_disk[replica_ids]
        dest_disk = self.broker_first_disk[dest_brokers.astype(jnp.int32)]
        disk_delta = jnp.where(apply_mask, dest_disk - cur_disk, 0)
        new_disk = self.replica_disk.at[replica_ids].add(disk_delta)
        return self.replace(replica_broker=new_broker, replica_disk=new_disk)

    def relocate_leadership(self, src_replica_ids: Array, dest_replica_ids: Array,
                            apply_mask: Optional[Array] = None) -> "TensorClusterModel":
        """Transfer leadership from leader replicas to follower replicas of
        the same partitions (vectorized ``relocateLeadership``,
        ClusterModel.java:406)."""
        if apply_mask is None:
            apply_mask = jnp.ones(src_replica_ids.shape, bool)
        # Add-of-delta on an int view for the same duplicate-index reason as
        # relocate_replicas: each applied transfer contributes -1 at the old
        # leader and +1 at the new one; masked duplicates contribute 0.
        lead = self.replica_is_leader.astype(jnp.int32)
        d = apply_mask.astype(jnp.int32)
        lead = lead.at[src_replica_ids].add(-d)
        lead = lead.at[dest_replica_ids].add(d)
        return self.replace(replica_is_leader=lead.astype(bool))

    def relocate_replicas_to_disk(self, replica_ids: Array, dest_disks: Array,
                                  apply_mask: Optional[Array] = None) -> "TensorClusterModel":
        """Intra-broker move: reassign replicas across a broker's disks."""
        if apply_mask is None:
            apply_mask = jnp.ones(replica_ids.shape, bool)
        cur = self.replica_disk[replica_ids]
        delta = jnp.where(apply_mask, dest_disks.astype(jnp.int32) - cur, 0)
        return self.replace(replica_disk=self.replica_disk.at[replica_ids].add(delta))

    def set_broker_state(self, broker_id: int, state: int) -> "TensorClusterModel":
        """Set a broker's liveness state (ClusterModel.setBrokerState).
        Marking DEAD also marks its replicas offline."""
        new_state = self.broker_state.at[broker_id].set(state)
        if state == BrokerState.DEAD:
            on_broker = self.replica_broker == broker_id
            new_offline = jnp.where(on_broker & self.replica_valid, True, self.replica_offline)
        else:
            new_offline = self.replica_offline
        return self.replace(broker_state=new_state, replica_offline=new_offline)

    def with_placement(self, replica_broker: Array, replica_is_leader: Array,
                       replica_disk: Optional[Array] = None) -> "TensorClusterModel":
        """Swap in a hypothetical replica placement (broker assignment,
        leadership, optionally disks) keeping every other axis untouched —
        the executor's balancedness scorer uses this to evaluate blends of
        the before/after placements as movement batches land."""
        kwargs = dict(replica_broker=replica_broker,
                      replica_is_leader=replica_is_leader)
        if replica_disk is not None:
            kwargs["replica_disk"] = replica_disk
        return self.replace(**kwargs)

    # ------------------------------------------------------------------
    # Sanity (reference: ClusterModel.sanityCheck, ClusterModel.java:1144)
    # ------------------------------------------------------------------
    def sanity_check(self) -> None:
        """Host-side invariant checks; raises on violation."""
        rb = np.asarray(self.replica_broker)
        valid = np.asarray(self.replica_valid)
        bvalid = np.asarray(self.broker_valid)
        if not ((rb[valid] >= 0) & (rb[valid] < self.num_brokers)).all():
            raise ValueError("replica assigned to out-of-range broker")
        if not bvalid[rb[valid]].all():
            raise ValueError("replica assigned to invalid broker slot")
        # Exactly one leader per valid partition with >=1 replica.
        leaders = np.asarray(masked_segment_count(
            self.replica_partition, self.num_partitions,
            self.replica_valid & self.replica_is_leader))
        rf = np.asarray(self.partition_replication_factor())
        bad = (rf > 0) & (leaders != 1)
        if bad.any():
            raise ValueError(f"partitions without exactly one leader: {np.nonzero(bad)[0][:10]}")
        # No two replicas of one partition on the same broker.  Host-side
        # int64 pair keys: the dense P×B segment space overflows int32 at
        # the 7k-broker / 334k-partition scale (P·B ≈ 2.3e9) and would
        # materialize gigabytes.
        rp = np.asarray(self.replica_partition)
        pairs = rp[valid].astype(np.int64) * self.num_brokers + rb[valid]
        if pairs.size != np.unique(pairs).size:
            raise ValueError("partition has multiple replicas on one broker")
        # Replica's disk must belong to the broker hosting the replica.
        rd = np.asarray(self.replica_disk)
        disk_owner = np.asarray(self.disk_broker)
        has_disk = valid & (rd >= 0)
        if not (disk_owner[rd[has_disk]] == rb[has_disk]).all():
            raise ValueError("replica assigned to a disk on a different broker")


def build_model(
    replica_broker: np.ndarray,
    replica_partition: np.ndarray,
    replica_topic: np.ndarray,
    replica_is_leader: np.ndarray,
    replica_load_leader: np.ndarray,
    replica_load_follower: np.ndarray,
    broker_capacity: np.ndarray,
    broker_rack: np.ndarray,
    broker_host: Optional[np.ndarray] = None,
    broker_state: Optional[np.ndarray] = None,
    partition_topic: Optional[np.ndarray] = None,
    replica_disk: Optional[np.ndarray] = None,
    disk_broker: Optional[np.ndarray] = None,
    disk_capacity: Optional[np.ndarray] = None,
    pad_replicas_to: Optional[int] = None,
    pad_brokers_to: Optional[int] = None,
) -> TensorClusterModel:
    """Assemble a TensorClusterModel from host numpy arrays, with padding.

    The edge-layer analogue of LoadMonitor's model generation
    (monitor/LoadMonitor.java:455-520): callers produce flat arrays (from
    aggregated samples + metadata) and this function performs padding,
    validation, and device placement.
    """
    R = int(replica_broker.shape[0])
    B = int(broker_capacity.shape[0])
    Rp = int(pad_replicas_to or R)
    Bp = int(pad_brokers_to or B)
    if Rp < R or Bp < B:
        raise ValueError("padding must not truncate")

    if broker_host is None:
        broker_host = np.arange(B, dtype=np.int32)  # one broker per host
    if broker_state is None:
        broker_state = np.zeros(B, np.int8)
    num_topics = int(replica_topic.max()) + 1 if R else 1
    num_partitions = int(replica_partition.max()) + 1 if R else 1
    if partition_topic is None:
        partition_topic = np.zeros(num_partitions, np.int32)
        partition_topic[replica_partition] = replica_topic
    P = int(partition_topic.shape[0])
    num_racks = int(broker_rack.max()) + 1 if B else 1
    num_hosts = int(broker_host.max()) + 1 if B else 1

    if disk_broker is None:
        # Non-JBOD: one implicit disk per broker, disk id == broker id.
        disk_broker = np.arange(Bp, dtype=np.int32)
        disk_capacity = np.zeros(Bp, np.float32)
        disk_capacity[:B] = broker_capacity[:, Resource.DISK]
        disk_valid = np.zeros(Bp, bool)
        disk_valid[:B] = True
        if replica_disk is None:
            replica_disk = replica_broker.astype(np.int32)
    else:
        assert disk_capacity is not None and replica_disk is not None
        disk_valid = np.ones(disk_broker.shape[0], bool)
    D = int(disk_broker.shape[0])
    # Default landing disk per broker: lowest disk index owned by the broker;
    # plus the padded broker→disks table for intra-broker candidate generation.
    broker_first_disk = np.zeros(Bp, np.int32)
    disks_of: dict = {}
    for d in range(D - 1, -1, -1):
        b = int(disk_broker[d])
        if 0 <= b < Bp:
            broker_first_disk[b] = d
            disks_of.setdefault(b, []).insert(0, d)
    max_dpb = max((len(v) for v in disks_of.values()), default=1)
    broker_disks = np.full((Bp, max_dpb), -1, np.int32)
    for b, ds in disks_of.items():
        broker_disks[b, : len(ds)] = ds

    def pad(arr, n, fill=0):
        out = np.full((n,) + arr.shape[1:], fill, arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    replica_valid = np.zeros(Rp, bool)
    replica_valid[:R] = True
    broker_valid = np.zeros(Bp, bool)
    broker_valid[:B] = True

    # Build the partition→replica-ids table (static membership); native
    # kernel at scale, numpy fallback inside.
    from cruise_control_tpu import native
    rf_counts = np.bincount(replica_partition, minlength=P)
    max_rf = int(rf_counts.max()) if R else 1
    partition_replicas = native.build_partition_replicas(
        replica_partition.astype(np.int32), P, max_rf)

    model = TensorClusterModel(
        replica_broker=jnp.asarray(pad(replica_broker.astype(np.int32), Rp)),
        replica_partition=jnp.asarray(pad(replica_partition.astype(np.int32), Rp)),
        replica_topic=jnp.asarray(pad(replica_topic.astype(np.int32), Rp)),
        replica_is_leader=jnp.asarray(pad(replica_is_leader.astype(bool), Rp)),
        replica_load_leader=jnp.asarray(pad(replica_load_leader.astype(np.float32), Rp)),
        replica_load_follower=jnp.asarray(pad(replica_load_follower.astype(np.float32), Rp)),
        replica_valid=jnp.asarray(replica_valid),
        replica_original_broker=jnp.asarray(pad(replica_broker.astype(np.int32), Rp)),
        replica_offline=jnp.asarray(np.zeros(Rp, bool)),
        replica_disk=jnp.asarray(pad(replica_disk.astype(np.int32), Rp)),
        broker_capacity=jnp.asarray(pad(broker_capacity.astype(np.float32), Bp)),
        broker_rack=jnp.asarray(pad(broker_rack.astype(np.int32), Bp)),
        broker_host=jnp.asarray(pad(broker_host.astype(np.int32), Bp)),
        broker_state=jnp.asarray(pad(broker_state.astype(np.int8), Bp)),
        broker_valid=jnp.asarray(broker_valid),
        disk_broker=jnp.asarray(disk_broker.astype(np.int32)),
        disk_capacity=jnp.asarray(disk_capacity.astype(np.float32)),
        disk_valid=jnp.asarray(disk_valid),
        broker_first_disk=jnp.asarray(broker_first_disk),
        broker_disks=jnp.asarray(broker_disks),
        partition_topic=jnp.asarray(partition_topic.astype(np.int32)),
        partition_valid=jnp.asarray(np.ones(P, bool)),
        partition_replicas=jnp.asarray(partition_replicas),
        num_brokers=Bp,
        num_racks=num_racks,
        num_hosts=num_hosts,
        num_topics=num_topics,
        num_partitions=P,
        num_disks=D,
        max_rf=max_rf,
    )
    return model
