"""CPU estimation models.

Parity with the reference's CPU estimation (model/ModelUtils.java:61,92 and
model/LinearRegressionModelParameters.java:28):

- static heuristic splitting broker CPU to replicas weighted by bytes rates,
  and deriving follower CPU from leader load;
- an optionally *trained* linear-regression model over
  (LEADER_BYTES_IN, LEADER_BYTES_OUT, FOLLOWER_BYTES_IN) → CPU, fit by OLS
  on bucketed samples (the TRAIN endpoint feeds this).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

# Reference defaults (ModelUtils static init / MonitorConfig):
# fraction of leader CPU a follower replica costs.
DEFAULT_CPU_WEIGHT_OF_FOLLOWER = 0.4


def follower_cpu_util_from_leader_load(leader_bytes_in: float, leader_bytes_out: float,
                                       leader_cpu_util: float,
                                       follower_ratio: float = DEFAULT_CPU_WEIGHT_OF_FOLLOWER
                                       ) -> float:
    """Static heuristic (ModelUtils.getFollowerCpuUtilFromLeaderLoad,
    ModelUtils.java:61): a follower costs the leader's CPU scaled by the
    bytes-in share (followers only replicate inbound traffic) times a
    configured follower weight."""
    total = leader_bytes_in + leader_bytes_out
    if total <= 0:
        return 0.0
    return leader_cpu_util * follower_ratio * (leader_bytes_in / total)


def estimate_leader_cpu_util(broker_cpu_util: float, broker_leader_bytes_in: float,
                             broker_leader_bytes_out: float, broker_follower_bytes_in: float,
                             leader_bytes_in: float, leader_bytes_out: float) -> float:
    """Split broker CPU to one leader partition by its bytes-rate share
    (SamplingUtils.estimateLeaderCpuUtil, sampling/SamplingUtils.java:84-111)."""
    denom = broker_leader_bytes_in + broker_leader_bytes_out + broker_follower_bytes_in
    if denom <= 0:
        return 0.0
    share = (leader_bytes_in + leader_bytes_out) / denom
    return broker_cpu_util * share


@dataclasses.dataclass
class LinearRegressionModelParameters:
    """OLS CPU model over bucketed samples
    (model/LinearRegressionModelParameters.java:28).  Coefficients for
    LEADER_BYTES_IN, LEADER_BYTES_OUT, FOLLOWER_BYTES_IN."""

    coef_leader_bytes_in: float = 0.0
    coef_leader_bytes_out: float = 0.0
    coef_follower_bytes_in: float = 0.0
    trained: bool = False
    num_samples: int = 0


class CpuModelTrainer:
    """Accumulates (bytes rates → broker CPU) training rows and fits OLS.

    The reference buckets samples by total bytes rate to de-bias the fit
    toward the dense low-traffic region; we keep per-bucket reservoirs the
    same way (LinearRegressionModelParameters.addMetricObservation).
    """

    NUM_BUCKETS = 20
    BUCKET_CAP = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: list[list[Tuple[float, float, float, float]]] = \
            [[] for _ in range(self.NUM_BUCKETS)]
        self._max_rate = 1.0
        self.params = LinearRegressionModelParameters()

    def add_observation(self, leader_bytes_in: float, leader_bytes_out: float,
                        follower_bytes_in: float, cpu_util: float) -> None:
        with self._lock:
            rate = leader_bytes_in + leader_bytes_out + follower_bytes_in
            self._max_rate = max(self._max_rate, rate)
            b = min(int(rate / self._max_rate * (self.NUM_BUCKETS - 1)),
                    self.NUM_BUCKETS - 1)
            bucket = self._buckets[b]
            if len(bucket) >= self.BUCKET_CAP:
                bucket.pop(0)
            bucket.append((leader_bytes_in, leader_bytes_out, follower_bytes_in, cpu_util))

    def train(self) -> LinearRegressionModelParameters:
        with self._lock:
            rows = [r for b in self._buckets for r in b]
            if len(rows) < 4:
                return self.params
            arr = np.asarray(rows, np.float64)
            x, y = arr[:, :3], arr[:, 3]
            coef, *_ = np.linalg.lstsq(x, y, rcond=None)
            self.params = LinearRegressionModelParameters(
                coef_leader_bytes_in=float(coef[0]),
                coef_leader_bytes_out=float(coef[1]),
                coef_follower_bytes_in=float(coef[2]),
                trained=True, num_samples=len(rows))
            return self.params

    def predict(self, leader_bytes_in: float, leader_bytes_out: float,
                follower_bytes_in: float) -> Optional[float]:
        p = self.params
        if not p.trained:
            return None
        return (p.coef_leader_bytes_in * leader_bytes_in
                + p.coef_leader_bytes_out * leader_bytes_out
                + p.coef_follower_bytes_in * follower_bytes_in)
