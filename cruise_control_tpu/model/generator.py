"""Random/deterministic synthetic cluster generators for tests and benchmarks.

Parity with the reference's test fixtures: ``RandomCluster``
(cruise-control/src/test/java/.../model/RandomCluster.java — random clusters
with uniform/linear/exponential replica distributions) and
``DeterministicCluster`` (test/java/.../common/DeterministicCluster.java —
small hand-crafted models).  These drive the OptimizationVerifier-style
property tests and the benchmark ladder in BASELINE.md.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.model.tensor_model import TensorClusterModel, build_model


@dataclasses.dataclass
class ClusterSpec:
    """Knobs mirroring the reference's TestConstants/ClusterProperty maps."""

    num_brokers: int = 3
    num_racks: int = 3
    num_topics: int = 5
    mean_partitions_per_topic: float = 20.0
    replication_factor: int = 2
    distribution: str = "uniform"  # uniform | linear | exponential
    # Mean per-partition leader loads (CPU %, NW_IN KB/s, NW_OUT KB/s, DISK MB)
    mean_cpu: float = 0.1
    mean_nw_in: float = 100.0
    mean_nw_out: float = 100.0
    mean_disk: float = 100.0
    # Broker capacities
    cpu_capacity: float = 100.0
    nw_in_capacity: float = 200000.0
    nw_out_capacity: float = 200000.0
    disk_capacity: float = 1000000.0
    disks_per_broker: int = 1  # > 1 builds a JBOD topology
    seed: int = 0


def generate_cluster(spec: ClusterSpec, pad_replicas_to: Optional[int] = None,
                     pad_replicas_to_multiple: Optional[int] = None) -> TensorClusterModel:
    """Generate a random cluster whose replicas are placed randomly (possibly
    skewed), so distribution goals have work to do.

    ``pad_replicas_to_multiple`` rounds the replica axis up to a multiple
    (e.g. the mesh size for replica-axis sharding) without the caller having
    to build the model twice to learn R."""
    rng = np.random.default_rng(spec.seed)
    B = spec.num_brokers
    rf = spec.replication_factor
    assert spec.num_racks <= B

    # Topics and partition counts.
    parts_per_topic = np.maximum(
        1, rng.poisson(spec.mean_partitions_per_topic, size=spec.num_topics))
    P = int(parts_per_topic.sum())
    partition_topic = np.repeat(np.arange(spec.num_topics, dtype=np.int32), parts_per_topic)

    # Placement skew: weight brokers per the chosen distribution so the
    # initial state is unbalanced (like RandomCluster's populate()).
    if spec.distribution == "uniform":
        weights = np.ones(B)
    elif spec.distribution == "linear":
        weights = np.arange(1, B + 1, dtype=np.float64)
    elif spec.distribution == "exponential":
        weights = np.exp(np.linspace(0.0, 3.0, B))
    else:
        raise ValueError(f"unknown distribution {spec.distribution!r}")
    weights = weights / weights.sum()

    R = P * rf
    if pad_replicas_to_multiple:
        k = int(pad_replicas_to_multiple)
        pad_replicas_to = max(pad_replicas_to or 0, ((R + k - 1) // k) * k)
    replica_partition = np.repeat(np.arange(P, dtype=np.int32), rf)
    replica_topic = partition_topic[replica_partition]
    replica_is_leader = (np.arange(R) % rf) == 0

    # Choose rf distinct brokers per partition, weighted.
    replica_broker = np.empty(R, np.int32)
    for p in range(P):
        chosen = rng.choice(B, size=rf, replace=False, p=weights)
        replica_broker[p * rf:(p + 1) * rf] = chosen

    # Per-partition loads; leader carries NW_OUT + leadership CPU, follower
    # carries replication NW_IN and a CPU fraction (reference:
    # ModelUtils.getFollowerCpuUtilFromLeaderLoad, model/ModelUtils.java:61).
    leader_load = np.empty((P, NUM_RESOURCES), np.float32)
    leader_load[:, Resource.CPU] = rng.exponential(spec.mean_cpu, P)
    leader_load[:, Resource.NW_IN] = rng.exponential(spec.mean_nw_in, P)
    leader_load[:, Resource.NW_OUT] = rng.exponential(spec.mean_nw_out, P)
    leader_load[:, Resource.DISK] = rng.exponential(spec.mean_disk, P)

    follower_load = leader_load.copy()
    follower_load[:, Resource.NW_OUT] = 0.0
    follower_load[:, Resource.CPU] *= 0.4  # follower CPU fraction heuristic

    replica_load_leader = leader_load[replica_partition]
    replica_load_follower = follower_load[replica_partition]

    broker_capacity = np.tile(
        np.array([spec.cpu_capacity, spec.nw_in_capacity, spec.nw_out_capacity,
                  spec.disk_capacity], np.float32), (B, 1))
    broker_rack = (np.arange(B) % spec.num_racks).astype(np.int32)

    disk_broker = disk_capacity = replica_disk = None
    if spec.disks_per_broker > 1:
        dpb = spec.disks_per_broker
        disk_broker = np.repeat(np.arange(B, dtype=np.int32), dpb)
        disk_capacity = np.full(B * dpb, spec.disk_capacity / dpb, np.float32)
        # Skewed initial disk placement so intra-broker goals have work.
        replica_disk = (replica_broker * dpb
                        + (rng.random(R) ** 2 * dpb).astype(np.int32)).astype(np.int32)

    return build_model(
        disk_broker=disk_broker,
        disk_capacity=disk_capacity,
        replica_disk=replica_disk,
        replica_broker=replica_broker,
        replica_partition=replica_partition,
        replica_topic=replica_topic,
        replica_is_leader=replica_is_leader,
        replica_load_leader=replica_load_leader,
        replica_load_follower=replica_load_follower,
        broker_capacity=broker_capacity,
        broker_rack=broker_rack,
        partition_topic=partition_topic,
        pad_replicas_to=pad_replicas_to,
    )


def small_deterministic_cluster() -> TensorClusterModel:
    """A tiny 3-broker / 2-topic hand-crafted model, analogous to the
    reference's DeterministicCluster fixtures: broker 0 heavily loaded,
    broker 2 nearly empty."""
    # topic 0: partitions 0..2 rf=2; topic 1: partitions 3..4 rf=2
    replica_partition = np.array([0, 0, 1, 1, 2, 2, 3, 3, 4, 4], np.int32)
    replica_topic = np.array([0, 0, 0, 0, 0, 0, 1, 1, 1, 1], np.int32)
    replica_is_leader = np.array([1, 0, 1, 0, 1, 0, 1, 0, 1, 0], bool)
    # Skew everything onto brokers 0/1.
    replica_broker = np.array([0, 1, 0, 1, 0, 1, 0, 1, 1, 0], np.int32)
    leader = np.array([
        [10.0, 100.0, 130.0, 75.0],
        [10.0, 100.0, 130.0, 75.0],
        [10.0, 100.0, 130.0, 75.0],
        [ 5.0,  50.0,  60.0, 40.0],
        [ 5.0,  50.0,  60.0, 40.0],
    ], np.float32)
    follower = leader.copy()
    follower[:, Resource.NW_OUT] = 0.0
    follower[:, Resource.CPU] *= 0.4
    broker_capacity = np.tile(np.array([100.0, 1000.0, 1000.0, 2000.0], np.float32), (3, 1))
    broker_rack = np.array([0, 1, 2], np.int32)
    return build_model(
        replica_broker=replica_broker,
        replica_partition=replica_partition,
        replica_topic=replica_topic,
        replica_is_leader=replica_is_leader,
        replica_load_leader=leader[replica_partition],
        replica_load_follower=follower[replica_partition],
        broker_capacity=broker_capacity,
        broker_rack=broker_rack,
    )
