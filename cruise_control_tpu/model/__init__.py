from cruise_control_tpu.model.tensor_model import (
    BrokerState,
    TensorClusterModel,
    build_model,
)
from cruise_control_tpu.model.stats import ClusterModelStats, compute_stats
from cruise_control_tpu.model.generator import ClusterSpec, generate_cluster, small_deterministic_cluster

__all__ = [
    "BrokerState",
    "TensorClusterModel",
    "build_model",
    "ClusterModelStats",
    "compute_stats",
    "ClusterSpec",
    "generate_cluster",
    "small_deterministic_cluster",
]
