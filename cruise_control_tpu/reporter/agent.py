"""The metrics-reporter agent: the broker-side half of the ingestion path.

Parity with ``CruiseControlMetricsReporter``
(cruise-control-metrics-reporter/src/main/java/.../CruiseControlMetricsReporter.java:60,88):
sample the broker's raw metrics every interval and produce serialized
``RawMetric`` records to the ``__CruiseControlMetrics`` topic, creating the
topic on startup if missing.  The reference plugs into the broker JVM as a
``MetricsReporter``; a JVM-free framework cannot live inside the broker
process, so this agent is a sidecar pulling from a pluggable
``BrokerMetricsSource`` — everything downstream (topic, serde, sampler,
processor, aggregator) is unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

from cruise_control_tpu.kafka.client import KafkaClient, KafkaError
from cruise_control_tpu.kafka.protocol import Record
from cruise_control_tpu.reporter.raw_metrics import RawMetric, RawMetricType
from cruise_control_tpu.reporter.serde import encode_metric

METRICS_TOPIC = "__CruiseControlMetrics"


class BrokerMetricsSource:
    """SPI: where a broker's raw numbers come from (the reference reads the
    broker's Yammer/Kafka metrics registry in-process; a sidecar reads a JMX
    bridge, node stats, or — in tests — a synthetic workload)."""

    def collect(self, broker_id: int, time_ms: int) -> List[RawMetric]:
        raise NotImplementedError


class SyntheticBrokerMetricsSource(BrokerMetricsSource):
    """Deterministic per-broker workload for tests: stable per-(broker,
    topic, partition) rates seeded by hash — the sidecar-world analogue of
    the embedded-broker fixture workloads."""

    def __init__(self, topic_partitions, leaders, cpu_util: float = 0.4,
                 bytes_in_per_partition: float = 64 * 1024.0,
                 partition_size_bytes: float = 512 * 1024 * 1024.0):
        # topic_partitions: {topic: num_partitions}; leaders: {(t, p): broker}
        self._topics = dict(topic_partitions)
        self._leaders = dict(leaders)
        self._cpu = cpu_util
        self._bin = bytes_in_per_partition
        self._size = partition_size_bytes

    def _scale(self, topic: str, partition: int) -> float:
        h = hash(("smet", topic, partition)) & 0xFFFF
        return 0.5 + (h / 0xFFFF)

    def collect(self, broker_id: int, time_ms: int) -> List[RawMetric]:
        out: List[RawMetric] = []
        total_in = total_out = 0.0
        for topic, nparts in sorted(self._topics.items()):
            t_in = t_out = 0.0
            led_any = False
            for p in range(nparts):
                if self._leaders.get((topic, p)) != broker_id:
                    continue
                led_any = True
                s = self._scale(topic, p)
                t_in += self._bin * s
                t_out += 1.4 * self._bin * s
                out.append(RawMetric(RawMetricType.PARTITION_SIZE, time_ms,
                                     broker_id, self._size * s, topic=topic,
                                     partition=p))
            if led_any:
                out.append(RawMetric(RawMetricType.TOPIC_BYTES_IN, time_ms,
                                     broker_id, t_in, topic=topic))
                out.append(RawMetric(RawMetricType.TOPIC_BYTES_OUT, time_ms,
                                     broker_id, t_out, topic=topic))
                out.append(RawMetric(RawMetricType.TOPIC_REPLICATION_BYTES_IN,
                                     time_ms, broker_id, t_in, topic=topic))
                out.append(RawMetric(RawMetricType.TOPIC_REPLICATION_BYTES_OUT,
                                     time_ms, broker_id, t_in, topic=topic))
                out.append(RawMetric(RawMetricType.TOPIC_PRODUCE_REQUEST_RATE,
                                     time_ms, broker_id, 10.0, topic=topic))
                out.append(RawMetric(RawMetricType.TOPIC_FETCH_REQUEST_RATE,
                                     time_ms, broker_id, 14.0, topic=topic))
                out.append(RawMetric(RawMetricType.TOPIC_MESSAGES_IN_PER_SEC,
                                     time_ms, broker_id, 100.0, topic=topic))
                total_in += t_in
                total_out += t_out
        out.append(RawMetric(RawMetricType.ALL_TOPIC_BYTES_IN, time_ms,
                             broker_id, total_in))
        out.append(RawMetric(RawMetricType.ALL_TOPIC_BYTES_OUT, time_ms,
                             broker_id, total_out))
        out.append(RawMetric(RawMetricType.BROKER_CPU_UTIL, time_ms,
                             broker_id, self._cpu))
        out.append(RawMetric(RawMetricType.BROKER_REQUEST_QUEUE_SIZE, time_ms,
                             broker_id, 1.0))
        out.append(RawMetric(
            RawMetricType.BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT, time_ms,
            broker_id, 0.9))
        out.append(RawMetric(RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH,
                             time_ms, broker_id, 5.0))
        return out


class MetricsReporterAgent:
    """One broker's reporter: collect → encode → produce each interval.

    ``report_once`` is the unit the scheduler (or a test) drives; ``run``
    wraps it in the reference's background-thread loop
    (CruiseControlMetricsReporter.java:88).
    """

    def __init__(self, client: KafkaClient, source: BrokerMetricsSource,
                 broker_id: int, topic: str = METRICS_TOPIC,
                 topic_partitions: int = 1, interval_ms: int = 10_000):
        self._client = client
        self._source = source
        self._broker_id = broker_id
        self._topic = topic
        self._topic_partitions = topic_partitions
        self._interval_s = interval_ms / 1000.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ensured = False

    def ensure_topic(self) -> None:
        """Create the metrics topic if missing (reporter startup,
        CruiseControlMetricsReporter.java maybeCreateTopic)."""
        if self._ensured:
            return
        errors = self._client.create_topics(
            {self._topic: (self._topic_partitions, 1)},
            configs={self._topic: {"retention.ms": "3600000",
                                   "compression.type": "none"}})
        code = errors.get(self._topic, 0)
        if code not in (0, 36):  # 36 = TOPIC_ALREADY_EXISTS
            raise KafkaError(code, f"creating {self._topic}")
        self._ensured = True

    def report_once(self, time_ms: Optional[int] = None) -> int:
        """Collect and produce one round of metrics; returns #records."""
        self.ensure_topic()
        ts = time_ms if time_ms is not None else int(time.time() * 1000)
        metrics = self._source.collect(self._broker_id, ts)
        if not metrics:
            return 0
        # All of one broker's records go to one partition (broker_id spread
        # over the topic's partitions — same keying as the reference).
        partition = self._broker_id % self._topic_partitions
        records = [Record(key=str(self._broker_id).encode(),
                          value=encode_metric(m), timestamp_ms=m.time_ms)
                   for m in metrics]
        self._client.produce((self._topic, partition), records)
        return len(records)

    def run(self) -> None:
        while not self._stop.is_set():
            try:
                self.report_once()
            except (KafkaError, ConnectionError, OSError):
                pass  # transient broker trouble: retry next interval
            self._stop.wait(self._interval_s)

    def start(self) -> "MetricsReporterAgent":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"metrics-reporter-{self._broker_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
