"""Versioned binary serde for ``RawMetric`` records.

Parity with the reference's ``MetricSerde``
(cruise-control-metrics-reporter/src/main/java/.../metric/MetricSerde.java):
each record on the ``__CruiseControlMetrics`` topic is a self-describing,
versioned binary blob, so old readers reject newer formats explicitly
instead of mis-parsing them.  The layout here is this framework's own
(the reference's is JVM ByteBuffer-specific):

    u8   version        (currently 0)
    u8   metric_type    (RawMetricType wire id)
    i64  time_ms        (big-endian)
    i32  broker_id
    f64  value
    i32  partition      (-1 for broker/topic scope)
    u16  topic_len + utf-8 topic bytes (len 0 for broker scope)

Everything is big-endian (network order, matching the Kafka wire protocol
the records ride on).
"""

from __future__ import annotations

import struct
from typing import Optional

from cruise_control_tpu.reporter.raw_metrics import (MetricScope, RawMetric,
                                                     RawMetricType)

SERDE_VERSION = 0

_HEADER = struct.Struct(">BBqid i H".replace(" ", ""))  # see encode_metric


class MetricSerdeError(ValueError):
    """Record bytes do not decode as a supported RawMetric format."""


def encode_metric(metric: RawMetric) -> bytes:
    """RawMetric → wire bytes (record value for __CruiseControlMetrics)."""
    topic_bytes = metric.topic.encode("utf-8") if metric.topic else b""
    if len(topic_bytes) > 0xFFFF:
        raise MetricSerdeError(f"topic too long: {len(topic_bytes)} bytes")
    return _HEADER.pack(SERDE_VERSION, int(metric.metric_type), metric.time_ms,
                        metric.broker_id, metric.value, metric.partition,
                        len(topic_bytes)) + topic_bytes


def decode_metric(data: bytes) -> RawMetric:
    """Wire bytes → RawMetric; raises MetricSerdeError on malformed or
    unsupported input (the reference throws on unknown versions likewise)."""
    if len(data) < _HEADER.size:
        raise MetricSerdeError(f"record too short: {len(data)} bytes")
    version, type_id, time_ms, broker_id, value, partition, topic_len = \
        _HEADER.unpack_from(data)
    if version != SERDE_VERSION:
        raise MetricSerdeError(f"unsupported serde version {version}")
    try:
        metric_type = RawMetricType(type_id)
    except ValueError as e:
        raise MetricSerdeError(f"unknown metric type id {type_id}") from e
    if len(data) != _HEADER.size + topic_len:
        raise MetricSerdeError(
            f"length mismatch: {len(data)} != {_HEADER.size + topic_len}")
    topic: Optional[str] = None
    if topic_len:
        topic = data[_HEADER.size:_HEADER.size + topic_len].decode("utf-8")
    if metric_type.scope == MetricScope.BROKER:
        topic = None
        partition = -1
    try:
        return RawMetric(metric_type=metric_type, time_ms=time_ms,
                         broker_id=broker_id, value=value, topic=topic,
                         partition=partition)
    except ValueError as e:
        # e.g. a topic-scoped type framed without a topic — keep the
        # documented contract that every bad record raises MetricSerdeError.
        raise MetricSerdeError(str(e)) from e
