"""Metrics-reporter module: the broker-side ingestion source.

Equivalent of ``cruise-control-metrics-reporter`` (SURVEY.md §2.8): an agent
that runs beside each Kafka broker, samples its raw metrics every interval,
and produces versioned binary ``RawMetric`` records to the
``__CruiseControlMetrics`` topic (CruiseControlMetricsReporter.java:60,88).
The reference plugs into the broker JVM as a ``MetricsReporter``; a TPU-side
Python framework cannot live inside the broker process, so the agent is a
sidecar pulling from a pluggable ``BrokerMetricsSource`` (JMX-bridge, local
stats, or synthetic for tests) with identical topic/serde semantics —
everything downstream (sampler → processor → aggregator) is unchanged
either way.
"""

from cruise_control_tpu.reporter.raw_metrics import (MetricScope, RawMetric,
                                                     RawMetricType)
from cruise_control_tpu.reporter.serde import decode_metric, encode_metric

__all__ = ["MetricScope", "RawMetric", "RawMetricType", "decode_metric",
           "encode_metric"]
