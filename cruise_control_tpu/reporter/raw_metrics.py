"""Raw broker/topic/partition metric types.

Parity with ``RawMetricType`` (cruise-control-metrics-reporter/.../metric/
RawMetricType.java:26): the ~50 raw metric ids the reporter emits, each
scoped BROKER / TOPIC / PARTITION.  Ids here are this framework's own wire
ids (serde is versioned independently of the reference's format).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional


class MetricScope(enum.IntEnum):
    BROKER = 0
    TOPIC = 1
    PARTITION = 2


class RawMetricType(enum.IntEnum):
    # --- broker scope: totals over all topics ---
    ALL_TOPIC_BYTES_IN = 0
    ALL_TOPIC_BYTES_OUT = 1
    ALL_TOPIC_REPLICATION_BYTES_IN = 2
    ALL_TOPIC_REPLICATION_BYTES_OUT = 3
    ALL_TOPIC_FETCH_REQUEST_RATE = 4
    ALL_TOPIC_PRODUCE_REQUEST_RATE = 5
    ALL_TOPIC_MESSAGES_IN_PER_SEC = 6
    # --- broker scope: broker health ---
    BROKER_CPU_UTIL = 7
    BROKER_PRODUCE_REQUEST_RATE = 8
    BROKER_CONSUMER_FETCH_REQUEST_RATE = 9
    BROKER_FOLLOWER_FETCH_REQUEST_RATE = 10
    BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT = 11
    BROKER_REQUEST_QUEUE_SIZE = 12
    BROKER_RESPONSE_QUEUE_SIZE = 13
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX = 14
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MEAN = 15
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH = 16
    BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_999TH = 17
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 18
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 19
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 20
    BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 21
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MAX = 22
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_MEAN = 23
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_50TH = 24
    BROKER_FOLLOWER_FETCH_REQUEST_QUEUE_TIME_MS_999TH = 25
    BROKER_PRODUCE_TOTAL_TIME_MS_MAX = 26
    BROKER_PRODUCE_TOTAL_TIME_MS_MEAN = 27
    BROKER_PRODUCE_TOTAL_TIME_MS_50TH = 28
    BROKER_PRODUCE_TOTAL_TIME_MS_999TH = 29
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MAX = 30
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_MEAN = 31
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_50TH = 32
    BROKER_CONSUMER_FETCH_TOTAL_TIME_MS_999TH = 33
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MAX = 34
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_MEAN = 35
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_50TH = 36
    BROKER_FOLLOWER_FETCH_TOTAL_TIME_MS_999TH = 37
    BROKER_PRODUCE_LOCAL_TIME_MS_MAX = 38
    BROKER_PRODUCE_LOCAL_TIME_MS_MEAN = 39
    BROKER_PRODUCE_LOCAL_TIME_MS_50TH = 40
    BROKER_PRODUCE_LOCAL_TIME_MS_999TH = 41
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MAX = 42
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_MEAN = 43
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_50TH = 44
    BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH = 45
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MAX = 46
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_MEAN = 47
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_50TH = 48
    BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH = 49
    BROKER_LOG_FLUSH_RATE = 50
    BROKER_LOG_FLUSH_TIME_MS_MAX = 51
    BROKER_LOG_FLUSH_TIME_MS_MEAN = 52
    BROKER_LOG_FLUSH_TIME_MS_50TH = 53
    BROKER_LOG_FLUSH_TIME_MS_999TH = 54
    # --- topic scope ---
    TOPIC_BYTES_IN = 55
    TOPIC_BYTES_OUT = 56
    TOPIC_REPLICATION_BYTES_IN = 57
    TOPIC_REPLICATION_BYTES_OUT = 58
    TOPIC_FETCH_REQUEST_RATE = 59
    TOPIC_PRODUCE_REQUEST_RATE = 60
    TOPIC_MESSAGES_IN_PER_SEC = 61
    # --- partition scope ---
    PARTITION_SIZE = 62

    @property
    def scope(self) -> MetricScope:
        if self >= RawMetricType.PARTITION_SIZE:
            return MetricScope.PARTITION
        if self >= RawMetricType.TOPIC_BYTES_IN:
            return MetricScope.TOPIC
        return MetricScope.BROKER


@dataclasses.dataclass(frozen=True)
class RawMetric:
    """One raw metric record (CruiseControlMetric/BrokerMetric/TopicMetric/
    PartitionMetric analogue)."""

    metric_type: RawMetricType
    time_ms: int
    broker_id: int
    value: float
    topic: Optional[str] = None
    partition: int = -1

    def __post_init__(self):
        scope = self.metric_type.scope
        if scope != MetricScope.BROKER and self.topic is None:
            raise ValueError(f"{self.metric_type.name} requires a topic")
        if scope == MetricScope.PARTITION and self.partition < 0:
            raise ValueError(f"{self.metric_type.name} requires a partition")


def broker_metric_counts() -> Dict[MetricScope, int]:
    out: Dict[MetricScope, int] = {s: 0 for s in MetricScope}
    for t in RawMetricType:
        out[t.scope] += 1
    return out
