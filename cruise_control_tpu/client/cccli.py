"""cccli — command-line client for the REST API.

Parity with the reference's Python client
(cruise-control-client/cruisecontrolclient/client/cccli.py: argparse-driven
CLI, one subcommand per endpoint, long-poll progress display via
User-Task-ID; Endpoint/Parameter model in client/Endpoint.py,
Responder/Query session handling).  Pure stdlib (urllib).
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Optional, Tuple


class CruiseControlClient:
    """HTTP session + endpoint model (client/Responder.py analogue)."""

    def __init__(self, base_url: str, auth: Optional[Tuple[str, str]] = None,
                 timeout_s: float = 60.0):
        self.base = base_url.rstrip("/")
        # The reference cccli accepts a bare host:port (-a localhost:9090).
        if "://" not in self.base:
            self.base = "http://" + self.base
        if not self.base.endswith("/kafkacruisecontrol"):
            self.base += "/kafkacruisecontrol"
        self._auth = auth
        self._timeout = timeout_s

    def _request(self, method: str, endpoint: str,
                 params: Dict[str, object]) -> Tuple[int, Dict, Dict[str, str]]:
        qs = urllib.parse.urlencode({k: str(v) for k, v in params.items()
                                     if v is not None})
        url = f"{self.base}/{endpoint}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(url, method=method)
        if self._auth:
            token = base64.b64encode(f"{self._auth[0]}:{self._auth[1]}".encode())
            req.add_header("Authorization", f"Basic {token.decode()}")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                return resp.status, json.loads(resp.read() or b"{}"), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}"), dict(e.headers)

    def call(self, method: str, endpoint: str, params: Dict[str, object],
             poll: bool = True, poll_interval_s: float = 1.0,
             progress=None) -> Tuple[int, Dict]:
        """Issue the request; re-poll while it reports 202 (the client's
        long-poll progress loop over User-Task-ID)."""
        while True:
            status, body, headers = self._request(method, endpoint, params)
            if status != 202 or not poll:
                return status, body
            if progress:
                progress(body)
            if "reviewId" in body:  # parked in purgatory: nothing to poll
                return status, body
            time.sleep(poll_interval_s)


def _print_progress(body: Dict) -> None:
    steps = body.get("progress", [])
    if steps:
        last = steps[-1]
        print(f"  … {last['step']} ({last['durationMs']} ms)", file=sys.stderr)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cccli", description="cruise-control-tpu command line client")
    p.add_argument("-a", "--address", default="http://127.0.0.1:9090",
                   help="server address (default %(default)s)")
    p.add_argument("--user", help="basic-auth user")
    p.add_argument("--password", help="basic-auth password")
    p.add_argument("--no-poll", action="store_true",
                   help="do not long-poll async operations")
    sub = p.add_subparsers(dest="command", required=True)

    def add(name, method, help_, params=()):
        sp = sub.add_parser(name, help=help_)
        sp.set_defaults(_method=method, _endpoint=name)
        for flag, kw in params:
            sp.add_argument(flag, **kw)
        return sp

    add("state", "GET", "component states",
        [("--substates", dict(help="comma list: monitor,executor,analyzer,anomaly_detector"))])
    add("load", "GET", "per-broker load")
    add("partition_load", "GET", "per-partition load",
        [("--entries", dict(type=int, default=100))])
    add("proposals", "GET", "optimization proposals",
        [("--goals", dict(help="comma list of goal names")),
         ("--ignore_proposal_cache", dict(action="store_true"))])
    add("kafka_cluster_state", "GET", "partition/replica state")
    add("metrics", "GET", "sensor registry",
        [("--format", dict(choices=["json", "prometheus"]))])
    add("user_tasks", "GET", "async task list")
    add("review_board", "GET", "two-step review board")
    add("bootstrap", "GET", "replay historical samples",
        [("--start", dict(type=int, required=True)),
         ("--end", dict(type=int, required=True))])
    add("train", "GET", "train the CPU estimation model")

    mut = [("--dryrun", dict(default="true", choices=["true", "false"])),
           ("--review_id", dict(type=int))]
    add("rebalance", "POST", "rebalance the cluster",
        mut + [("--goals", dict()), ("--destination_broker_ids", dict()),
               ("--fast_mode", dict(action="store_true")),
               ("--rebalance_disk", dict(action="store_true"))])
    add("add_broker", "POST", "move load onto new brokers",
        mut + [("--brokerid", dict(required=True))])
    add("remove_broker", "POST", "decommission brokers",
        mut + [("--brokerid", dict(required=True))])
    add("demote_broker", "POST", "move leadership off brokers",
        mut + [("--brokerid", dict(required=True))])
    add("fix_offline_replicas", "POST", "heal offline replicas", mut)
    add("topic_configuration", "POST", "change topic replication factor",
        mut + [("--topic", dict(required=True)),
               ("--replication_factor", dict(type=int, required=True))])
    add("stop_proposal_execution", "POST", "stop the ongoing execution",
        [("--force_stop", dict(action="store_true"))])
    add("pause_sampling", "POST", "pause metric sampling",
        [("--reason", dict(default=""))])
    add("resume_sampling", "POST", "resume metric sampling")
    add("admin", "POST", "admin actions",
        [("--enable_self_healing_for", dict()),
         ("--disable_self_healing_for", dict()),
         ("--concurrent_partition_movements_per_broker", dict(type=int)),
         ("--drop_recently_removed_brokers", dict())])
    add("review", "POST", "approve/discard parked requests",
        [("--approve", dict()), ("--discard", dict()),
         ("--reason", dict(default=""))])
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    auth = (args.user, args.password) if args.user else None
    client = CruiseControlClient(args.address, auth=auth)
    params = {k: v for k, v in vars(args).items()
              if not k.startswith("_") and k not in
              ("address", "user", "password", "command", "no_poll")
              and v is not None and v is not False}  # keep integer 0 values
    params = {k: ("true" if v is True else v) for k, v in params.items()}
    status, body = client.call(args._method, args._endpoint, params,
                               poll=not args.no_poll, progress=_print_progress)
    print(json.dumps(body, indent=2, default=str))
    return 0 if status < 400 else 1


if __name__ == "__main__":
    sys.exit(main())
